"""Continuous batching for ``generate()`` serving.

The reference's protocol is unary request/response; its only batching is
the client's (SURVEY §7 hard part (b): "dynamic micro-batching +
continuous batching for generate() under a protocol designed for unary
calls"). This scheduler closes that gap the TPU way:

* A fixed pool of ``slots`` decode lanes and a fixed cache length — every
  device computation has STATIC shapes, so XLA compiles exactly three
  executables (prefill per bucket, slot-insert, fused decode+sample) and
  the MXU never waits on a recompile.
* New requests are admitted into free slots **while older requests are
  mid-decode**: prefill runs as its own batched forward (bucketed prompt
  lengths), its K/V is spliced into the shared cache with a
  ``dynamic_update_slice``, and the next fused step decodes old + new
  lanes together (``DecoderLM.decode_step_ragged`` — per-row positions).
* Sampling is fused into the decode executable (greedy/temperature per
  lane), and bursts of up to ``steps_per_poll`` decode steps run as ONE
  device call (``lax.scan`` over the fused step), so the host syncs once
  per burst — not once per token. Dispatch/sync latency is the decode
  bottleneck off-device; this amortises it k-fold.
* Bursts are **software-pipelined** (``pipeline_depth``): the scheduler
  dispatches burst N+1 (and starts its device→host token copy with
  ``copy_to_host_async``) before reading burst N's tokens, so the device
  never idles waiting on the host sync round-trip. Decode state lives on
  device across bursts, so correctness only needs the host to *observe*
  tokens late: each dispatch snapshots which request occupied each lane,
  and tokens from a burst are credited strictly to that snapshot (a lane
  that finished mid-pipeline just decodes a few ignored tokens before the
  host notices and re-admits).
* The KV cache is held as per-layer arrays and updated IN PLACE: only the
  one-position scatter touches HBM per step (a stacked cache threaded
  through the layer scan made XLA rewrite every byte of it every step).
  The attention READ is bounded by a static bucket covering the deepest
  lane's position (host-tracked, no sync) — decode cost follows the live
  prefix, not the allocated cache.
* **Depth-aware sub-bursts** (``depth_groups``): at mixed prefix depths a
  single burst bounds EVERY lane's read by the deepest lane's bucket, so
  shallow lanes stream (and mask away) slab they never attend to. With
  grouping on, live lanes are partitioned by attention bucket and the
  poll dispatches one gathered sub-burst per group — each group's cache
  read narrows to its OWN bucket. A sub-burst gathers its lanes' cache
  prefixes into a ``[Gb, KV, bucket, Dh]`` slab (Gb = pow2 group-size
  bucket, so one executable exists per (Gb, bucket) pair), runs the same
  fused step scan, and scatters state back; a cost model (extra
  sub-burst ~= one more param read per step vs. the modeled KV-read
  saving) merges groups that aren't worth splitting. Groups are
  re-planned every poll, so lanes re-pack automatically as their
  prefixes deepen across bucket boundaries.
* **Chunked prefill interleave** (``prefill_chunk``): a long-prompt
  admission no longer stalls every decode lane for a full prompt-length
  forward. The prompt is split into ``prefill_chunk``-token slices
  executed BETWEEN decode polls (``DecoderLM.prefill_chunk`` extends a
  staging slab without re-reading the prefix — the slab lives OUTSIDE
  the decode cache, so in-flight bursts never see a half-built prompt
  and the decode executables stay bit-identical to the whole-prompt
  path); only the final slice samples the first token, and the finished
  slab goes through the ordinary lane insert. Decode keeps its burst
  cadence while long prompts trickle in.
* With a mesh, params/cache shard over the ``model`` axis (KV heads) and
  optionally the ``seq`` axis (cache length) — long prompts span ICI.

No reference counterpart (category: new TPU-native capability; BASELINE
config 5 "Llama-2-7B generate() with engine-side dynamic batching").
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.roles import caller_thread, scheduler_only
from ..tracing import wall_us

logger = logging.getLogger(__name__)


class PromptTooLong(ValueError):
    """The request cannot fit the serving cache: the prompt exceeds
    every prefill bucket and ``max_seq``. Carries a wire status so the
    engine answers a typed **413** (REST) / ``INVALID_ARGUMENT`` (gRPC)
    instead of a 500 traceback — the client sent an unservable request;
    retrying it unchanged can never succeed."""

    status = 413


class BudgetExceeded(PromptTooLong):
    """``prompt_len + max_new_tokens > max_seq``: the generation would
    outgrow the decode cache. Rejected **at submit/admit time** with the
    same 413-class status as :class:`PromptTooLong` — before this check
    the overrun was silently clamped (a client asking for 512 tokens got
    40 with no signal) and anything slipping past surfaced deep in the
    scheduler as a shape error. Size ``max_seq`` to prompt + budget, or
    lower ``max_new_tokens``."""


class RetuneError(ValueError):
    """A :meth:`ContinuousBatcher.retune` request named a knob value
    outside the boot-time compile census (or an unknown/ill-typed knob).
    Typed and raised synchronously on the caller thread BEFORE anything
    is staged: a config the warm() pass did not precompile would stall
    the scheduler tens of seconds mid-traffic, so the planner's
    out-of-census proposals are refused here, never half-applied."""


class BatcherDead(RuntimeError):
    """The continuous batcher's scheduler loop is not serving: it died
    (in-flight work at crash time), its crash-loop budget is exhausted
    (latched dead until the reconciler replaces the member), or it was
    closed. Carries the 503 wire status plus ``retry_after_s`` so the
    engine maps it to ``503 + Retry-After`` exactly like PR 2's shed
    path maps :class:`~..resilience.ShedError` to 429 — clients back
    off and retry (another replica, or this one once its supervised
    restart lands)."""

    status = 503

    def __init__(self, info: str, retry_after_s: float = 1.0):
        super().__init__(info)
        self.info = info
        self.retry_after_s = float(retry_after_s)


@dataclasses.dataclass
class GenRequest:
    tokens: List[int]
    max_new_tokens: int = 32
    temperature: float = 0.0
    eos_id: Optional[int] = None
    seed: int = 0
    future: Future = dataclasses.field(default_factory=Future)
    # streaming: called from the scheduler thread with each newly credited
    # span of tokens (must be cheap + non-blocking; exceptions are logged,
    # never propagated into the decode loop)
    on_tokens: Optional[object] = None
    # prompt tokens served from the radix prefix cache at admit (0 = full
    # prefill); surfaced per-request so responses/graph nodes can report
    # cache effectiveness
    cache_hit_tokens: int = 0
    # -- lifecycle timeline (monotonic seconds; 0.0 = not reached) --------
    # stamped by the scheduler as the request crosses each phase boundary;
    # feed both the SLO histograms (queue wait / TTFT / TPOT) and — when a
    # sampled trace context rode in on ``trace`` — the retroactive
    # per-request timeline spans. Plain float stores: no allocation, no
    # lock, written by one thread at a time per field.
    submit_t: float = 0.0
    admit_t: float = 0.0
    # lane activation: the moment the prompt K/V landed in the decode
    # cache (post-insert) — for chunked admissions this is many polls
    # after admit_t, so decode residency must anchor here, not at admit
    decode_start_t: float = 0.0
    first_tok_t: float = 0.0
    # wall-clock anchor of submit_t (epoch microseconds) so retroactive
    # spans can place monotonic intervals on the Jaeger timeline
    submit_wall_us: int = 0
    # (trace_id, parent_span_id) captured from the submitting thread's
    # active span; None when tracing is off or the request is unsampled
    trace: Optional[Tuple[str, str]] = None
    # disaggregated serving: a remote admit carries its prefill-side slab
    # here ({"slab" dev arrays, "first", "key", "covered", "nbytes",
    # "version"}) and skips local prefill entirely — the wave-routing
    # loop routes it to _admit_remote_lane (see admit_remote)
    remote: Optional[Dict[str, Any]] = None
    # decode-lane preemption checkpoint ({"emitted": [...], "key":
    # [hi, lo]}): set when a pressure reclaim evicted this request from
    # its lane mid-decode. The K/V is NOT checkpointed — resume
    # recomputes it with a prefill over prompt+generated-so-far and
    # continues the exact sampling stream from the checkpointed
    # post-split RNG lane key (see _admit_resume). None = never
    # preempted, or preempted before any token was credited (a plain
    # re-admit reproduces the identical stream from the seed alone).
    resume: Optional[Dict[str, Any]] = None
    # absolute deadline (monotonic seconds) when the submit carried a
    # budget — the preemption victim policy reads it (a lane that must
    # answer soon is preempted only after every deadline-free lane)
    deadline_t: Optional[float] = None
    # multi-tenant serving (serving/weightpager.py): owning tenant id
    # (None = single-tenant back-compat) and SLO class ("strict" |
    # "standard" | "best_effort"). The victim policy protects a strict
    # tenant's last live lane; _resolve splits the SLO samples per
    # tenant so the scheduler's starvation score sees per-tenant TTFT
    tenant: Optional[str] = None
    slo: str = "standard"


@dataclasses.dataclass
class _ChunkJob:
    """A long-prompt admission mid-chunked-prefill: the slot is reserved
    but not yet decoding; one chunk advances per scheduler poll. The
    prompt K/V accumulate in a STAGING slab (cache_one layout) outside
    the decode cache, spliced into the lane only when complete."""

    request: GenRequest
    slot: int
    next_start: int  # absolute position of the next chunk's first token
    slab: Any  # {"k","v"} stacked [L, 1, KV, bucket, Dh]
    bucket: int
    # prompt tokens already covered by a spliced prefix-cache slab
    # (chunking then starts at the splice point)
    hit_tokens: int = 0
    # preemption recompute-resume: (emitted tokens, checkpointed lane
    # key) — the final chunk then inserts the checkpointed continuation
    # state instead of its own sample and replays the emitted K/V
    resume: Optional[Tuple[List[int], Any]] = None


@dataclasses.dataclass
class _SwapJob:
    """A requested live weight swap: the new params are already cast,
    device-resident and (when meshed) sharded — double-buffered next to
    the serving set. The scheduler flips the pointer at a poll boundary
    once every in-flight lane (decode, chunked prefill, pipelined burst)
    has finished on the OLD version; until then admissions hold so the
    drain converges."""

    params: Any
    version: Any
    future: Future = dataclasses.field(default_factory=Future)
    # lanes in flight when the scheduler first observed the request
    # (flight-recorder attribution), and polls spent draining them
    drain_lanes: Optional[int] = None
    waited_polls: int = 0
    # double-buffered param bytes — the pressure ledger's "swap"
    # component while the drain holds both versions resident
    nbytes: int = 0
    # when the swap was staged (monotonic): the straggler bound
    # (swap_drain_ms) is measured from here, so one long generation
    # cannot stall the flip indefinitely
    staged_t: float = 0.0


@dataclasses.dataclass
class _DrainJob:
    """A requested graceful drain: the scheduler checkpoints every live
    lane at the next poll boundary (reusing the preemption machinery),
    collects chunked admissions, the resume queue, and queued-not-
    admitted requests, and resolves the future with the full list of
    :class:`GenRequest` — each carrying its host-side checkpoint in
    ``resume`` — for the caller to hand to a peer."""

    future: Future = dataclasses.field(default_factory=Future)


@dataclasses.dataclass
class _RetuneJob:
    """A validated live knob retune (autonomic planner actuation): the
    scheduler applies it at the next poll boundary — the same staging
    discipline as :class:`_SwapJob`/:class:`_DrainJob`, so a knob flip
    can never tear a live burst (the loop snapshots ``_fused_k`` once
    per poll) or race a chunked prefill (a ``prefill_chunk`` change
    waits until the in-flight chunk jobs drain). ``knobs`` holds the
    canonicalized target values; validation already happened on the
    caller thread (:class:`RetuneError` on refusal)."""

    knobs: Dict[str, Any]
    origin: str = "planner"
    future: Future = dataclasses.field(default_factory=Future)
    # polls spent deferring (chunked prefills in flight while the job
    # changes prefill_chunk) — flight-recorder attribution
    waited_polls: int = 0


@dataclasses.dataclass
class _Slot:
    request: GenRequest
    emitted: List[int] = dataclasses.field(default_factory=list)
    # the prefill's first sampled token stays ON DEVICE at admit (reading
    # it would cost a host sync per admission); the next burst's [0] row
    # carries it to the host instead
    first_pending: bool = True
    # tokens covered by bursts DISPATCHED so far (not yet necessarily
    # observed). When the request has no eos, completion is predictable:
    # dispatched >= max_new_tokens means the in-flight bursts already
    # cover the whole budget and the lane can be re-admitted NOW instead
    # of pipeline_depth bursts later (see the pre-free block in _loop)
    dispatched: int = 0
    # crediting fence: set once the request's output is complete (budget
    # or eos) so rows from later in-flight bursts — overshoot decode, or
    # rows that now belong to the lane's next occupant — are never
    # appended or streamed to a finished request
    credit_done: bool = False


class ContinuousBatcher:
    """Slot-based continuous batching scheduler over a DecoderLM.

    ``submit()`` is thread-safe and returns a Future resolving to the
    generated token list. A single scheduler thread owns the device loop.
    """

    # floor for attn_bucket: cache reads must stay MXU/VPU-tileable on
    # TPU. Tests lower it (via the class attribute) to exercise depth
    # grouping at tiny cache lengths on CPU.
    MIN_ATTN_BUCKET = 64

    def __init__(
        self,
        model,
        params,
        slots: int = 8,
        max_seq: Optional[int] = None,
        mesh=None,
        shard_cache_seq: bool = False,
        prefill_buckets: Sequence[int] = (32, 128, 512, 1024, 1792),
        steps_per_poll: int = 8,
        pipeline_depth: int = 3,
        attn_bucket: int = 128,
        fused_steps_per_dispatch: int = 0,
        draft_model=None,
        draft_params=None,
        speculate_tokens: int = 4,
        prefix_cache_hbm_bytes: int = 0,
        prefix_cache_min_tokens: int = 16,
        admit_queue_limit: int = 0,
        depth_groups: int = 0,
        depth_group_split_bytes: Optional[int] = None,
        prefill_chunk: int = 0,
        flight_recorder_capacity: int = 512,
        restart_budget: int = 3,
        restart_backoff_s: float = 0.5,
        hbm_ledger_bytes: int = 0,
        pressure_high: float = 0.90,
        pressure_low: float = 0.75,
        host_kv_tier_bytes: int = 0,
        kv_tier_min_tokens: int = 0,
        kv_tier_promote_min_tokens: int = 0,
        swap_drain_ms: int = 0,
        swap_resume_policy: str = "resume",
        profiler=None,
    ):
        import jax
        import jax.numpy as jnp
        from jax import lax

        self.model = model
        self.slots = int(slots)
        self.max_seq = int(max_seq or model.cfg.max_seq)
        self.mesh = mesh
        self.steps_per_poll = int(steps_per_poll)
        # burst length actually dispatched: pow2 floor of steps_per_poll —
        # computed ONCE so warm() and the loop can never disagree on which
        # burst executable exists. The floor is surfaced (not silent): it
        # rides server stats as ``steps_per_poll_effective`` and logs once
        # here, so an operator who configured 12 can see they got 8.
        k = max(1, self.steps_per_poll)
        while k & (k - 1):
            k &= k - 1
        self._k = k
        if k != self.steps_per_poll:
            logger.info(
                "steps_per_poll=%d rounded down to the pow2 burst length "
                "%d (see steps_per_poll_effective in server stats)",
                self.steps_per_poll, k,
            )
        # fused multi-step decode: one dispatch runs up to this many
        # decode steps with ON-DEVICE stop-token detection and per-lane
        # done masks (0 = off — the step-at-a-time burst path, exactly
        # the pre-fused code). pow2-floored like steps_per_poll so one
        # executable exists per (K, attn bucket[, group size]).
        self.fused_steps_per_dispatch = max(0, int(fused_steps_per_dispatch))
        fk = self.fused_steps_per_dispatch
        while fk & (fk - 1):
            fk &= fk - 1
        self._fused_k = fk
        # True while the device-resident per-lane stop/budget registers
        # match the host's view; membership changes and mode flips clear
        # it so the next fused dispatch re-uploads (never per burst)
        self._fused_sync = False
        # how many bursts may be in flight before the host reads the oldest
        # one's tokens; 1 = fully synchronous (dispatch, read, dispatch ...)
        self.pipeline_depth = max(1, int(pipeline_depth))
        # attention-read bucket granularity: the per-burst cache read is
        # rounded up to a multiple of this. Smaller = tighter KV reads at
        # deep prefixes but more burst executables (one per bucket); 64
        # is the practical TPU floor (the read must stay MXU/VPU-
        # tileable), enforced via the MIN_ATTN_BUCKET class attribute so
        # production configs keep the historical clamp while CPU tests
        # lower it to exercise the depth-grouping machinery at tiny
        # cache lengths
        self.attn_bucket = max(type(self).MIN_ATTN_BUCKET, int(attn_bucket))
        # depth-aware sub-bursts: max sub-bursts per poll (0/1 = off —
        # the single-burst path is byte-identical to pre-grouping code)
        self.depth_groups = max(0, int(depth_groups))
        # chunked prefill: prompt tokens per interleaved prefill slice
        # (0 = off; prompts whose bucket fits one chunk never chunk)
        self.prefill_chunk = max(0, int(prefill_chunk))
        # speculative decoding: a cheap draft proposes `speculate_tokens`
        # tokens per round and ONE target chunk forward verifies them.
        # Exact for any draft: greedy lanes emit the target's argmax
        # decode; temperature lanes use speculative SAMPLING (accept with
        # min(1, p/q), resample the residual on rejection) whose output
        # distribution equals sampling the target. The draft only sets
        # how many target forwards each token costs.
        self.draft_model = draft_model
        self.speculate_tokens = int(speculate_tokens) if draft_model is not None else 0
        self.prefill_buckets = tuple(
            sorted(b for b in prefill_buckets if b <= self.max_seq)
        ) or (self.max_seq,)

        self._queue: "queue.Queue[GenRequest]" = queue.Queue()
        # -- admit-queue load shedding (shed-before-work) -----------------
        # hard cap on queued-not-admitted requests (0 = uncapped), plus a
        # deadline-aware shed: completion timestamps of finished requests
        # give an observed service rate, and a submit whose expected queue
        # wait (depth / rate) already exceeds its remaining deadline is
        # rejected NOW — before its prefill occupies the device for a
        # response nobody will wait for
        self.admit_queue_limit = max(0, int(admit_queue_limit))
        self._finish_times = collections.deque(maxlen=32)
        self._active: Dict[int, _Slot] = {}
        # device copies of the lane masks; re-uploaded only when lane
        # membership changes (every host->device transfer pays the
        # dispatch-latency tax, so the steady-state loop must not upload
        # anything per burst)
        self._masks_dirty = True
        self._active_dev = None
        self._temps_dev = None
        self._any_stoch = False
        # host mirror of each lane's device position (prompt length at
        # admit, +k per dispatched burst) — lets the scheduler pick the
        # attention-read bucket WITHOUT a device sync
        self._pos_host: Dict[int, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._thread_lock = threading.Lock()
        self._started = threading.Event()
        # -- scheduler supervision (crash-loop restart) -------------------
        # a loop death no longer poisons the batcher forever: the
        # supervisor fails in-flight work with a typed BatcherDead,
        # rebuilds the device state (the donated cache buffers are gone),
        # re-warms, and resumes — bounded by ``restart_budget`` restarts
        # with exponential backoff from ``restart_backoff_s``. Exhausting
        # the budget latches ``health = "dead"`` (readiness goes red so
        # the reconciler replaces the member). ``health`` is a plain str
        # written by one thread at a time: "serving" | "restarting" |
        # "dead" | "closed".
        self.health = "serving"
        self.restart_budget = max(0, int(restart_budget))
        self.restart_backoff_s = max(0.0, float(restart_backoff_s))
        # the budget counts crashes in quick succession (a crash LOOP):
        # after this long without a death, the counter resets — a
        # once-a-day transient must never slowly latch a healthy member
        self.restart_window_s = 300.0
        self._restarts = 0
        self._last_crash_t = 0.0
        # chaos hook: called at the top of every scheduler poll with the
        # running poll count; raising kills the loop, exercising the REAL
        # crash-recovery path (resilience.faults wires it from the
        # SELDON_FAULTS scheduler section; tests set it directly)
        self.fault_hook: Optional[Any] = None
        # multi-tenant hook: called at the top of every poll with the
        # poll count (after the chaos hook). The TenantScheduler wires
        # its wake-up here — bookkeeping only, it must never block or
        # call caller-role batcher methods (serving/weightpager.py)
        self.tenant_hook: Optional[Any] = None
        # the WeightPager whose resident checkpoint the pressure ledger
        # bills as its "pager" component (set by the serving component
        # when multi-tenancy is on; None keeps the ledger unchanged)
        self.tenant_pager: Optional[Any] = None
        self._poll_count = 0
        # WORKING polls only (lanes live, chunked jobs pending, bursts
        # in flight, or queued work): the pressure hook's clock, so a
        # SELDON_FAULTS shrink window lands relative to traffic instead
        # of firing during idle churn
        self._work_poll_count = 0
        # warm() records its arguments here so a crash-restart re-runs
        # the same precompile before resuming admissions
        self._warm_args: Optional[Dict[str, Any]] = None
        # -- radix prefix KV cache (cross-request prompt reuse) -----------
        # device K/V slabs of completed requests' prompts, indexed by a
        # radix tree over token IDs; an admit whose prompt shares a cached
        # prefix splices the slab and prefills only the suffix. Budgeted
        # in HBM bytes (0 = off), LRU-evicted at radix-node granularity.
        self.prefix_cache_min_tokens = max(1, int(prefix_cache_min_tokens))
        self._prefix_cache_budget = int(prefix_cache_hbm_bytes)
        self._prefix_index = None
        if self._prefix_cache_budget > 0:
            from .prefix_cache import RadixPrefixIndex

            self._prefix_index = RadixPrefixIndex(self._prefix_cache_budget)
        # -- tiered KV memory: host-RAM spill tier (serving/kvtier.py) ----
        # SKV1-serialized slabs in pinned host RAM under their own byte
        # budget (0 = off): the reclaim ladder DEMOTES prefix slabs here
        # instead of destroying them (promote = device_put + splice on a
        # later match, locally or from a peer's tier over the KV
        # transport), and preempted lanes checkpoint their exact cache
        # columns for copy-back resume (recompute+replay stays the
        # fallback when the tier evicted the entry).
        self.host_kv_tier_bytes = max(0, int(host_kv_tier_bytes))
        # demote threshold: prefixes shorter than this never enter the
        # tier (defaults to prefix_cache_min_tokens); promote threshold:
        # tier matches shallower than this are not worth the PCIe copy
        # (defaults to the demote threshold)
        self.kv_tier_min_tokens = (
            int(kv_tier_min_tokens) or self.prefix_cache_min_tokens
        )
        self.kv_tier_promote_min_tokens = (
            int(kv_tier_promote_min_tokens) or self.kv_tier_min_tokens
        )
        self._kv_tier = None
        if self.host_kv_tier_bytes > 0:
            from .kvtier import HostKVTier

            self._kv_tier = HostKVTier(
                self.host_kv_tier_bytes,
                min_tokens=self.kv_tier_min_tokens,
                version=self.weight_version
                if hasattr(self, "weight_version") else 0,
            )
        # checkpoint-entry keys are per-batcher sequence numbers
        self._tier_ck_seq = 0
        # spec_rounds / spec_emitted feed the acceptance-rate gauge:
        # emitted/rounds ranges 1 (nothing accepted) .. gamma+1 (all).
        # prefill_steps/prefill_tokens split device prefill work out from
        # decode steps (the prefix cache's win shows up as prefill_tokens
        # dropping while prefix_tokens_saved climbs)
        # burst_reads/burst_read_bytes: modeled HBM read traffic of
        # dispatched decode (sub)bursts — params once per step plus each
        # lane-row's bucketed KV read (spec rounds are excluded: their
        # draft/verify byte model lives in modelbench's round-true MBU).
        # group_* feed the depth-grouping occupancy gauge: real lanes vs
        # pow2-bucket pad rows across grouped sub-bursts.
        # lane_steps = sum over dispatched (sub)bursts of k x rows — the
        # occupancy denominator. With grouping OFF it equals steps x
        # slots; with grouping ON a sub-burst contributes only its
        # gathered rows, so occupancy stays comparable across configs
        # (steps alone would halve apparent occupancy whenever a poll
        # splits into two sub-bursts)
        self.stats = {
            "admitted": 0, "finished": 0, "cancelled": 0, "steps": 0,
            "lane_steps": 0,
            "tokens": 0, "spec_rounds": 0, "spec_emitted": 0,
            "prefill_steps": 0, "prefill_tokens": 0, "prefill_chunks": 0,
            "prefix_hits": 0, "prefix_misses": 0, "prefix_evicted": 0,
            "prefix_tokens_saved": 0, "prefix_cache_bytes": 0,
            "shed": 0,
            "burst_reads": 0, "burst_read_bytes": 0,
            "group_bursts": 0, "group_lanes": 0, "group_pad_lanes": 0,
            # disaggregated serving: slabs/bytes shipped out (prefill
            # role), slabs/bytes admitted in (decode role), and transfer
            # bytes the decode-side radix cache deduplicated away
            "kv_exports": 0, "kv_export_bytes": 0,
            "kv_imports": 0, "kv_import_bytes": 0,
            "kv_transfer_bytes_saved": 0,
            # fault tolerance: supervised scheduler restarts that landed,
            # prefill-peer ejections/readmissions (decode role — bumped by
            # the server's failover transport), and remote prefills served
            # LOCALLY because the entire prefill pool was ejected
            "batcher_restarts": 0,
            "peer_ejections": 0, "peer_readmissions": 0,
            "degraded_local_prefill": 0,
            # HBM pressure: decode lanes preempted (checkpoint-to-host +
            # requeue), recompute-resumes that landed, admissions shed /
            # remote admits refused while over the high watermark, and
            # prefix slabs the reclaim ladder evicted (a subset of
            # prefix_evicted — the pressure-attributed share)
            "preemptions": 0, "preempt_resumes": 0,
            "pressure_sheds": 0, "pressure_refused": 0,
            "pressure_prefix_evictions": 0,
            # tiered KV memory (host-RAM spill tier): slabs demoted to
            # host RAM (prefix demotions + lane checkpoints + export
            # publishes), tier lookups that found an entry, entries
            # promoted back to device (device_put: local prefix match,
            # peer pull, checkpoint copy-back), entries LRU-evicted or
            # CRC-dropped, the tier's live byte level, and resumes that
            # EXPECTED a tier checkpoint but fell back to recompute +
            # teacher-forced replay (the tier evicted/refused it)
            "kv_tier_demotions": 0, "kv_tier_promotions": 0,
            "kv_tier_hits": 0, "kv_tier_evictions": 0,
            "kv_tier_bytes": 0, "kv_tier_replay_fallbacks": 0,
            # fused multi-step decode: device steps run inside stop-aware
            # fused bursts, and the dispatches that carried them — the
            # dispatch-floor win IS fused_steps / fused_dispatches
            # climbing while the host poll rate stays flat
            "fused_steps": 0, "fused_dispatches": 0,
            # operator note, not a counter: the pow2-floored burst length
            # actually dispatched (== steps_per_poll unless it was
            # silently-no-longer rounded down)
            "steps_per_poll_effective": k,
        }
        # export_prefill runs on caller threads (the prefill transport's
        # handlers), concurrently with each other; its stat updates take
        # this lock so counters can't lose increments
        self._export_lock = threading.Lock()
        # SLO instrumentation: queue-wait / TTFT / TPOT samples of
        # COMPLETED requests. ``slo_pending`` is the drain queue the
        # serving component ships as Meta.metrics TIMERs (drop-oldest
        # under pressure — telemetry must never grow unbounded);
        # ``slo_recent`` is a reservoir benches/diagnostics read for
        # percentiles. Cumulative sums ride in ``stats`` so window-diffed
        # bench snapshots get means for free.
        self.slo_pending: "collections.deque" = collections.deque(maxlen=4096)
        self.slo_recent: "collections.deque" = collections.deque(maxlen=2048)
        self.stats.update({
            "slo_samples": 0, "queue_wait_s_sum": 0.0,
            "ttft_s_sum": 0.0, "tpot_s_sum": 0.0,
        })
        # per-tenant splits of the same samples (multi-tenant serving):
        # keyed lazily by tenant id at _resolve time so the single-tenant
        # path allocates nothing. tenant_slo carries cumulative sums +
        # counts; the pending deques drain as tenant-tagged TIMERs; the
        # recent reservoirs feed the TenantScheduler's TTFT feedback.
        self.tenant_slo: Dict[str, Dict[str, float]] = {}
        self.tenant_slo_pending: Dict[str, "collections.deque"] = {}
        self.tenant_slo_recent: Dict[str, "collections.deque"] = {}
        # scheduler flight recorder: one structured record per poll (batch
        # composition, depth-group plan + cost-model verdict, chunk
        # interleave, shed events), bounded + drop-oldest, cheap enough to
        # leave on (0 = off)
        from .flightrecorder import FlightRecorder

        self.flight: Optional[FlightRecorder] = (
            FlightRecorder(flight_recorder_capacity)
            if int(flight_recorder_capacity) > 0
            else None
        )
        # device-time ledger (serving/profiler.py): every warmed-
        # executable dispatch below runs inside ``self._prof.measure``.
        # A disabled ledger's measure() is a shared no-op — the hooks
        # cost one attribute check — and the hooks never touch the
        # dispatched computation, so profiler on vs off is byte-
        # identical and compiles nothing new (tests/test_profiler.py
        # pins both).
        from .profiler import DeviceTimeLedger

        self._prof = (
            profiler if profiler is not None else DeviceTimeLedger()
        )
        # test/debug hook: set to a list and every dispatched decode
        # (sub)burst appends {"lanes", "attn_len", "need"} — the
        # scheduler-level proof that no lane's read bound exceeds its
        # group's bucket
        self.trace_groups: Optional[List[Dict[str, Any]]] = None
        # -- HBM pressure: unified ledger + watermark controller ----------
        # live decode footprint + staging slabs + prefix cache + pending
        # swap double buffer against hbm_ledger_bytes (0 = off: the hot
        # loop never consults it). Over the HIGH watermark the reclaim
        # ladder runs each poll (evict prefixes -> cancel speculation ->
        # preempt lanes -> shed admissions) until usage drops to LOW.
        from .pressure import PressureController

        self._pressure = PressureController(
            hbm_ledger_bytes, high=pressure_high, low=pressure_low
        )
        # chaos hook: called each poll with the poll count; a returned
        # int re-budgets the ledger (-1 restores the boot budget) — the
        # SELDON_FAULTS "pressure" section wires it (resilience.faults)
        self.pressure_hook: Optional[Any] = None
        # preempted requests awaiting recompute-resume: drained BEFORE
        # the admit queue so a victim re-acquires a lane ahead of newer
        # work (its recompute is the price already paid once)
        self._resume_queue: "collections.deque" = collections.deque()
        # reclaim rung 2: speculation cancelled under pressure (draft
        # cache freed; plain bursts decode — greedy streams identical by
        # the spec-exactness contract). Restored when pressure clears.
        self._spec_suppressed = False
        # chunked-prefill jobs in flight, keyed by reserved slot
        self._chunked: Dict[int, _ChunkJob] = {}
        # -- live weight hot-swap -----------------------------------------
        # request_weight_swap stages a double-buffered _SwapJob here; the
        # scheduler loop executes it at a poll boundary once all lanes
        # drained. weight_version keys the prefix cache (old-weights K/V
        # can never splice into a new-weights prefill) and rides flight-
        # recorder swap events.
        self.weight_version: Any = 0
        self.stats["weight_swaps"] = 0
        self._swap_lock = threading.Lock()
        self._pending_swap: Optional[_SwapJob] = None
        self._swap_seq = 0
        # hot-swap straggler bound: after this long draining, in-flight
        # lanes are preempt-checkpointed so one long generation cannot
        # stall a weight flip indefinitely (0 = wait forever, the
        # pre-existing behavior). Policy for the checkpointed
        # stragglers: "resume" re-queues them to continue on the NEW
        # weights (their prefix replays under the new version — a
        # deliberate, documented identity trade); "fail" refuses them
        # typed (WeightVersionMismatch, 409-class) so the client
        # re-submits under the new version knowingly.
        self.swap_drain_ms = max(0, int(swap_drain_ms))
        if swap_resume_policy not in ("resume", "fail"):
            raise ValueError(
                f"swap_resume_policy must be resume|fail, got "
                f"{swap_resume_policy!r}"
            )
        self.swap_resume_policy = swap_resume_policy
        # -- graceful drain / live-lane migration -------------------------
        # drain() stages a _DrainJob; the scheduler checkpoints every
        # live lane at a poll boundary and hands the host-side
        # checkpoints back for migration to a peer (serving/migration.py)
        self._pending_drain: Optional[_DrainJob] = None
        self._drain_lock = threading.Lock()
        self.stats.update({
            # drains completed, checkpoints exported to a peer,
            # checkpoints successfully migrated (peer accepted), resumes
            # admitted FROM a wire checkpoint/resume token, and lanes
            # preempt-checkpointed by the hot-swap straggler bound
            "drains": 0, "checkpoint_exports": 0, "migrations": 0,
            "migrated_resumes": 0, "swap_preemptions": 0,
        })
        # -- planner retune (autonomic serving planner) -------------------
        # retune() stages a validated _RetuneJob; the scheduler applies
        # it at a poll boundary. The census snapshot records which
        # executables warm() will compile — derived from the SAME boot
        # knobs warm() reads — so a later retune can be checked against
        # what actually exists instead of stalling the loop on a compile.
        self._retune_lock = threading.Lock()
        self._pending_retune: Optional[_RetuneJob] = None
        _census_fks: List[int] = []
        if self._fused_k > 0:
            _cfk = self._fused_k
            _clo = min(self._k, self._fused_k)
            while _cfk >= _clo:
                _census_fks.append(_cfk)
                _cfk //= 2
        self._retune_census: Dict[str, Any] = {
            # fused Ks warm() compiles: pow2s in [min(k, fused), fused]
            "fused_ks": tuple(sorted(_census_fks)),
            # group-burst variants exist only when boot depth_groups > 1
            "depth_groups": self.depth_groups,
            # chunk executables exist only for the boot chunk size
            "prefill_chunk": self.prefill_chunk,
            # warm()'s attention-bucket overhang covered this depth
            "pipeline_depth": self.pipeline_depth,
        }
        self.stats["planner_retunes"] = 0

        # -- device state ----------------------------------------------------
        # The persistent KV cache lives UNSTACKED: per-layer [S, KV, T, Dh]
        # arrays. A stacked [L, ...] cache threaded through the layer scan
        # as xs/ys makes XLA rewrite every layer's cache every step (cost
        # scales with total cache bytes); per-layer arrays carried through
        # the burst scan update in place — only the one-position scatter
        # touches HBM (see DecoderLM.decode_step_ragged_list).
        def cache_sharding_for(kv_heads: int):
            """Per-layer cache [S, KV, T, Dh]: KV heads over `model` (tp),
            cache length over `seq` (long context spans ICI). KV head
            counts that don't divide the model axis (GQA targets, thin
            drafts) replicate the KV dim instead of failing device_put.
            The layout itself lives on the model (DecoderLM.cache_sharding)
            so it stays next to param_sharding; this closure only binds
            the mesh + seq knob for the supervisor's crash-restart."""
            if mesh is None:
                return None
            if hasattr(model, "cache_sharding"):
                return model.cache_sharding(
                    mesh, kv_heads=kv_heads, shard_seq=shard_cache_seq
                )
            from jax.sharding import NamedSharding, PartitionSpec as P

            model_ax = "model" if "model" in mesh.axis_names else None
            seq_ax = (
                "seq"
                if shard_cache_seq and "seq" in mesh.axis_names and mesh.shape["seq"] > 1
                else None
            )
            if model_ax is not None and kv_heads % dict(mesh.shape)["model"] != 0:
                model_ax = None
            return NamedSharding(mesh, P(None, model_ax, seq_ax, None))

        def unstack_cache(owner, sharding):
            stacked = owner.init_cache(self.slots, self.max_seq)
            n_layers = stacked["k"].shape[0]
            out = {
                "k": [stacked["k"][l] for l in range(n_layers)],
                "v": [stacked["v"][l] for l in range(n_layers)],
            }
            if sharding is not None:
                out = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, sharding), out
                )
            return out

        cast_memo: Dict[int, Any] = {}

        def serving_cast(model_, p):
            """Store float params in the model's COMPUTE dtype. The forward
            casts every param to compute dtype at use, so pre-casting is
            numerically identical — but decode is HBM-bound and fp32
            storage would double both the footprint and the bytes every
            fused step reads (a 1.3B model: 5.4GB/step vs 2.7GB).

            Identity-memoised so leaves the early-exit draft SHARES with
            the target (embed/unembed/ln_f in generateserver's self-draft)
            stay one device array instead of casting into two copies
            (~262MB duplicated at the flagship config otherwise)."""
            dt = jnp.dtype(getattr(model_, "compute_dtype", "bfloat16"))
            if dt == jnp.float32:
                return p

            def cast(a):
                if not (hasattr(a, "dtype") and a.dtype == jnp.float32):
                    return a
                key = id(a)
                if key not in cast_memo:
                    cast_memo[key] = a.astype(dt)
                return cast_memo[key]

            return jax.tree_util.tree_map(cast, p)

        params = serving_cast(model, params)
        if mesh is not None:
            if hasattr(model, "set_serving_mesh"):
                # arm sharded-STORAGE / replicated-COMPUTE serving BEFORE
                # any executable traces: every entry gathers params/cache
                # to full replication (exact all-gather, no arithmetic) so
                # the math is the byte-identical 1-device program, and
                # every exit re-shards cache writes (models/llm.py)
                model.set_serving_mesh(mesh, shard_seq=shard_cache_seq)
            params = jax.device_put(params, model.param_sharding(mesh, params))
        self.params = params
        # the cast memo pins the boot params' cast leaves; a weight swap
        # clears it so the OLD buffer actually frees once the flip lands
        self._cast_memo = cast_memo
        # kept as closures for the supervisor: a crash-restart reallocates
        # the donated cache (and lane registers) through the same path the
        # constructor used, params untouched
        self._cache_sharding_for = cache_sharding_for
        self._unstack_cache = unstack_cache
        # staging/transfer slab layout [L, 1, KV, bucket, Dh]: every
        # host->device slab upload (remote admit, tier promote, copy-back
        # resume, fresh chunked-prefill slab) lands pre-sharded through
        # _upload_slab so the insert/splice executables never reshard
        self._slab_sharding = (
            model.slab_sharding(mesh)
            if mesh is not None and hasattr(model, "slab_sharding")
            else None
        )
        # per-shard split factors for the pressure ledger: how many ways
        # the persistent cache's bytes divide across chips (model axis,
        # plus seq when the cache length is sharded) — 1 when unmeshed
        # or when indivisible KV heads forced replication
        self._kv_model_shard = 1
        self._kv_seq_shard = 1
        if mesh is not None:
            mshape = dict(mesh.shape)
            tp = int(mshape.get("model", 1))
            kvh = int(getattr(model.cfg, "n_kv_heads", 0) or 0)
            if tp > 1 and kvh and kvh % tp == 0:
                self._kv_model_shard = tp
            sq = int(mshape.get("seq", 1))
            if shard_cache_seq and sq > 1:
                self._kv_seq_shard = sq
        self._kv_shard = self._kv_model_shard * self._kv_seq_shard
        self._draft_params = None
        self._draft_cache = None
        if self.speculate_tokens > 0:
            dp = serving_cast(draft_model, draft_params)
            if mesh is not None:
                if hasattr(draft_model, "set_serving_mesh"):
                    draft_model.set_serving_mesh(mesh)
                dp = jax.device_put(dp, draft_model.param_sharding(mesh, dp))
            self._draft_params = dp
        self._alloc_device_state()

        # -- executables -----------------------------------------------------

        def sample_next(keys, logits, temps):
            """The ONE per-lane greedy/seeded next-token sampler: split
            each lane's key, draw categorical at temps>0 else argmax.
            Every batched decode path (step-at-a-time burst, fused
            masked step, batched prefill firsts) calls THIS — the
            byte-identity contract across those paths rests on them
            sharing the sampling math, so any change lands everywhere
            by construction."""
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            split = jax.vmap(jax.random.split)(keys)  # [S, 2, key]
            keys, subs = split[:, 0], split[:, 1]
            sampled = jax.vmap(
                lambda k, lg, t: jax.random.categorical(k, lg / jnp.maximum(t, 1e-6))
            )(subs, logits, temps).astype(jnp.int32)
            return keys, jnp.where(temps > 0, sampled, greedy)

        def fused_step(params, ks, vs, cur_tok, pos, active, temps, keys, attn_len):
            logits, ks, vs = model.decode_step_ragged_list(
                params, ks, vs, cur_tok[:, None], pos, attn_len=attn_len
            )
            keys, nxt = sample_next(keys, logits, temps)
            nxt = jnp.where(active, nxt, 0)
            pos = jnp.where(active, pos + 1, pos)
            return nxt, pos, ks, vs, keys

        def insert(cache, cache_one, slot, first_tok, first_pos, lane_key, cur_tok, pos, keys):
            # cache_one is the prefill's stacked [L, 1, KV, Tb, Dh] slab;
            # each layer's slice lands in that layer's cache at `slot`
            new = {
                name: [
                    lax.dynamic_update_slice(
                        layer, cache_one[name][l], (slot, 0, 0, 0)
                    )
                    for l, layer in enumerate(cache[name])
                ]
                for name in ("k", "v")
            }
            cur_tok = cur_tok.at[slot].set(first_tok)
            pos = pos.at[slot].set(first_pos)
            keys = keys.at[slot].set(lane_key)
            return new, cur_tok, pos, keys

        def prefill_one(params, prompt, last_index, seed, temp):
            # cache_one spans only the prompt bucket — decode writes extend
            # it in place, so inserting a full max_seq slab per admission
            # would just copy zeros over HBM
            logits, cache_one = model.prefill(
                params, prompt, prompt.shape[1], last_index=last_index
            )
            key = jax.random.PRNGKey(seed)
            key, sub = jax.random.split(key)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = jax.random.categorical(
                sub, logits / jnp.maximum(temp, 1e-6), axis=-1
            ).astype(jnp.int32)
            first = jnp.where(temp > 0, sampled, greedy)
            return first, cache_one, key

        def prefill_many(params, prompts, last_index, seeds, temps):
            # m admissions share ONE forward: the prompt matmuls go from
            # [Tb, d] to [m*Tb, d] rows, so the MXU amortises what m
            # separate [1, Tb] prefills would each pay — at 20-30 admits/s
            # the per-admission forward is the throughput tier's largest
            # non-decode device cost. m is a small static bucket (2/4/8),
            # so at most 3 extra executables exist per prompt bucket.
            logits, slab = model.prefill(
                params, prompts, prompts.shape[1], last_index=last_index
            )
            keys = jax.vmap(jax.random.PRNGKey)(seeds)
            keys, firsts = sample_next(keys, logits, temps)
            return firsts, slab, keys

        def insert_many(cache, slab, slot_ix, firsts, first_pos, lane_keys,
                       cur_tok, pos, keys):
            # slab is the batched prefill's [L, m, KV, Tb, Dh] stack; each
            # row i lands in its lane slot_ix[i] (traced start indices —
            # one executable per (m, bucket), not per slot assignment)
            m = slab["k"].shape[1]
            new = {
                name: [
                    layer
                    for layer in cache[name]
                ]
                for name in ("k", "v")
            }
            for i in range(m):
                for name in ("k", "v"):
                    for l in range(len(new[name])):
                        new[name][l] = lax.dynamic_update_slice(
                            new[name][l], slab[name][l, i:i + 1],
                            (slot_ix[i], 0, 0, 0),
                        )
            cur_tok = cur_tok.at[slot_ix].set(firsts)
            pos = pos.at[slot_ix].set(first_pos)
            keys = keys.at[slot_ix].set(lane_keys)
            return new, cur_tok, pos, keys

        def fused_burst(params, cache, cur_tok, pos, active, temps, keys, k, attn_len):
            """k fused decode steps as one executable; returns [k, slots]
            tokens so the host syncs once per burst. ``attn_len`` (static)
            bounds the cache read — the scheduler picks a bucket >= every
            lane's end-of-burst position, so one executable exists per
            (k, bucket) pair and the read narrows to live prefix."""

            def body(carry, _):
                ks, vs, cur_tok, pos, keys = carry
                nxt, pos, ks, vs, keys = fused_step(
                    params, ks, vs, cur_tok, pos, active, temps, keys, attn_len
                )
                return (ks, vs, nxt, pos, keys), nxt

            (ks, vs, cur_tok_out, pos, keys), toks = lax.scan(
                body, (cache["k"], cache["v"], cur_tok, pos, keys), None, length=k
            )
            # row 0 = the tokens the burst STARTED from (deferred prefill
            # firsts ride home with the burst's one sync)
            toks = jnp.concatenate([cur_tok[None, :], toks], axis=0)
            return toks, cur_tok_out, pos, {"k": ks, "v": vs}, keys

        # -- stop-aware fused multi-step decode ------------------------------
        def fused_masked_step(params, ks, vs, cur_tok, pos, alive, temps,
                              keys, attn_len, park):
            """One decode step under a per-lane ``alive`` mask: finished
            lanes' K/V writes park OUT OF BOUNDS at ``park`` (dropped by
            JAX scatter semantics — the lane's cache freezes) and their
            token/position carry unchanged, so a lane that hit its stop
            keeps its stop token in ``cur_tok`` for the next burst's
            done0 check. For alive lanes the matmuls, mask bound, key
            split, and sampling are exactly ``fused_step``'s — the
            byte-identity contract vs the step-at-a-time path rests on
            that. Keys split for EVERY lane each step (as fused_step
            does): a frozen lane's key is dead state its next occupant's
            insert overwrites."""
            wpos = jnp.where(alive, pos, park)
            logits, ks, vs = model.decode_step_ragged_list(
                params, ks, vs, cur_tok[:, None], pos, attn_len=attn_len,
                write_pos=wpos,
            )
            keys, nxt = sample_next(keys, logits, temps)
            cur_tok = jnp.where(alive, nxt, cur_tok)
            pos = jnp.where(alive, pos + 1, pos)
            return cur_tok, pos, ks, vs, keys

        def fused_scan_body(params, act, temps, stops, attn_len, park):
            """The ONE fused scan body both burst variants run — a fix to
            the done condition or the budget decrement lands in the
            whole-batch AND the gathered depth-group executable by
            construction, so the grouped-vs-whole-batch byte-identity
            contract cannot drift one-sided."""
            def body(carry, _):
                ks, vs, cur, p, kk, budget, done = carry
                alive = act & ~done
                cur, p, ks, vs, kk = fused_masked_step(
                    params, ks, vs, cur, p, alive, temps, kk, attn_len, park
                )
                budget = budget - alive.astype(jnp.int32)
                done = done | (alive & ((cur == stops) | (budget <= 0)))
                return (ks, vs, cur, p, kk, budget, done), (
                    jnp.where(alive, cur, 0), alive,
                )
            return body

        def fused_stop_burst(params, cache, cur_tok, pos, active, temps,
                             keys, stops, budgets, k, attn_len):
            """k decode steps with ON-DEVICE stop-token detection and
            per-lane done masks: a lane freezes the moment it emits its
            stop token or exhausts its remaining budget — its writes park
            OOB, its registers stop advancing — while the other lanes
            keep decoding. One dispatch can therefore run far past the
            step-at-a-time burst length without decoding garbage past a
            stop. Returns ``([k+1, S]`` tokens with row 0 = the start
            tokens, per-lane emitted ``counts``, a ``done`` bitmap, and
            the updated lane registers) — the host syncs once per poll
            and reads nothing else. ``stops`` is -1 for lanes without an
            eos (tokens are >= 0, so it never matches); ``budgets`` is
            each lane's remaining allowance AFTER its current token
            (decremented on device, re-uploaded only on membership
            changes)."""
            park = cache["k"][0].shape[2]  # static: index >= T is dropped
            body = fused_scan_body(params, active, temps, stops, attn_len,
                                   park)
            # a lane can arrive already-done: its stop token was emitted
            # in an earlier burst the host has not read yet (pipeline
            # lag), or its budget was fully covered — either way it runs
            # zero steps here instead of overshoot-decoding
            done0 = ~active | (budgets <= 0) | (cur_tok == stops)
            (ks, vs, cur, pos, keys, budgets, done), (toks, alive_rows) = (
                lax.scan(
                    body,
                    (cache["k"], cache["v"], cur_tok, pos, keys, budgets,
                     done0),
                    None, length=k,
                )
            )
            counts = alive_rows.astype(jnp.int32).sum(axis=0)
            toks = jnp.concatenate([cur_tok[None, :], toks], axis=0)
            return (toks, counts, done, cur, pos, {"k": ks, "v": vs}, keys,
                    budgets)

        def fused_group_stop_burst(params, cache, cur_tok, pos, temps, keys,
                                   stops, budgets, lane_ix, n_real, k,
                                   attn_len):
            """Stop-aware fused burst over a GATHERED depth group: the
            group_burst gather/scatter discipline (pads parked at
            ``attn_len``, no pad state leaking back into other groups'
            lanes) composed with fused_stop_burst's done masks — one
            executable per (group-size bucket, attn bucket, K) triple,
            all precompiled by warm()."""
            act = jnp.arange(lane_ix.shape[0], dtype=jnp.int32) < n_real
            g_tok = cur_tok[lane_ix]
            g_pos = jnp.where(act, pos[lane_ix], attn_len)
            g_temps = temps[lane_ix]
            g_keys = keys[lane_ix]
            g_stop = jnp.where(act, stops[lane_ix], -1)
            g_budget = budgets[lane_ix]
            g_ks = [layer[lane_ix, :, :attn_len, :] for layer in cache["k"]]
            g_vs = [layer[lane_ix, :, :attn_len, :] for layer in cache["v"]]
            # pads park their writes at attn_len (group_burst's
            # discipline); full-depth sliced views need no mask bound
            body = fused_scan_body(params, act, g_temps, g_stop, None,
                                   attn_len)
            done0 = ~act | (g_budget <= 0) | (g_tok == g_stop)
            ((g_ks, g_vs, tok_out, g_pos, g_keys, g_budget, done),
             (toks, alive_rows)) = lax.scan(
                body, (g_ks, g_vs, g_tok, g_pos, g_keys, g_budget, done0),
                None, length=k,
            )
            counts = alive_rows.astype(jnp.int32).sum(axis=0)
            toks = jnp.concatenate([g_tok[None, :], toks], axis=0)
            new = {
                "k": [
                    layer.at[lane_ix, :, :attn_len, :].set(g)
                    for layer, g in zip(cache["k"], g_ks)
                ],
                "v": [
                    layer.at[lane_ix, :, :attn_len, :].set(g)
                    for layer, g in zip(cache["v"], g_vs)
                ],
            }
            cur_tok = cur_tok.at[lane_ix].set(
                jnp.where(act, tok_out, cur_tok[lane_ix])
            )
            pos = pos.at[lane_ix].set(jnp.where(act, g_pos, pos[lane_ix]))
            keys = keys.at[lane_ix].set(
                jnp.where(act[:, None], g_keys, keys[lane_ix])
            )
            budgets = budgets.at[lane_ix].set(
                jnp.where(act, g_budget, budgets[lane_ix])
            )
            return toks, counts, done, cur_tok, pos, new, keys, budgets

        # -- prefix-cache executables ---------------------------------------
        def prefix_prefill(params, slab, suffix, start_pos, last_index, seed, temp):
            # suffix-only prefill over the cached prefix slab: the model's
            # prefix-splice op + the same first-token sampling prefill_one
            # does. One executable per (slab bucket, suffix bucket) pair —
            # start_pos/last_index are traced
            logits, suffix_slab = model.prefill_with_prefix(
                params, slab, suffix, start_pos, last_index=last_index
            )
            key = jax.random.PRNGKey(seed)
            key, sub = jax.random.split(key)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = jax.random.categorical(
                sub, logits / jnp.maximum(temp, 1e-6), axis=-1
            ).astype(jnp.int32)
            first = jnp.where(temp > 0, sampled, greedy)
            return first, suffix_slab, key

        def insert_prefix(cache, slab, suffix_slab, slot, start_pos,
                          first_tok, first_pos, lane_key, cur_tok, pos, keys):
            # splice the donor prefix slab at the lane's origin, then the
            # freshly prefilled suffix at start_pos (both traced starts —
            # donor residue past the real prompt end is decode-overwritten
            # before it can become readable, the standard residue invariant)
            new = {}
            for name in ("k", "v"):
                layers = []
                for l, layer in enumerate(cache[name]):
                    layer = lax.dynamic_update_slice(
                        layer, slab[name][l], (slot, 0, 0, 0)
                    )
                    layer = lax.dynamic_update_slice(
                        layer, suffix_slab[name][l], (slot, 0, start_pos, 0)
                    )
                    layers.append(layer)
                new[name] = layers
            cur_tok = cur_tok.at[slot].set(first_tok)
            pos = pos.at[slot].set(first_pos)
            keys = keys.at[slot].set(lane_key)
            return new, cur_tok, pos, keys

        def extract_prefix(cache, slot, bucket):
            # copy one lane's prompt-prefix K/V out as a stacked cache_one
            # slab [L, 1, KV, bucket, Dh] — the publishable unit. A copy,
            # not a view: it must outlive the donated cache's churn
            return {
                name: jnp.stack(
                    [
                        lax.dynamic_slice(
                            layer, (slot, 0, 0, 0),
                            (1, layer.shape[1], bucket, layer.shape[3]),
                        )
                        for layer in cache[name]
                    ]
                )
                for name in ("k", "v")
            }

        # -- depth-aware grouped sub-burst -----------------------------------
        def group_burst(params, cache, cur_tok, pos, temps, keys, lane_ix,
                        n_real, k, attn_len):
            """k fused decode steps over a GATHERED lane group: lane_ix
            ([Gb] int32, DISTINCT lanes; rows >= n_real are pads) selects
            the group, each lane's cache prefix [0, attn_len) is gathered
            into a [Gb, KV, attn_len, Dh] slab, the burst scans over the
            slab, and state scatters back. The read per step is the
            GROUP's bucket, not the batch max — the whole point. Pads are
            parked at position attn_len so their K/V writes fall out of
            bounds and are dropped (jax scatter semantics); their lanes'
            slabs round-trip bit-identical, so padding with lanes of
            other (deeper) groups is safe in any dispatch order. One
            executable per (Gb, attn_len) pair; gather+scatter cost
            ~4/k of the group's per-burst read, amortised by the scan."""
            act = jnp.arange(lane_ix.shape[0], dtype=jnp.int32) < n_real
            g_tok = cur_tok[lane_ix]
            g_pos = jnp.where(act, pos[lane_ix], attn_len)
            g_temps = temps[lane_ix]
            g_keys = keys[lane_ix]
            g_ks = [layer[lane_ix, :, :attn_len, :] for layer in cache["k"]]
            g_vs = [layer[lane_ix, :, :attn_len, :] for layer in cache["v"]]

            def body(carry, _):
                ks, vs, tok, p, kk = carry
                nxt, p, ks, vs, kk = fused_step(
                    params, ks, vs, tok, p, act, g_temps, kk, None
                )
                return (ks, vs, nxt, p, kk), nxt

            (g_ks, g_vs, tok_out, g_pos, g_keys), toks = lax.scan(
                body, (g_ks, g_vs, g_tok, g_pos, g_keys), None, length=k
            )
            toks = jnp.concatenate([g_tok[None, :], toks], axis=0)
            new = {
                "k": [
                    layer.at[lane_ix, :, :attn_len, :].set(g)
                    for layer, g in zip(cache["k"], g_ks)
                ],
                "v": [
                    layer.at[lane_ix, :, :attn_len, :].set(g)
                    for layer, g in zip(cache["v"], g_vs)
                ],
            }
            # pads (inactive rows) must not leak burst-local state back
            # into lanes that belong to OTHER groups' bursts
            cur_tok = cur_tok.at[lane_ix].set(
                jnp.where(act, tok_out, cur_tok[lane_ix])
            )
            pos = pos.at[lane_ix].set(jnp.where(act, g_pos, pos[lane_ix]))
            keys = keys.at[lane_ix].set(
                jnp.where(act[:, None], g_keys, keys[lane_ix])
            )
            return toks, cur_tok, pos, new, keys

        # -- preemption recompute-resume: teacher-forced decode replay -------
        def replay_burst(params, cache, lane_ix, toks, act, start_pos,
                         attn_len):
            """Rebuild the K/V of already-emitted tokens for ONE gathered
            lane by replaying them through the SAME fused decode step
            that wrote them originally. A prefill over prompt+generated
            would recompute those positions with different matmul shapes
            — visibly different K/V at bf16, enough to flip a near-tied
            argmax downstream — so byte-identical resume REQUIRES the
            decode op. ``toks``/``act`` are a fixed-length (k) forced
            chunk (pads inactive: their writes land at the unadvanced
            position the lane's next real step overwrites before any
            read). One executable per (k, attn_len) pair, same discipline
            as group_burst; the gathered [1]-lane execution is bitwise
            equal to the full-batch row (the depth-grouping invariant)."""
            g_ks = [layer[lane_ix, :, :attn_len, :] for layer in cache["k"]]
            g_vs = [layer[lane_ix, :, :attn_len, :] for layer in cache["v"]]
            pos0 = jnp.full((1,), start_pos, jnp.int32)

            def body(carry, x):
                ks, vs, pos = carry
                tok, a = x
                _logits, ks, vs = model.decode_step_ragged_list(
                    params, ks, vs, tok[None, None], pos, attn_len=None
                )
                pos = jnp.where(a, pos + 1, pos)
                return (ks, vs, pos), None

            (g_ks, g_vs, _pos), _ = lax.scan(
                body, (g_ks, g_vs, pos0), (toks, act)
            )
            new = {
                "k": [
                    layer.at[lane_ix, :, :attn_len, :].set(g)
                    for layer, g in zip(cache["k"], g_ks)
                ],
                "v": [
                    layer.at[lane_ix, :, :attn_len, :].set(g)
                    for layer, g in zip(cache["v"], g_vs)
                ],
            }
            return new

        self._replay_fn = jax.jit(
            replay_burst, donate_argnums=(1,), static_argnums=(6,)
        )

        # -- chunked prefill (interleaved with decode polls) -----------------
        def chunk_prefill_step(params, slab, tokens, start_pos, last_index,
                               seed, temp, attn_len, is_last):
            """One prompt chunk into a STAGING slab (cache_one layout,
            outside the decode cache — in-flight bursts can never touch a
            half-built prompt, and the decode executables stay bit-exact
            vs the whole-prompt path). The FINAL chunk (static
            ``is_last``) additionally samples the first token exactly
            like prefill_one — same PRNG derivation, so chunked and
            unchunked admits emit identical streams; the finished slab
            then goes through the ORDINARY lane insert."""
            logits, slab = model.prefill_chunk(
                params, slab, tokens, start_pos, attn_len,
                last_index=last_index, want_logits=is_last,
            )
            if not is_last:
                zero = jnp.zeros((), jnp.int32)
                return slab, zero, jax.random.PRNGKey(0)
            key = jax.random.PRNGKey(seed)
            key, sub = jax.random.split(key)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = jax.random.categorical(
                sub, logits / jnp.maximum(temp, 1e-6), axis=-1
            ).astype(jnp.int32)
            first = jnp.where(temp > 0, sampled, greedy)
            return slab, first[0], key

        def splice_slab(slab, donor):
            # prefix-cache hit under chunking: the donor's K/V land at the
            # head of the staging slab, chunking resumes at the match
            # point (donor bucket <= prompt bucket per _prefix_match)
            return {
                name: lax.dynamic_update_slice(
                    slab[name], donor[name], (0, 0, 0, 0, 0)
                )
                for name in ("k", "v")
            }

        self._burst_fn = jax.jit(
            fused_burst, donate_argnums=(1,), static_argnums=(7, 8)
        )
        self._group_burst_fn = jax.jit(
            group_burst, donate_argnums=(1,), static_argnums=(8, 9)
        )
        self._fused_burst_fn = jax.jit(
            fused_stop_burst, donate_argnums=(1,), static_argnums=(9, 10)
        )
        self._fused_group_fn = jax.jit(
            fused_group_stop_burst, donate_argnums=(1,),
            static_argnums=(10, 11),
        )
        self._chunk_fn = jax.jit(
            chunk_prefill_step, donate_argnums=(1,), static_argnums=(7, 8)
        )
        self._splice_fn = jax.jit(splice_slab, donate_argnums=(0,))
        # depth-grouping cost model: a separate sub-burst re-reads the
        # params every step; splitting a shallower group off only pays
        # when its modeled KV-read saving per step beats that (override
        # via depth_group_split_bytes — tests force 0 to always split)
        self._kv_key_bytes = 2 * sum(
            layer.dtype.itemsize * layer.shape[1] * layer.shape[3]
            for layer in self._cache["k"]
        )
        # the draft cache's per-token K/V price (speculation only): the
        # pressure ledger charges live lanes for BOTH caches while the
        # draft is resident, and stops when rung 2 frees it
        self._draft_kv_key_bytes = (
            2 * sum(
                layer.dtype.itemsize * layer.shape[1] * layer.shape[3]
                for layer in self._draft_cache["k"]
            )
            if self.speculate_tokens > 0
            else 0
        )
        self._param_bytes = sum(
            leaf.nbytes
            for leaf in jax.tree_util.tree_leaves(self.params)
            if hasattr(leaf, "nbytes")
        )

        # per-chip param footprint under the mesh layout: each leaf's
        # shard shape is pure sharding metadata (no device sync), so this
        # is exact even for the mixed partitioned/replicated TP layout.
        # Equal to _param_bytes when unmeshed/fully replicated.
        def _leaf_shard_bytes(leaf):
            sh = getattr(leaf, "sharding", None)
            if sh is None or not hasattr(sh, "shard_shape"):
                return leaf.nbytes
            n = leaf.dtype.itemsize
            for d in sh.shard_shape(leaf.shape):
                n *= d
            return n

        self._param_shard_bytes = sum(
            _leaf_shard_bytes(leaf)
            for leaf in jax.tree_util.tree_leaves(self.params)
            if hasattr(leaf, "nbytes")
        )
        self._group_split_bytes = (
            int(depth_group_split_bytes)
            if depth_group_split_bytes is not None
            else self._param_bytes
        )
        self._insert_fn = jax.jit(insert, donate_argnums=(0,))
        self._prefill_fn = jax.jit(prefill_one)
        self._prefill_many_fn = jax.jit(prefill_many)
        self._insert_many_fn = jax.jit(insert_many, donate_argnums=(0,))
        self._prefix_prefill_fn = jax.jit(prefix_prefill)
        self._insert_prefix_fn = jax.jit(insert_prefix, donate_argnums=(0,))
        self._extract_fn = jax.jit(extract_prefix, static_argnums=(2,))

        # -- speculative executables (exact; see spec_round docstring) ------
        self._spec_burst_fn = None
        self._draft_prefill_fn = None
        self._draft_insert_fn = None
        if self.speculate_tokens > 0:
            gamma = self.speculate_tokens
            draft = draft_model

            def _lane_split(keys):
                split = jax.vmap(jax.random.split)(keys)
                return split[:, 0], split[:, 1]

            def spec_round(
                params, dparams, ks, vs, dks, dvs, cur_tok, pos, active,
                temps, keys, attn_len, any_stoch,
            ):
                """One speculation round: the draft proposes gamma tokens,
                ONE target chunk forward verifies, the accepted prefix + a
                correction/bonus token are emitted.

                Exactness per lane (Leviathan et al. speculative sampling):
                  * temp == 0 — draft argmax, accept while it equals the
                    target argmax: output IS the target's greedy decode.
                  * temp > 0 — draft SAMPLES from q, accept d_i with prob
                    min(1, p(d_i)/q(d_i)); on first rejection resample from
                    norm(max(p-q, 0)); on full acceptance sample the bonus
                    from p. The emitted distribution provably equals
                    sampling from the target — for ANY draft.
                Returns per-lane emitted tokens [S, gamma+1] and counts [S].
                """
                safe_t = jnp.maximum(temps, 1e-6)[:, None]  # [S,1]
                stoch = (temps > 0)
                dtok, dpos = cur_tok, pos
                drafts, q_rows = [], []
                for _ in range(gamma):
                    dlogits, dks, dvs = draft.decode_step_ragged_list(
                        dparams, dks, dvs, dtok[:, None], dpos, attn_len=attn_len
                    )
                    greedy = jnp.argmax(dlogits, -1).astype(jnp.int32)
                    if any_stoch:
                        keys, subs = _lane_split(keys)
                        q_rows.append(jax.nn.softmax(dlogits / safe_t, axis=-1))
                        sampled = jax.vmap(jax.random.categorical)(
                            subs, dlogits / safe_t
                        ).astype(jnp.int32)
                        dtok = jnp.where(stoch, sampled, greedy)
                    else:
                        dtok = greedy
                    dtok = jnp.where(active, dtok, 0)
                    drafts.append(dtok)
                    dpos = jnp.where(active, dpos + 1, dpos)
                drafts_arr = jnp.stack(drafts, axis=1)  # [S, gamma]
                window = jnp.concatenate([cur_tok[:, None], drafts_arr], axis=1)
                tlogits, ks, vs = model.decode_chunk_ragged_list(
                    params, ks, vs, window, pos, attn_len=attn_len
                )
                t_greedy = jnp.argmax(tlogits, -1).astype(jnp.int32)  # [S,g+1]
                acc_greedy = drafts_arr == t_greedy[:, :gamma]

                if any_stoch:
                    q_full = jnp.stack(q_rows, axis=1)  # [S, gamma, V]
                    p = jax.nn.softmax(tlogits / safe_t[..., None], axis=-1)
                    # acceptance: p_{i-1}(d_i)/q_{i-1}(d_i) vs lane uniforms
                    p_sel = jnp.take_along_axis(
                        p[:, :gamma, :], drafts_arr[..., None], axis=2
                    )[..., 0]  # [S, gamma]
                    q_sel = jnp.take_along_axis(
                        q_full, drafts_arr[..., None], axis=2
                    )[..., 0]
                    keys, subs = _lane_split(keys)
                    u = jax.vmap(lambda kk: jax.random.uniform(kk, (gamma,)))(subs)
                    acc_stoch = u < jnp.minimum(
                        p_sel / jnp.maximum(q_sel, 1e-20), 1.0
                    )
                    acc = jnp.where(stoch[:, None], acc_stoch, acc_greedy)
                else:
                    acc = acc_greedy
                accepted = jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(axis=1)

                # correction/bonus token at window index `accepted`
                corr_greedy = jnp.take_along_axis(
                    t_greedy, accepted[:, None], axis=1
                )[:, 0]
                if any_stoch:
                    p_at_a = jnp.take_along_axis(
                        p, accepted[:, None, None], axis=1
                    )[:, 0]  # [S, V]
                    a_clamp = jnp.minimum(accepted, gamma - 1)
                    q_at_a = jnp.take_along_axis(
                        q_full, a_clamp[:, None, None], axis=1
                    )[:, 0]
                    resid = jnp.maximum(p_at_a - q_at_a, 0.0)
                    resid_sum = resid.sum(-1, keepdims=True)
                    # numerically-empty residual (p <= q everywhere) -> p
                    resid = jnp.where(resid_sum > 1e-12, resid, p_at_a)
                    dist = jnp.where((accepted == gamma)[:, None], p_at_a, resid)
                    keys, subs = _lane_split(keys)
                    corr_sample = jax.vmap(jax.random.categorical)(
                        subs, jnp.log(dist + 1e-30)
                    ).astype(jnp.int32)
                    correction = jnp.where(stoch, corr_sample, corr_greedy)
                else:
                    correction = corr_greedy

                cols = jnp.arange(gamma + 1, dtype=jnp.int32)[None, :]
                drafts_padded = jnp.concatenate(
                    [drafts_arr, jnp.zeros((self.slots, 1), jnp.int32)], axis=1
                )
                out = jnp.where(cols < accepted[:, None], drafts_padded, 0)
                out = jnp.where(cols == accepted[:, None], correction[:, None], out)
                count = jnp.where(active, accepted + 1, 0)
                out = jnp.where(active[:, None], out, 0)
                cur_tok = jnp.where(active, correction, cur_tok)
                pos = jnp.where(active, pos + accepted + 1, pos)
                return ks, vs, dks, dvs, cur_tok, pos, keys, out, count

            def spec_burst(
                params, dparams, caches, cur_tok, pos, active, temps, keys,
                k, attn_len, any_stoch,
            ):
                """k speculation rounds as one executable. ``any_stoch``
                (static) compiles the greedy-only variant without the
                q/p softmaxes + sampling when every lane is greedy. Returns
                (start_tok [S], toks [k, S, gamma+1], counts [k, S], ...)."""

                def body(carry, _):
                    ks, vs, dks, dvs, cur_tok, pos, keys = carry
                    ks, vs, dks, dvs, cur_tok, pos, keys, out, count = spec_round(
                        params, dparams, ks, vs, dks, dvs, cur_tok, pos,
                        active, temps, keys, attn_len, any_stoch,
                    )
                    return (ks, vs, dks, dvs, cur_tok, pos, keys), (out, count)

                start_tok = cur_tok
                (ks, vs, dks, dvs, cur_tok, pos, keys), (toks, counts) = lax.scan(
                    body,
                    (caches["k"], caches["v"], caches["dk"], caches["dv"],
                     cur_tok, pos, keys),
                    None,
                    length=k,
                )
                new_caches = {"k": ks, "v": vs, "dk": dks, "dv": dvs}
                return start_tok, toks, counts, cur_tok, pos, keys, new_caches

            self._spec_burst_fn = jax.jit(
                spec_burst, donate_argnums=(2,), static_argnums=(8, 9, 10)
            )

            def draft_prefill(dparams, prompt, last_index):
                # the draft only needs its K/V prefix; its own next-token
                # guess is irrelevant (the first emitted token comes from
                # the TARGET prefill, and round drafting restarts from it)
                _logits, cache_one = draft.prefill(
                    dparams, prompt, prompt.shape[1], last_index=last_index
                )
                return cache_one

            def draft_insert(dcache, cache_one, slot):
                return {
                    name: [
                        lax.dynamic_update_slice(
                            layer, cache_one[src][l], (slot, 0, 0, 0)
                        )
                        for l, layer in enumerate(dcache[name])
                    ]
                    for name, src in (("k", "k"), ("v", "v"))
                }

            self._draft_prefill_fn = jax.jit(draft_prefill)
            self._draft_insert_fn = jax.jit(draft_insert, donate_argnums=(0,))

    # -- public api ----------------------------------------------------------

    def observed_rate(self) -> Optional[float]:
        """Finished requests per second over the recent completion window
        (None until two completions exist — never shed blind)."""
        times = list(self._finish_times)
        if len(times) < 2:
            return None
        span = times[-1] - times[0]
        if span <= 0:
            return None
        return (len(times) - 1) / span

    def slo_summary(self) -> Optional[Dict[str, Any]]:
        """Percentile summary (ms) of the recent completed-request SLO
        reservoir: queue wait, TTFT, TPOT. None until a request completes."""
        samples = list(self.slo_recent)
        if not samples:
            return None

        def pct(vals: List[float]) -> Dict[str, float]:
            vals = sorted(vals)
            n = len(vals)
            return {
                "p50_ms": round(vals[n // 2] * 1e3, 3),
                "p99_ms": round(vals[min(n - 1, int(n * 0.99))] * 1e3, 3),
                "mean_ms": round(sum(vals) / n * 1e3, 3),
            }

        # single-token completions carry tpot=None (no inter-token
        # interval exists) — excluded here exactly as the TIMER export
        # excludes them, so /prometheus and this summary agree
        tpots = [s[2] for s in samples if s[2] is not None]
        return {
            "samples": len(samples),
            "queue_wait_ms": pct([s[0] for s in samples]),
            "ttft_ms": pct([s[1] for s in samples]),
            "tpot_ms": pct(tpots) if tpots else None,
        }

    @caller_thread
    def _shed_check(
        self, deadline_s: Optional[float], remote: bool = False
    ) -> None:
        """Admit-queue shedding, BEFORE the request costs any device work:
        the HBM-pressure admission watermark, an explicit queue cap, and
        the deadline-aware rule (expected queue wait = depth / observed
        completion rate > remaining budget).

        The pressure rung is the ladder's last resort — it only fires
        while the ledger is latched over the high watermark. ``remote``
        selects the typed refusal: a local submit sheds with the PR 2
        :class:`~..resilience.ShedError` (429 + Retry-After); a remote
        admit refuses with :class:`~.pressure.PressureRefused` (503 +
        Retry-After) so a decode pool under pressure pushes back to its
        prefill peers BEFORE a slab crosses the wire, instead of
        half-admitting it."""
        pc = self._pressure
        if pc.budget_bytes > 0 and pc.active:
            after = pc.retry_after_s()
            if remote:
                from .pressure import PressureRefused

                self.stats["pressure_refused"] += 1
                self._note_shed("pressure", self._queue.qsize(), None)
                raise PressureRefused(
                    f"decode pool over its HBM ledger high watermark "
                    f"({pc.used} of {pc.budget_bytes} bytes); refusing "
                    "remote admits until reclaim reaches the low "
                    "watermark",
                    retry_after_s=after,
                )
            from ..resilience import ShedError

            self.stats["shed"] += 1
            self.stats["pressure_sheds"] += 1
            self._note_shed("pressure", self._queue.qsize(),
                            self.observed_rate())
            raise ShedError(
                f"HBM ledger over its high watermark ({pc.used} of "
                f"{pc.budget_bytes} bytes) — admissions shed until the "
                "reclaim ladder reaches the low watermark",
                retry_after_s=after,
            )
        depth = self._queue.qsize()
        if self.admit_queue_limit and depth >= self.admit_queue_limit:
            from ..resilience import ShedError

            rate = self.observed_rate()
            self.stats["shed"] += 1
            self._note_shed("queue_full", depth, rate)
            raise ShedError(
                f"admit queue full ({depth} >= {self.admit_queue_limit})",
                retry_after_s=(depth / rate) if rate else 1.0,
            )
        if deadline_s is None or depth == 0:
            return
        rate = self.observed_rate()
        if rate is None:
            return
        est_wait = depth / rate
        if est_wait > deadline_s:
            from ..resilience import ShedError

            self.stats["shed"] += 1
            self._note_shed("deadline", depth, rate)
            raise ShedError(
                f"deadline {deadline_s * 1000:.0f}ms below estimated queue "
                f"wait {est_wait * 1000:.0f}ms ({depth} queued at "
                f"{rate:.2f} req/s) — shed before work",
                retry_after_s=est_wait,
            )

    @caller_thread
    def _note_shed(self, reason: str, depth: int, rate: Optional[float]) -> None:
        """Flight-recorder + trace breadcrumbs for a shed decision (runs on
        the SUBMITTING thread, where the request's span is still active)."""
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "shed", "reason": reason, "queue": depth,
                "rate_per_s": round(rate, 3) if rate else None,
            })
        from ..tracing import get_tracer

        tracer = get_tracer()
        if tracer.enabled:
            parent = tracer.active_span()
            if parent is not None and parent.trace_id != "0":
                # monotonic-anchored timestamp: a raw time.time() here
                # could disorder the shed breadcrumb against the sibling
                # spans' anchored clocks under an NTP step
                tracer.record_span(
                    "gen.shed", parent.trace_id, parent.span_id,
                    wall_us(), 0,
                    tags={"reason": reason, "queue_depth": depth},
                )

    def _dead_error(self) -> BatcherDead:
        """The typed refusal every entrypoint raises once the scheduler
        is gone — BatcherDead carries retry_after_s so the engine answers
        503 + Retry-After instead of an opaque 500."""
        if self.health == "closed":
            return BatcherDead("batcher is closed", retry_after_s=1.0)
        if self.health == "dead":
            return BatcherDead(
                "continuous batcher died and exhausted its crash-loop "
                "budget; this member stays unready until the control "
                "plane replaces it",
                retry_after_s=5.0,
            )
        return BatcherDead(
            "continuous batcher died; see server log", retry_after_s=5.0
        )

    def _check_alive(self) -> None:
        # the health latch is checked alongside _stop: _crash_recover
        # writes health="dead" a few instructions before it sets _stop,
        # and an entrypoint landing in that window must still refuse
        # (drain() in particular must never overwrite the dead latch)
        if self._stop.is_set() or self.health in ("dead", "closed"):
            raise self._dead_error()
        if self.health == "draining":
            # a draining member refuses new work typed (503 +
            # Retry-After) so the gateway/engine routes the retry at a
            # peer; in-flight work is being checkpointed and handed
            # over, not dropped
            raise BatcherDead(
                "batcher is draining for migration; retry another member",
                retry_after_s=1.0,
            )

    def _check_budget(self, prompt_len: int, max_new_tokens) -> None:
        """Reject ``prompt_len + max_new_tokens > max_seq`` at the
        boundary with a typed :class:`BudgetExceeded` (413-class).
        Historically the overrun was silently clamped to the remaining
        headroom — a client asking for 512 tokens got 40 with no signal
        — and anything slipping past surfaced deep in the scheduler as
        an opaque shape error."""
        m = int(max_new_tokens)
        if prompt_len + m > self.max_seq:
            raise BudgetExceeded(
                f"prompt of {prompt_len} + max_new_tokens {m} exceeds "
                f"max_seq {self.max_seq}; raise max_seq or lower the "
                "generation budget"
            )

    @caller_thread
    def submit(
        self,
        tokens: Sequence[int],
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        seed: int = 0,
        on_tokens=None,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        slo: str = "standard",
    ) -> Future:
        self._check_alive()
        if not len(tokens):
            raise ValueError("empty prompt")
        if len(tokens) >= self.max_seq:
            raise PromptTooLong(
                f"prompt of {len(tokens)} exceeds max_seq {self.max_seq}"
            )
        self._check_budget(len(tokens), max_new_tokens)
        self._shed_check(deadline_s)
        req = GenRequest(
            tokens=list(map(int, tokens)),
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            eos_id=eos_id,
            seed=int(seed),
            on_tokens=on_tokens,
            tenant=tenant,
            slo=str(slo or "standard"),
        )
        req.submit_t = time.monotonic()
        if deadline_s is not None:
            req.deadline_t = req.submit_t + float(deadline_s)
        req.submit_wall_us = wall_us(req.submit_t)
        # capture the submitting thread's sampled trace context so the
        # scheduler thread can parent this request's timeline spans under
        # the serving span (the engine's graph-hop span, propagated into
        # this thread by InProcessClient's context copy). The unsampled
        # sentinel carries trace_id "0" and is skipped — a dropped
        # request must not grow retroactive span fragments.
        from ..tracing import get_tracer

        tracer = get_tracer()
        if tracer.enabled:
            parent = tracer.active_span()
            if parent is not None and parent.trace_id != "0":
                req.trace = (parent.trace_id, parent.span_id)
        # callers read per-request admit metadata (cache_hit_tokens) off
        # the future after it resolves
        req.future.gen_request = req
        self._queue.put(req)
        if self._stop.is_set():
            # the loop died between the entry check and the put: its drain
            # already ran, so nothing will ever pop this request — fail the
            # stranded queue here instead of leaving the future unresolved
            self._drain_queue(self._dead_error())
            return req.future
        self.start()
        return req.future

    @caller_thread
    def generate(self, tokens, **kw) -> List[int]:
        """Blocking convenience: submit and wait for the generated ids."""
        return self.submit(tokens, **kw).result()

    # -- disaggregated serving (prefill/decode pools, KV-slab handoff) -----

    @property
    def _slab_token_bytes(self) -> int:
        """K+V bytes one prompt position occupies across every layer —
        the per-token unit the transfer-dedup accounting is priced in."""
        return self._kv_key_bytes

    @caller_thread
    def export_prefill(
        self,
        tokens: Sequence[int],
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        seed: int = 0,
        covered_len: int = 0,
    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """PREFILL-side half of disaggregation: run the prompt forward
        and return ``(meta, slab)`` — the host-side ``cache_one`` K/V
        stack plus everything a decode pool needs to splice it as a
        remote lane insert (first sampled token, post-split RNG lane
        key, weight version, sampling params).

        Reuses PR 3's staging-slab path: with ``prefill_chunk`` set and
        a multi-chunk bucket the slab is built chunk by chunk exactly
        like an interleaved admission (no decode lanes are touched —
        this method never requires the scheduler loop, which a
        prefill-role server does not run); otherwise the ordinary
        bucketed whole-prompt prefill produces it in one forward. The
        first token is sampled on THIS side with the same PRNG
        derivation an admission uses, so disaggregated greedy output is
        byte-identical to unified serving.

        ``covered_len`` > 0 (the decode side's radix prefix cache
        already holds that many leading tokens) slices the transfer down
        to the suffix columns — the K/V is still computed here (a full
        prefill is the only way to produce correct suffix K/V without
        the donor slab), but only ``bucket - covered_len`` positions
        cross the wire and ``kv_transfer_bytes_saved`` records the
        dedup."""
        import jax.numpy as jnp

        from ..tracing import device_trace
        from .disagg import prompt_hash

        self._check_alive()
        n = len(tokens)
        if not n:
            raise ValueError("empty prompt")
        if n >= self.max_seq:
            raise PromptTooLong(
                f"prompt of {n} exceeds max_seq {self.max_seq}"
            )
        self._check_budget(n, max_new_tokens)
        tokens = [int(t) for t in tokens]
        bucket = self._bucket(n)
        covered = max(0, min(int(covered_len), n - 1))
        C = self.prefill_chunk
        chunks = 0
        if C and bucket > C:
            # the staging path: one _chunk_fn slice at a time, same
            # offsets/slide-back as _advance_chunks, final slice samples
            slab = self._new_slab(bucket)
            first = key = None
            start = 0
            while True:
                is_last = start + C >= n
                s = max(0, min(start, bucket - C)) if is_last else start
                end = min(s + C, n)
                buf = np.zeros((1, C), np.int32)
                buf[0, : end - s] = tokens[s:end]
                attn_len = min(bucket, self._attn_need(s + C))
                with self._prof.measure(
                    "chunk_prefill", variant=f"b{bucket}",
                    bytes_read=self._param_bytes + C * self._kv_key_bytes,
                    tokens=C,
                ) as _m, device_trace("gen.prefill_chunk"):
                    slab, first, key = self._chunk_fn(
                        self.params, slab, jnp.asarray(buf),
                        jnp.int32(s), jnp.int32(n - 1 - s),
                        jnp.int32(seed), jnp.float32(temperature),
                        attn_len, is_last,
                    )
                    _m.sync(slab)
                chunks += 1
                if is_last:
                    break
                start = end
            cache_one, first_tok = slab, first
        else:
            prompt = np.zeros((1, bucket), np.int32)
            prompt[0, :n] = tokens
            with self._prof.measure(
                "prefill", variant=f"p{bucket}",
                bytes_read=self._param_bytes + bucket * self._kv_key_bytes,
                tokens=bucket,
            ) as _m, device_trace("gen.prefill"):
                first, cache_one, key = self._prefill_fn(
                    self.params, jnp.asarray(prompt),
                    jnp.asarray([n - 1], jnp.int32),
                    jnp.int32(seed), jnp.float32(temperature),
                )
                _m.sync(cache_one)
            first_tok = first[0]
        # host pull IS the export (the slab must cross a transport);
        # suffix-only when the decode side already holds the prefix
        k = np.asarray(cache_one["k"])
        v = np.asarray(cache_one["v"])
        if self._kv_tier is not None:
            # the FULL prompt slab is already host-side here — publishing
            # it into the tier costs one SKV1 encode and zero device
            # work, and makes this member's KV port answer peer
            # prefix-lookups for the prompt (cluster-wide sharing)
            if self._kv_tier.put_prefix(tokens, {"k": k, "v": v},
                                        self.weight_version):
                if self.flight is not None and self.flight.enabled:
                    self.flight.record({
                        "type": "kv_demote", "kind": "prefix",
                        "source": "export",
                        "tokens": n,
                        "phash": prompt_hash(tokens)[:8],
                        "bytes": int(k.nbytes) + int(v.nbytes),
                    })
        if covered:
            k = k[:, :, :, covered:, :]
            v = v[:, :, :, covered:, :]
        meta = {
            "tokens": tokens,
            "prompt_hash": prompt_hash(tokens),
            "n_tokens": n,
            "bucket": bucket,
            "covered_len": covered,
            "layout": "cache_one",
            "first_token": int(np.asarray(first_tok)),
            "rng_key": np.asarray(key).astype(np.uint32).tolist(),
            "weight_version": self.weight_version,
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "eos_id": eos_id,
            "seed": int(seed),
        }
        nbytes = int(k.nbytes) + int(v.nbytes)
        with self._export_lock:
            self.stats["kv_exports"] += 1
            self.stats["kv_export_bytes"] += nbytes
            self.stats["prefill_steps"] += max(1, chunks)
            self.stats["prefill_tokens"] += chunks * C if chunks else bucket
            self.stats["prefill_chunks"] += chunks
            # kv_transfer_bytes_saved is counted on the DECODE side only
            # (the pool whose radix cache made the dedup decision): the
            # exported series is direction-less, so counting the same
            # covered tokens here too would double the cluster-wide sum
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "kv_export",
                "tokens": n,
                "bucket": bucket,
                "covered_len": covered,
                "bytes": nbytes,
                "chunks": chunks,
                "weight_version": self.weight_version,
            })
        return meta, {"k": k, "v": v}

    @caller_thread
    def remote_covered_len(self, tokens: Sequence[int]) -> int:
        """DECODE-side consult before requesting a remote prefill: the
        longest locally cached prefix usable as the transfer-dedup base
        (0 = ask for the full slab). Applies the same usability caps as
        a local prefix-cache admit, so a nonzero answer is one
        admit_remote can actually splice."""
        if self._prefix_index is None:
            return 0
        tokens = [int(t) for t in tokens]
        n = len(tokens)
        m, slab = self._prefix_index.match(tokens)
        m = min(m, n - 1)
        if slab is None or m < self.prefix_cache_min_tokens:
            return 0
        if slab["k"].shape[3] > self._bucket(n):
            return 0  # donor wider than the prompt bucket: not a win
        return m

    @caller_thread
    def admit_remote(
        self,
        slab: Dict[str, Any],
        meta: Dict[str, Any],
        on_tokens=None,
        deadline_s: Optional[float] = None,
    ) -> Future:
        """DECODE-side half of disaggregation: validate a shipped slab
        message, upload it, and queue it as a remote lane insert —
        spliced by the scheduler thread through the SAME insert
        executables an ordinary admission uses, so decode after a remote
        admit is byte-identical to unified serving.

        Rejections are typed and happen BEFORE any lane state exists:
        weight-version mismatch (a hot-swap landed between prefill and
        admit) raises :class:`~.disagg.WeightVersionMismatch`; a
        shape/dtype/layout mismatch raises :class:`~.disagg.DisaggError`;
        a suffix-only slab whose local donor prefix was evicted raises
        :class:`~.disagg.PrefixGone` at insert time (the caller retries
        with ``covered_len=0``). Returns the request Future, exactly
        like :meth:`submit`."""
        import jax.numpy as jnp

        from .disagg import DisaggError, PrefixGone, WeightVersionMismatch
        from .disagg import prompt_hash as _phash

        self._check_alive()
        if self.speculate_tokens > 0:
            raise DisaggError(
                "remote admits are not supported with speculative "
                "decoding (the draft cache has no prefix for the lane)"
            )
        tokens = [int(t) for t in meta.get("tokens") or []]
        if not tokens:
            raise DisaggError("slab meta carries no prompt tokens")
        n = len(tokens)
        if n >= self.max_seq:
            raise DisaggError(
                f"remote prompt of {n} exceeds max_seq {self.max_seq}"
            )
        self._check_budget(n, meta.get("max_new_tokens", 32))
        if meta.get("prompt_hash") and meta["prompt_hash"] != _phash(tokens):
            raise DisaggError("slab prompt hash mismatch — corrupt meta")
        if meta.get("layout", "cache_one") != "cache_one":
            raise DisaggError(
                f"unsupported slab layout {meta.get('layout')!r}"
            )
        if meta.get("weight_version") != self.weight_version:
            raise WeightVersionMismatch(
                f"slab prefilled under weight_version "
                f"{meta.get('weight_version')!r} but this decode pool "
                f"serves {self.weight_version!r}"
            )
        covered = max(0, int(meta.get("covered_len", 0)))
        if covered and self._prefix_index is None:
            raise PrefixGone(
                "suffix-only slab but this decode pool runs no prefix "
                "cache — re-request with covered_len=0"
            )
        self._shed_check(deadline_s, remote=True)
        cfg = self.model.cfg
        k = np.asarray(slab["k"])
        v = np.asarray(slab["v"])
        bucket = self._bucket(n)
        want = (cfg.n_layers, 1, cfg.n_kv_heads, bucket - covered,
                cfg.head_dim)
        if tuple(k.shape) != want or tuple(v.shape) != want:
            raise DisaggError(
                f"slab shape {tuple(k.shape)} does not match the serving "
                f"model's {want} (prompt {n} -> bucket {bucket}, "
                f"covered {covered})"
            )
        dt = jnp.dtype(getattr(self.model, "compute_dtype", cfg.dtype))
        if str(k.dtype) != str(dt):
            raise DisaggError(
                f"slab dtype {k.dtype} vs serving compute dtype {dt} — "
                "prefill and decode pools must share a dtype"
            )
        if meta.get("first_token") is None:
            raise DisaggError("slab meta carries no first_token")
        key_arr = np.asarray(meta.get("rng_key", [0, 0]), np.uint32)
        req = GenRequest(
            tokens=tokens,
            max_new_tokens=int(meta.get("max_new_tokens", 32)),
            temperature=float(meta.get("temperature", 0.0)),
            eos_id=meta.get("eos_id"),
            seed=int(meta.get("seed", 0)),
            on_tokens=on_tokens,
        )
        req.submit_t = time.monotonic()
        if deadline_s is not None:
            req.deadline_t = req.submit_t + float(deadline_s)
        req.submit_wall_us = wall_us(req.submit_t)
        req.cache_hit_tokens = covered
        from ..tracing import get_tracer

        tracer = get_tracer()
        if tracer.enabled:
            parent = tracer.active_span()
            if parent is not None and parent.trace_id != "0":
                req.trace = (parent.trace_id, parent.span_id)
        # device upload happens HERE, on the caller thread: the H2D copy
        # overlaps whatever burst the scheduler is running (pre-sharded
        # under a mesh — wire bytes stay layout-independent, the shards
        # form on upload)
        req.remote = {
            "slab": self._upload_slab({"k": k, "v": v}),
            "first": int(meta["first_token"]),
            "key": jnp.asarray(key_arr),
            "covered": covered,
            "nbytes": int(k.nbytes) + int(v.nbytes),
            "version": meta.get("weight_version"),
        }
        req.future.gen_request = req
        self._queue.put(req)
        if self._stop.is_set():
            self._drain_queue(self._dead_error())
            return req.future
        self.start()
        return req.future

    # -- live-lane migration (graceful drain + wire-checkpoint resume) -----

    @caller_thread
    def drain(self, timeout_s: float = 30.0) -> List[GenRequest]:
        """Graceful drain: checkpoint every live lane at the next poll
        boundary (the same preemption machinery PR 9 built — emitted
        tokens + post-split RNG lane key + sampling params, NOT the
        K/V), stop admissions (``health = "draining"``, new submits
        refuse typed 503), and return EVERY request this batcher still
        owes an answer for: checkpointed lanes (``req.resume`` set),
        mid-chunked-prefill admissions (requeued whole), the preemption
        resume queue, and queued-not-admitted requests. The caller
        (``GenerateServer.drain_to``) hands them to a peer via the SGC1
        codec; their futures stay pending until the peer answers —
        rolling maintenance drops zero requests.

        A dead/closed member has nothing drainable (its queued futures
        were already failed typed by the supervisor's drain), so the
        entry check's :class:`BatcherDead` propagates. A drain that
        outruns ``timeout_s`` is CANCELLED, not stranded: the scheduler
        observes the cancellation, keeps (or re-queues) the work, and
        restores ``health = "serving"`` so the member resumes normal
        service instead of latching draining forever."""
        self._check_alive()
        with self._drain_lock:
            if self._pending_drain is not None:
                raise RuntimeError("a drain is already in progress")
            # re-check under the lock: the supervisor writes the dead
            # latch without it, and overwriting "dead" with "draining"
            # would misreport a terminally dead member as mid-drain
            if self._stop.is_set() or self.health in ("dead", "closed"):
                raise self._dead_error()
            # refuse new admissions NOW (caller threads see it before
            # the scheduler reaches the poll boundary) — a request
            # admitted after this line would miss the checkpoint sweep
            self.health = "draining"
            job = _DrainJob()
            self._pending_drain = job
        self.start()
        from concurrent.futures import TimeoutError as _FuturesTimeout

        try:
            return job.future.result(timeout=timeout_s)
        except _FuturesTimeout:
            if not job.future.cancel():
                # the scheduler is resolving the drain RIGHT NOW (the
                # future is running/done): take the result after a
                # short grace instead of abandoning checkpointed work
                return job.future.result(timeout=5.0)
            # cancelled before the scheduler started it: the next poll
            # clears the latch and resumes admissions (_do_drain's
            # set_running_or_notify_cancel branch)
            raise RuntimeError(
                f"drain did not complete within {timeout_s}s; cancelled "
                "— admissions resume on the next poll"
            )

    @caller_thread
    def submit_checkpoint(self, ck: Dict[str, Any], on_tokens=None) -> Future:
        """Admit a wire checkpoint (an SGC1 dict — a drained peer's
        lane, or a client resume token) and continue the generation
        exactly where it stopped: the scheduler resumes it through
        :meth:`_admit_resume` (prompt K/V recompute + teacher-forced
        replay of the emitted tokens), so greedy AND seeded-sampling
        output is byte-identical to an uninterrupted run and crediting
        continues after the checkpoint (already-delivered stream spans
        are never re-sent).

        Typed refusals, all BEFORE any lane state exists: a checkpoint
        from another ``weight_version`` raises
        :class:`~.disagg.WeightVersionMismatch` (its emitted prefix is
        not reproducible under these weights); over-long prompts and
        budget overruns raise the same 413-class errors ``submit``
        does. The checkpoint's cumulative wait anchor re-bases
        ``submit_t`` so queue-wait telemetry spans both members."""
        from .disagg import WeightVersionMismatch

        self._check_alive()
        wv = ck.get("weight_version")
        if wv is not None and wv != self.weight_version:
            raise WeightVersionMismatch(
                f"checkpoint was taken under weight_version {wv!r} but "
                f"this member serves {self.weight_version!r} — its "
                "emitted prefix is not reproducible here"
            )
        tokens = [int(t) for t in ck.get("prompt") or []]
        if not tokens:
            raise ValueError("checkpoint carries no prompt tokens")
        if len(tokens) >= self.max_seq:
            raise PromptTooLong(
                f"checkpoint prompt of {len(tokens)} exceeds max_seq "
                f"{self.max_seq}"
            )
        mnt = int(ck.get("max_new_tokens", 32))
        self._check_budget(len(tokens), mnt)
        emitted = [int(t) for t in ck.get("emitted") or []]
        if len(emitted) > mnt:
            raise ValueError(
                f"checkpoint emitted {len(emitted)} tokens past its "
                f"max_new_tokens {mnt}"
            )
        req = GenRequest(
            tokens=tokens,
            max_new_tokens=mnt,
            temperature=float(ck.get("temperature", 0.0)),
            eos_id=ck.get("eos_id"),
            seed=int(ck.get("seed", 0)),
            on_tokens=on_tokens,
        )
        now = time.monotonic()
        # cumulative queue-wait anchor: the time the request already
        # waited on the source member rides the checkpoint, so the
        # queue-wait histogram sees source wait + local wait instead of
        # restarting the clock at migration
        wait_s = max(0.0, float(ck.get("wait_s") or 0.0))
        req.submit_t = now - wait_s
        req.submit_wall_us = (
            int(ck.get("submit_wall_us") or 0) or wall_us(req.submit_t)
        )
        dl = ck.get("deadline_s")
        if dl is not None:
            req.deadline_t = now + max(0.0, float(dl))
        if emitted and (
            len(emitted) >= mnt
            or (req.eos_id is not None and emitted[-1] == req.eos_id)
        ):
            # the checkpoint is already COMPLETE (a final-state resume
            # token): nothing is left to decode, so answer host-side
            # without occupying a lane — re-admitting it would append
            # one overshoot token before the done check could fire
            req.future.gen_request = req
            req.future.set_result(tokens + emitted)
            with self._export_lock:
                self.stats["migrated_resumes"] += 1
            return req.future
        if emitted:
            key = ck.get("rng_key")
            if key is None:
                # crash tokens ship keyless (reading the lane key per
                # span would cost a host sync per span): re-derive it
                # from the deterministic split chain
                from .migration import derive_lane_key

                key = derive_lane_key(req.seed, len(emitted))
            req.resume = {
                "emitted": emitted, "key": [int(k) for k in key],
            }
        with self._export_lock:
            self.stats["migrated_resumes"] += 1
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "migrated_resume",
                "tokens": len(tokens),
                "emitted": len(emitted),
                "weight_version": self.weight_version,
            })
        req.future.gen_request = req
        self._queue.put(req)
        if self._stop.is_set():
            self._drain_queue(self._dead_error())
            return req.future
        self.start()
        return req.future

    @caller_thread
    def request_weight_swap(self, params, version=None) -> Future:
        """Stage a live weight hot-swap; returns a Future resolving to
        the new weight version once the scheduler flips.

        Thread-safe, callable under traffic. The new params are cast to
        the serving compute dtype, validated leaf-for-leaf against the
        served set (same tree / shapes / dtypes — the jitted executables
        are specialized on them, so an incompatible checkpoint is
        REJECTED here instead of retracing mid-traffic), device-put
        (sharded when meshed) — i.e. double-buffered next to the live
        weights, the upload overlapping serving. The scheduler then:

        * stops admitting new requests (queued submits wait),
        * lets every in-flight lane — decode, chunked prefill, pipelined
          burst — finish on the OLD version,
        * flips the param pointer at the next poll boundary, bumps
          ``weight_version``, purges the prefix cache (its slabs are
          keyed by weight version — stale K/V can never splice into a
          new-weights prefill), records a flight-recorder
          ``weight_swap`` event, and resumes admissions on the new
          weights.
        """
        import jax
        import jax.numpy as jnp

        self._check_alive()
        if self.speculate_tokens > 0:
            raise RuntimeError(
                "weight hot-swap is not supported with speculative decoding "
                "(the draft shares or derives from the served params)"
            )
        dt = jnp.dtype(getattr(self.model, "compute_dtype", "bfloat16"))
        if dt != jnp.float32:
            with self._prof.measure(
                "swap_cast", variant=str(dt),
                bytes_read=self._param_bytes,
            ) as _m:
                params = jax.tree_util.tree_map(
                    lambda a: a.astype(dt)
                    if hasattr(a, "dtype") and a.dtype == jnp.float32
                    else a,
                    params,
                )
                _m.sync(params)
        from ..models.llm import DecoderLM

        check = getattr(self.model, "params_swappable", None)
        if check is None:
            check = DecoderLM.params_swappable
        ok, why = check(self.params, params)
        if not ok:
            raise ValueError(f"weight hot-swap rejected: {why}")
        if self.mesh is not None:
            params = jax.device_put(
                params, self.model.param_sharding(self.mesh, params)
            )
        with self._swap_lock:
            if self._pending_swap is not None:
                raise RuntimeError("a weight swap is already pending")
            if version is None:
                self._swap_seq += 1
                if self._swap_seq == self.weight_version:
                    self._swap_seq += 1
                version = self._swap_seq
            elif version == self.weight_version:
                # a flip that keeps the version number would leave the
                # version-keyed prefix cache holding OLD-weights K/V that
                # still matches — the exact splice the keying exists to
                # prevent
                raise ValueError(
                    f"weight swap version {version!r} is already the "
                    "served version; pick a new version id"
                )
            job = _SwapJob(
                params=params,
                version=version,
                nbytes=sum(
                    leaf.nbytes
                    for leaf in jax.tree_util.tree_leaves(params)
                    if hasattr(leaf, "nbytes")
                ),
                staged_t=time.monotonic(),
            )
            self._pending_swap = job
        # the loop must be alive to execute the swap, traffic or not
        self.start()
        return job.future

    def swap_pending(self) -> bool:
        """Whether a staged weight swap is awaiting its drain — callers
        about to pay a full checkpoint load (GenerateServer.hot_swap) can
        fail fast instead of discovering the conflict afterwards. The
        authoritative check stays inside request_weight_swap."""
        return self._pending_swap is not None

    @caller_thread
    def cancel_weight_swap(self) -> bool:
        """Abort a staged-but-not-yet-executed weight swap, resuming
        admissions on the next poll. The escape hatch for a drain that
        cannot converge (e.g. a stalled streaming consumer holding a
        lane open with no deadline): without it the staged job would
        hold every admission until close(). Returns True when a pending
        swap was cancelled; False when none was pending (including a
        swap that already flipped)."""
        with self._swap_lock:
            swap, self._pending_swap = self._pending_swap, None
        if swap is None:
            return False
        if not swap.future.done():
            swap.future.set_exception(
                RuntimeError("weight swap cancelled before the flip")
            )
        return True

    # knobs retune() accepts; everything else (slots, steps_per_poll,
    # speculate_tokens, cache geometry) would invalidate compiled
    # executables or reallocate device state and is refused typed
    RETUNABLE_KNOBS = (
        "fused_steps_per_dispatch", "depth_groups",
        "depth_group_split_bytes", "prefill_chunk", "pipeline_depth",
        "admit_queue_limit", "pressure_high", "pressure_low",
    )

    def retune_census(self) -> Dict[str, Any]:
        """The boot-time compile census a retune is validated against:
        which fused Ks warm() compiled, whether group-burst variants
        exist, the one chunk size with precompiled executables, and the
        warmed pipeline depth. The planner reads this to prune its
        search space to configs this member can actually flip to."""
        return dict(self._retune_census)

    def serving_config(self) -> Dict[str, Any]:
        """The CURRENT values of the profile-grid config axes
        (planning/artifact.py CONFIG_KEYS) — unlike the boot census
        these move with every applied retune. The planner diffs the
        cost model's pick against this to decide whether a retune is
        even needed."""
        return {
            "slots": int(self.slots),
            "prefill_chunk": int(self.prefill_chunk or 0),
            "fused_steps_per_dispatch": int(
                self.fused_steps_per_dispatch or 0
            ),
            "depth_groups": int(self.depth_groups or 0),
            "depth_group_split_bytes": int(self._group_split_bytes or 0),
            "kv_tier_bytes": int(
                getattr(self._kv_tier, "budget_bytes", 0) or 0
            ),
        }

    @caller_thread
    def retune(self, origin: str = "planner", **knobs) -> Future:
        """Stage a live retune of scheduler knobs; returns a Future
        resolving to ``{knob: [old, new]}`` for the knobs that actually
        changed once the scheduler applies the job at a poll boundary.

        Thread-safe, callable under traffic — the autonomic planner's
        ONE actuation path into the hot loop. Same staging discipline as
        swap/drain: nothing changes on the caller thread; the scheduler
        applies every knob together at the top of a poll, where no burst
        is mid-dispatch (the loop snapshots ``_fused_k`` once per poll)
        and — for a ``prefill_chunk`` change — only once in-flight
        chunked prefills have drained. Byte identity is preserved by
        construction: every retunable knob already carries an
        on-vs-off/byte-identity contract (fused decode, depth grouping,
        chunked prefill, pressure, admission caps), so a mid-run retune
        produces the same tokens as booting with the new values.

        Validation is synchronous and typed (:class:`RetuneError`):
        a value outside the boot compile census — a fused K warm() never
        compiled, depth grouping on a member booted without group
        variants, a chunk size with no precompiled chunk executables, a
        pipeline deepening past the warmed attention overhang — is
        refused HERE, before staging, so the scheduler can never be
        asked to compile mid-traffic.
        """
        self._check_alive()
        if not knobs:
            raise RetuneError("retune called with no knobs")
        unknown = set(knobs) - set(self.RETUNABLE_KNOBS)
        if unknown:
            raise RetuneError(
                f"unknown/unretunable knob(s) {sorted(unknown)}; "
                f"retunable: {list(self.RETUNABLE_KNOBS)}"
            )
        census = self._retune_census
        target: Dict[str, Any] = {}

        def _int(name, lo=0):
            try:
                v = int(knobs[name])
            except (TypeError, ValueError):
                raise RetuneError(
                    f"{name} must be an int, got {knobs[name]!r}"
                ) from None
            if v < lo:
                raise RetuneError(f"{name} must be >= {lo}, got {v}")
            return v

        if "fused_steps_per_dispatch" in knobs:
            raw = _int("fused_steps_per_dispatch")
            fk = raw
            while fk & (fk - 1):
                fk &= fk - 1
            if fk > 0 and self._spec_burst_fn is not None:
                raise RetuneError(
                    "fused decode cannot be enabled under speculative "
                    "decoding (no fused executables exist in spec mode)"
                )
            if fk > 0 and fk not in census["fused_ks"]:
                raise RetuneError(
                    f"fused_steps_per_dispatch={raw} (pow2 floor {fk}) "
                    f"is outside the boot compile census "
                    f"{list(census['fused_ks'])}; only warmed Ks (or 0) "
                    "can be retuned to"
                )
            target["fused_steps_per_dispatch"] = (raw, fk)
        if "depth_groups" in knobs:
            dg = _int("depth_groups")
            if dg > 1 and census["depth_groups"] <= 1:
                raise RetuneError(
                    "depth_groups>1 requires group-burst variants, which "
                    "warm() only compiles when the member boots with "
                    "depth_groups>1"
                )
            target["depth_groups"] = dg
        if "depth_group_split_bytes" in knobs:
            # pure host-side cost-model parameter: no executable depends
            # on it, any non-negative value is in census
            target["depth_group_split_bytes"] = _int(
                "depth_group_split_bytes"
            )
        if "prefill_chunk" in knobs:
            pc = _int("prefill_chunk")
            if pc not in (0, census["prefill_chunk"]):
                raise RetuneError(
                    f"prefill_chunk={pc} has no precompiled chunk "
                    f"executables; census allows 0 or "
                    f"{census['prefill_chunk']}"
                )
            target["prefill_chunk"] = pc
        if "pipeline_depth" in knobs:
            pd = _int("pipeline_depth", lo=1)
            if pd > census["pipeline_depth"]:
                raise RetuneError(
                    f"pipeline_depth={pd} exceeds the warmed depth "
                    f"{census['pipeline_depth']} (warm()'s attention "
                    "overhang only covered the boot depth)"
                )
            target["pipeline_depth"] = pd
        if "admit_queue_limit" in knobs:
            target["admit_queue_limit"] = _int("admit_queue_limit")
        if "pressure_high" in knobs or "pressure_low" in knobs:
            try:
                high = float(knobs.get(
                    "pressure_high", self._pressure.high_frac
                ))
                low = float(knobs.get(
                    "pressure_low", self._pressure.low_frac
                ))
            except (TypeError, ValueError):
                raise RetuneError(
                    "pressure watermarks must be floats"
                ) from None
            if not (0.0 < high <= 1.0):
                raise RetuneError(
                    f"pressure_high {high} not in (0, 1]"
                )
            if not (0.0 < low <= high):
                raise RetuneError(
                    f"pressure_low {low} must be in (0, high={high}]"
                )
            target["pressure_high"] = high
            target["pressure_low"] = low
        with self._retune_lock:
            if self._pending_retune is not None:
                raise RetuneError("a retune is already pending")
            job = _RetuneJob(knobs=target, origin=str(origin))
            self._pending_retune = job
        # the loop must be alive to apply the job, traffic or not
        self.start()
        return job.future

    @scheduler_only
    def _do_retune(self, job: _RetuneJob) -> None:
        """Apply a staged retune (scheduler thread, poll boundary). Runs
        under ``_retune_lock`` for the same cancel-vs-apply atomicity as
        :meth:`_do_swap`. A job that changes ``prefill_chunk`` DEFERS
        while chunked prefills are in flight — their staged slabs and
        offsets were planned at the old chunk size."""
        with self._retune_lock:
            if self._pending_retune is not job:
                return
            new_pc = job.knobs.get("prefill_chunk")
            if (
                new_pc is not None
                and new_pc != self.prefill_chunk
                and self._chunked
            ):
                job.waited_polls += 1
                return
            changed: Dict[str, List[Any]] = {}

            def _apply(name, old, new, setter):
                if old != new:
                    changed[name] = [old, new]
                setter(new)

            for name, val in job.knobs.items():
                if name == "fused_steps_per_dispatch":
                    raw, fk = val
                    if self._fused_k != fk:
                        changed[name] = [self._fused_k, fk]
                        # device stop/budget registers re-upload before
                        # the next fused dispatch
                        self._fused_sync = False
                    self.fused_steps_per_dispatch = raw
                    self._fused_k = fk
                elif name == "depth_groups":
                    _apply(
                        name, self.depth_groups, val,
                        lambda v: setattr(self, "depth_groups", v),
                    )
                elif name == "depth_group_split_bytes":
                    _apply(
                        name, self._group_split_bytes, val,
                        lambda v: setattr(self, "_group_split_bytes", v),
                    )
                elif name == "prefill_chunk":
                    _apply(
                        name, self.prefill_chunk, val,
                        lambda v: setattr(self, "prefill_chunk", v),
                    )
                elif name == "pipeline_depth":
                    _apply(
                        name, self.pipeline_depth, val,
                        lambda v: setattr(self, "pipeline_depth", v),
                    )
                elif name == "admit_queue_limit":
                    _apply(
                        name, self.admit_queue_limit, val,
                        lambda v: setattr(self, "admit_queue_limit", v),
                    )
                elif name == "pressure_high":
                    _apply(
                        name, self._pressure.high_frac, val,
                        lambda v: setattr(self._pressure, "high_frac", v),
                    )
                elif name == "pressure_low":
                    _apply(
                        name, self._pressure.low_frac, val,
                        lambda v: setattr(self._pressure, "low_frac", v),
                    )
            self.stats["planner_retunes"] += 1
            if self.flight is not None and self.flight.enabled:
                self.flight.record({
                    "type": "planner_retune",
                    "origin": job.origin,
                    "changed": changed,
                    "waited_polls": job.waited_polls,
                })
            self._pending_retune = None
        if changed:
            logger.info(
                "planner retune (%s): %s (deferred %d polls)",
                job.origin,
                ", ".join(
                    f"{k} {o!r}->{n!r}" for k, (o, n) in changed.items()
                ),
                job.waited_polls,
            )
        if not job.future.done():
            job.future.set_result(changed)

    @caller_thread
    def cancel_retune(self) -> bool:
        """Abort a staged-but-not-yet-applied retune (e.g. a planner
        tick superseded by a newer decision before the poll boundary).
        Returns True when a pending job was cancelled."""
        with self._retune_lock:
            job, self._pending_retune = self._pending_retune, None
        if job is None:
            return False
        if not job.future.done():
            job.future.set_exception(
                RetuneError("retune cancelled before the poll boundary")
            )
        return True

    @scheduler_only
    def _do_swap(self, swap: _SwapJob) -> None:
        """Execute a drained swap (scheduler thread, poll boundary).

        The whole flip runs under ``_swap_lock`` so ``cancel_weight_swap``
        either lands BEFORE (pops the job — we see the mismatch and skip)
        or AFTER (pending is already None — cancel returns False); it can
        never fail the future of a swap that actually flipped. The flip
        is host-side pointer work, so the hold is short.
        """
        with self._swap_lock:
            if self._pending_swap is not swap:
                return  # cancelled between the drain check and here
            old_v = self.weight_version
            self.params = swap.params
            self.weight_version = swap.version
            # drop the boot-cast memo so the old buffer's last pin dies
            # with the pointer flip (double-buffering ends here)
            self._cast_memo.clear()
            if self._prefix_index is not None:
                purged = self._prefix_index.set_version(swap.version)
                self.stats["prefix_evicted"] += purged
                self.stats["prefix_cache_bytes"] = self._prefix_index.total_bytes
            if self._kv_tier is not None:
                # the tier's entries are OLD-weights K/V too: purge on
                # the same version key (a swap straggler's checkpoint
                # then replays on the new weights instead of splicing
                # stale cache — correct by construction)
                self._kv_tier.set_version(swap.version)
            self.stats["weight_swaps"] += 1
            if self.flight is not None and self.flight.enabled:
                self.flight.record({
                    "type": "weight_swap",
                    "old_version": old_v,
                    "new_version": swap.version,
                    "drained_lanes": swap.drain_lanes or 0,
                    "waited_polls": swap.waited_polls,
                })
            self._pending_swap = None
        logger.info(
            "weight swap %r -> %r (drained %d lanes over %d polls)",
            old_v, swap.version, swap.drain_lanes or 0, swap.waited_polls,
        )
        if not swap.future.done():
            swap.future.set_result(swap.version)

    @scheduler_only
    def _swap_preempt_stragglers(self, pending) -> None:
        """Hot-swap straggler bound: the drain has run past
        ``swap_drain_ms``, so preempt-checkpoint every in-flight lane
        (and chunked admission) instead of holding the flip hostage to
        one long generation. Policy ``"resume"`` requeues them — they
        resume AFTER the flip, on the NEW weights (an explicit identity
        trade the knob documents); ``"fail"`` refuses them typed
        (WeightVersionMismatch, 409-class) so the client re-submits
        under the new version knowingly."""
        self._drain_pending(pending)
        victims: List[GenRequest] = []
        for slot in sorted(self._chunked):
            victims.append(self._chunked.pop(slot).request)
        for slot in sorted(self._active):
            _s, req = self._checkpoint_lane(slot)
            victims.append(req)
        if not victims:
            return
        self.stats["swap_preemptions"] += len(victims)
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "swap_straggler_preempt",
                "lanes": len(victims),
                "policy": self.swap_resume_policy,
                "swap_drain_ms": self.swap_drain_ms,
            })
        logger.warning(
            "weight swap straggler bound hit after %dms: %d in-flight "
            "lane(s) preempt-checkpointed (policy=%s)",
            self.swap_drain_ms, len(victims), self.swap_resume_policy,
        )
        if self.swap_resume_policy == "fail":
            from .disagg import WeightVersionMismatch

            for req in victims:
                if req.resume is None:
                    # zero tokens emitted (chunked admission / fresh
                    # lane): there is no old-weights prefix to betray —
                    # a plain re-admit under the new weights reproduces
                    # its stream from the seed alone, so failing it
                    # would be a needless 409
                    self._resume_queue.append(req)
                elif not req.future.done():
                    req.future.set_exception(WeightVersionMismatch(
                        "generation preempted by a weight swap after "
                        f"swap_drain_ms={self.swap_drain_ms} and "
                        "swap_resume_policy=fail forbids resuming its "
                        "emitted prefix under the new weights; re-submit"
                    ))
        else:
            for req in victims:
                self._resume_queue.append(req)

    @scheduler_only
    def _do_drain(self, job: _DrainJob, pending) -> None:
        """Execute a staged graceful drain at this poll boundary:
        flush the pipeline (checkpoints must see exact host state),
        checkpoint every live lane, collect chunked admissions whole,
        then sweep the resume queue and the admit queue. Admissions are
        already refused (``health == "draining"`` flipped on the caller
        thread), so the collected list is complete. A job whose caller
        timed out and cancelled is aborted BEFORE any lane is touched —
        the latch clears and the member resumes serving with its work
        intact."""
        if not job.future.set_running_or_notify_cancel():
            # the drain() caller gave up (timeout): nothing was
            # checkpointed yet, so just un-latch and keep serving
            with self._drain_lock:
                if self._pending_drain is job:
                    self._pending_drain = None
            self.health = "serving"
            logger.warning(
                "graceful drain cancelled by its caller before the poll "
                "boundary; admissions resumed"
            )
            return
        # re-assert the latch: a supervised restart between staging and
        # this poll rewrote health back to "serving" — the member must
        # refuse new work from here on, or post-drain admissions would
        # be stranded when the caller tears it down
        self.health = "draining"
        try:
            self._drain_pending(pending)
            drained: List[GenRequest] = []
            n_lanes = n_ck = 0
            for slot in sorted(self._chunked):
                drained.append(self._chunked.pop(slot).request)
            n_chunked = len(drained)
            for slot in sorted(self._active):
                _s, req = self._checkpoint_lane(slot)
                n_lanes += 1
                if req.resume is not None:
                    n_ck += 1
                drained.append(req)
            while self._resume_queue:
                drained.append(self._resume_queue.popleft())
            while True:
                try:
                    drained.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            drained = [r for r in drained if not r.future.cancelled()]
            self.stats["drains"] += 1
            if self.flight is not None and self.flight.enabled:
                self.flight.record({
                    "type": "drain",
                    "lanes": n_lanes,
                    "checkpoints": n_ck,
                    "chunked": n_chunked,
                    "handed": len(drained),
                })
            logger.info(
                "graceful drain: %d lane(s) checkpointed (%d with "
                "emitted tokens), %d chunked, %d total requests handed "
                "to migration", n_lanes, n_ck, n_chunked, len(drained),
            )
            with self._drain_lock:
                self._pending_drain = None
            if not job.future.done():
                job.future.set_result(drained)
        except Exception as e:  # noqa: BLE001 - the drain caller must wake
            with self._drain_lock:
                self._pending_drain = None
            if not job.future.done():
                job.future.set_exception(e)

    def _fail_pending_drain(self, err: Exception) -> None:
        with self._drain_lock:
            job, self._pending_drain = self._pending_drain, None
        if job is not None and not job.future.done():
            job.future.set_exception(err)

    @scheduler_only
    def _alloc_device_state(self) -> None:
        """(Re)allocate everything the scheduler loop mutates on device:
        the unstacked per-layer KV cache (and the draft's), the per-lane
        token/position registers, and the per-lane PRNG streams (each
        request's sampling is seeded by ITS seed, folded in at admit, so
        results are reproducible no matter which other requests share the
        decode batch). Called by the constructor and by the supervisor
        after a loop death — the donating burst executables consumed the
        old buffers, so a restarted loop must never touch them."""
        import jax
        import jax.numpy as jnp

        self._cache = self._unstack_cache(
            self.model, self._cache_sharding_for(self.model.cfg.n_kv_heads)
        )
        if self.speculate_tokens > 0:
            self._draft_cache = self._unstack_cache(
                self.draft_model,
                self._cache_sharding_for(self.draft_model.cfg.n_kv_heads),
            )
        self._cur_tok = jnp.zeros((self.slots,), jnp.int32)
        self._pos = jnp.zeros((self.slots,), jnp.int32)
        self._keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(self.slots))
        # per-lane stop tokens (-1 = no eos, never matches) and remaining
        # token budgets for the stop-aware fused burst; the device
        # decrements its own budget copy per step, the host re-uploads
        # only on membership changes (_fused_sync)
        self._stops_dev = jnp.full((self.slots,), -1, jnp.int32)
        self._budget_dev = jnp.zeros((self.slots,), jnp.int32)
        self._fused_sync = False

    @scheduler_only
    def _rebuild(self) -> None:
        """Crash recovery (scheduler thread): fresh device state + a
        reset prefix index (its slabs referenced the invalidated cache
        stream's world — correctness never depends on the cache, so the
        safe reset only costs re-warming it), then the recorded ``warm()``
        re-precompile so the restarted loop serves its first admission
        without an XLA stall. Host-side lane bookkeeping is cleared by
        the caller's in-flight sweep before this runs."""
        self._active.clear()
        self._chunked.clear()
        self._pos_host.clear()
        self._masks_dirty = True
        self._active_dev = None
        self._temps_dev = None
        # _alloc_device_state rebuilds the draft cache, so a suppression
        # that was live at crash time is simply over; preempted requests
        # in the resume queue survive (their checkpoints are host-side)
        self._spec_suppressed = False
        self._alloc_device_state()
        if self._prefix_index is not None:
            from .prefix_cache import RadixPrefixIndex

            self._prefix_index = RadixPrefixIndex(self._prefix_cache_budget)
            self._prefix_index.set_version(self.weight_version)
            self.stats["prefix_cache_bytes"] = 0
        if self._warm_args is not None:
            self.warm(**self._warm_args)

    @caller_thread
    def start(self) -> None:
        if self._stop.is_set():
            raise BatcherDead(
                "batcher is closed" if self.health == "closed"
                else "continuous batcher is dead; see server log",
                retry_after_s=5.0,
            )
        with self._thread_lock:
            # check-then-act under a lock: two racing submits must not spawn
            # two scheduler threads over the same donated device state
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="continuous-batcher", daemon=True
                )
                self._thread.start()
        self._started.wait()

    def warm(
        self,
        prompt_lens: Sequence[int] = (),
        max_new_tokens: int = 0,
        batch_sizes: Sequence[int] = (1, 4, 8),
    ) -> None:
        """Pre-compile every executable the serving loop will need for the
        given traffic shape, BEFORE traffic arrives.

        jit executables compile lazily, so without this the first
        admission wave compiles the batched prefill, and every new
        attention-read bucket a deepening prefix crosses compiles a new
        burst — tens of seconds of stall landing mid-traffic. Warm runs
        each variant once on dummy inputs (donating executables get a
        throwaway same-shape cache) while the scheduler is idle; it must
        be called before the first submit() (the wrapper's
        warmup-before-listen phase).

        Mirrors the reference's model-warmup-before-ready pattern
        (readiness gating); compile-stall avoidance is the TPU-specific
        reason it is load-bearing here.
        """
        import jax
        import jax.numpy as jnp

        # remember the traffic shape so a supervised crash-restart can
        # re-run the exact same precompile before resuming admissions
        self._warm_args = {
            "prompt_lens": tuple(prompt_lens),
            "max_new_tokens": int(max_new_tokens),
            "batch_sizes": tuple(batch_sizes),
        }
        # clamp declared warmup lens to the cache length: an oversized
        # config entry warms the max_seq bucket rather than failing load()
        # with _bucket's too-long-REQUEST error (submit() still rejects
        # real prompts at the boundary)
        buckets = sorted({self._bucket(min(p, self.max_seq)) for p in prompt_lens})
        if not buckets:
            buckets = [self.prefill_buckets[0]]
        k = self._k
        # per-poll worst-case advance: spec rounds emit up to gamma+1
        # tokens each; a fused dispatch advances up to fused_steps (its
        # adaptive K never exceeds that)
        adv = max(
            k * (self.speculate_tokens + 1 if self._spec_burst_fn else 1),
            self._fused_k,
        )
        # attention buckets a run at these prompt lengths can touch: from
        # the shallowest first-burst prefix to the deepest end-of-budget.
        # eos-bearing lanes outlive their budget until the host OBSERVES
        # the stop — up to pipeline_depth-1 bursts of extra _pos_host
        # advance — so cover that overhang too
        lo = min(prompt_lens) if prompt_lens else 1
        hi = (
            (max(prompt_lens) if prompt_lens else 1)
            + max_new_tokens
            + adv * (1 + max(0, self.pipeline_depth - 1))
        )
        ab = self.attn_bucket
        attn_lens = sorted(
            {
                min(self.max_seq, -(-p // ab) * ab)
                for p in range(lo + adv, hi + 1, ab)
            }
            | {min(self.max_seq, -(-(hi) // ab) * ab)}
        )
        # Warm runs the donating executables against the LIVE cache and
        # threads the returned state back in, instead of allocating a
        # cache-sized throwaway per variant (at slots=32 / 1.26B that dummy
        # was a whole extra 3.2 GB of HBM at the peak — the difference
        # between the flagship throughput config fitting or OOMing). Safe
        # because lanes already tolerate residue: every readable position
        # of a lane is rewritten by its current occupant's insert + decode
        # steps before the mask can admit it (the same invariant that lets
        # lanes be reused across requests without scrubbing).
        for bucket in buckets:
            for m in batch_sizes:
                if m > 1 and self.speculate_tokens > 0:
                    continue  # spec mode admits singly
                if m > self.slots:
                    continue  # a wave can never exceed the lane pool
                if m == 8 and not self._chunk8_ok(bucket):
                    continue  # slab would not fit; admission won't use it
                prompts = jnp.zeros((m, bucket), jnp.int32)
                last = jnp.zeros((m,), jnp.int32)
                if m == 1:
                    first, cache_one, lane_key = self._prefill_fn(
                        self.params, prompts, last, jnp.int32(0), jnp.float32(0.0)
                    )
                    self._cache, self._cur_tok, self._pos, self._keys = (
                        self._insert_fn(
                            self._cache, cache_one, 0, first[0], 1, lane_key,
                            self._cur_tok, self._pos, self._keys,
                        )
                    )
                else:
                    firsts, slab, lane_keys = self._prefill_many_fn(
                        self.params, prompts, last,
                        jnp.zeros((m,), jnp.int32), jnp.zeros((m,), jnp.float32),
                    )
                    self._cache, self._cur_tok, self._pos, self._keys = (
                        self._insert_many_fn(
                            self._cache, slab, jnp.arange(m, dtype=jnp.int32),
                            firsts, last + 1, lane_keys,
                            self._cur_tok, self._pos, self._keys,
                        )
                    )
                # block so only one warm call is in flight at a time
                self._cache["k"][0].block_until_ready()  # seldon-lint: disable=host-sync-hot-path (warm precompile: intentional sync while the loop is idle)
                if self.speculate_tokens > 0:
                    dslab = self._draft_prefill_fn(
                        self._draft_params, prompts, last
                    )
                    self._draft_cache = self._draft_insert_fn(
                        self._draft_cache, dslab, 0
                    )
        if self.prefill_chunk > 0:
            # chunked-prefill executables: one per (bucket, chunk offset,
            # is_last) the declared prompt shapes can touch. A shorter
            # real prompt in the same bucket takes its final chunk at an
            # earlier offset, so BOTH variants compile at every offset.
            C = self.prefill_chunk
            for bucket in buckets:
                if bucket <= C:
                    continue
                slab = self._new_slab(bucket)
                for start in range(0, bucket, C):
                    start = min(start, bucket - C)
                    attn_len = min(bucket, self._attn_need(start + C))
                    for is_last in (False, True):
                        buf = jnp.zeros((1, C), jnp.int32)
                        slab, _first, _key = self._chunk_fn(
                            self.params, slab, buf,
                            jnp.int32(start), jnp.int32(C - 1),
                            jnp.int32(0), jnp.float32(0.0),
                            attn_len, is_last,
                        )
                        slab["k"].block_until_ready()  # seldon-lint: disable=host-sync-hot-path (warm precompile: intentional sync while the loop is idle)
                del slab
        if self._prefix_index is not None:
            # prefix-cache executables: extract per donor bucket, and the
            # suffix prefill + splice per (donor, suffix<=donor) bucket
            # pair — the shapes hit traffic takes (a longer-than-donor
            # suffix compiles on first use; it is the rare shape)
            for d in buckets:
                slab = self._extract_fn(self._cache, 0, d)
                if self.prefill_chunk > 0:
                    # chunked-hit splice executables: donor slab into a
                    # fresh staging slab, one per (donor, prompt bucket)
                    # pair the declared shapes can take — compiled here,
                    # never inline on the scheduler thread
                    for b in buckets:
                        if b >= d and b > self.prefill_chunk:
                            out = self._splice_fn(self._new_slab(b), slab)
                            out["k"].block_until_ready()  # seldon-lint: disable=host-sync-hot-path (warm precompile: intentional sync while the loop is idle)
                for s_b in buckets:
                    if s_b > d:
                        continue
                    suffix = jnp.zeros((1, s_b), jnp.int32)
                    first, suffix_slab, lane_key = self._prefix_prefill_fn(
                        self.params, slab, suffix, jnp.int32(1),
                        jnp.zeros((1,), jnp.int32),
                        jnp.int32(0), jnp.float32(0.0),
                    )
                    self._cache, self._cur_tok, self._pos, self._keys = (
                        self._insert_prefix_fn(
                            self._cache, slab, suffix_slab, 0, jnp.int32(1),
                            first[0], 2, lane_key,
                            self._cur_tok, self._pos, self._keys,
                        )
                    )
                    self._cache["k"][0].block_until_ready()  # seldon-lint: disable=host-sync-hot-path (warm precompile: intentional sync while the loop is idle)
        if self._kv_tier is not None:
            # tier spill / copy-back executables: a rung-3 preemption
            # extracts the victim lane's cache columns at its ATTENTION
            # width (_attn_need(pos)) and the copy-back resume inserts a
            # slab of that same width — widths the prefix-cache warm
            # above (prompt buckets) never touches. Compile every width
            # a lane can spill at so the first preemption and the first
            # resume never compile inline on the scheduler thread.
            tier_widths = sorted({
                self._attn_need(p) for p in range(max(1, lo), hi + 1)
            })
            for w in tier_widths:
                slab = self._extract_fn(self._cache, 0, w)
                self._cache, self._cur_tok, self._pos, self._keys = (
                    self._insert_fn(
                        self._cache, slab, 0, jnp.int32(0), w,
                        jax.random.PRNGKey(0),
                        self._cur_tok, self._pos, self._keys,
                    )
                )
                self._cache["k"][0].block_until_ready()  # seldon-lint: disable=host-sync-hot-path (warm precompile: intentional sync while the loop is idle)
            # census line, PR 13 style: a width-count jump between runs
            # means a config change grew the tier's compile surface
            logger.info(
                "warm: kv-tier extract/insert compile census: %d width "
                "variant(s) (%s)", len(tier_widths), tier_widths,
            )
        active = jnp.zeros((self.slots,), bool)
        temps = jnp.zeros((self.slots,), jnp.float32)
        for attn_len in attn_lens:
            if self._spec_burst_fn is not None:
                caches = {
                    "k": self._cache["k"], "v": self._cache["v"],
                    "dk": self._draft_cache["k"], "dv": self._draft_cache["v"],
                }
                # greedy variant only: temperature lanes compile their own
                # (rare) variant on first use
                (
                    _start, _toks, _counts, self._cur_tok, self._pos,
                    self._keys, nc,
                ) = self._spec_burst_fn(
                    self.params, self._draft_params, caches,
                    self._cur_tok, self._pos, active, temps,
                    self._keys, k, attn_len, False,
                )
                self._cache = {"k": nc["k"], "v": nc["v"]}
                self._draft_cache = {"k": nc["dk"], "v": nc["dv"]}
                self._cache["k"][0].block_until_ready()  # seldon-lint: disable=host-sync-hot-path (warm precompile: intentional sync while the loop is idle)
            else:
                toks, self._cur_tok, self._pos, self._cache, self._keys = (
                    self._burst_fn(
                        self.params, self._cache, self._cur_tok, self._pos,
                        active, temps, self._keys, k, attn_len,
                    )
                )
                toks.block_until_ready()  # seldon-lint: disable=host-sync-hot-path (warm precompile: intentional sync while the loop is idle)
                if self.depth_groups > 1:
                    # grouped sub-burst variants: every pow2 group-size
                    # bucket at this attention bucket (mixed-depth polls
                    # pick any of them; compile-before-listen holds)
                    for gb in self._warm_group_sizes():
                        lane_ix = jnp.arange(gb, dtype=jnp.int32)
                        toks, self._cur_tok, self._pos, self._cache, self._keys = (
                            self._group_burst_fn(
                                self.params, self._cache, self._cur_tok,
                                self._pos, temps, self._keys, lane_ix,
                                0, k, attn_len,
                            )
                        )
                        toks.block_until_ready()  # seldon-lint: disable=host-sync-hot-path (warm precompile: intentional sync while the loop is idle)
        if self._fused_k > 0 and self._spec_burst_fn is None:
            # stop-aware fused variants: every (K, attn bucket, group
            # size) the adaptive-K plan can reach — K is a pow2 in
            # [min(steps_per_poll, fused), fused] (see _fused_plan), so
            # the shrink can never ask for an executable this loop did
            # not build. The one-line census below is the CI-visible
            # retrace-hazard guard: a variant-count jump between runs
            # means a config change grew the compile surface.
            fks: List[int] = []
            fk = self._fused_k
            lo_k = min(self._k, self._fused_k)
            while fk >= lo_k:
                fks.append(fk)
                fk //= 2
            fks = sorted(fks)
            gbs = self._warm_group_sizes() if self.depth_groups > 1 else []
            stops0 = jnp.full((self.slots,), -1, jnp.int32)
            budget0 = jnp.zeros((self.slots,), jnp.int32)
            compiled = 0
            for attn_len in attn_lens:
                for fk in fks:
                    (
                        toks, _counts, _done, self._cur_tok, self._pos,
                        self._cache, self._keys, budget0,
                    ) = self._fused_burst_fn(
                        self.params, self._cache, self._cur_tok, self._pos,
                        active, temps, self._keys, stops0, budget0, fk,
                        attn_len,
                    )
                    toks.block_until_ready()  # seldon-lint: disable=host-sync-hot-path (warm precompile: intentional sync while the loop is idle)
                    compiled += 1
                    for gb in gbs:
                        lane_ix = jnp.arange(gb, dtype=jnp.int32)
                        (
                            toks, _counts, _done, self._cur_tok, self._pos,
                            self._cache, self._keys, budget0,
                        ) = self._fused_group_fn(
                            self.params, self._cache, self._cur_tok,
                            self._pos, temps, self._keys, stops0, budget0,
                            lane_ix, 0, fk, attn_len,
                        )
                        toks.block_until_ready()  # seldon-lint: disable=host-sync-hot-path (warm precompile: intentional sync while the loop is idle)
                        compiled += 1
            logger.info(
                "warm: fused decode compile census: %d variant(s) "
                "(k=%s x attn=%s x group_sizes=%s)",
                compiled, fks, attn_lens, gbs or [self.slots],
            )
        if self.mesh is not None:
            # sharded-serving census, same PR-13 contract as the fused
            # line: every executable above just compiled against the
            # MESH layouts, so a partitioned-leaf or per-shard-byte jump
            # between runs means a layout change moved bytes across
            # chips. One designed sync makes the census report compiled
            # executables, not queued ones.
            self._cache["k"][0].block_until_ready()  # seldon-lint: disable=host-sync-hot-path (sharded warm census: intentional sync while the loop is idle so the census reports compiled sharded executables)
            leaves = [
                leaf for leaf in jax.tree_util.tree_leaves(self.params)
                if hasattr(leaf, "sharding")
            ]
            partitioned = sum(
                1 for leaf in leaves
                if not leaf.sharding.is_fully_replicated
            )
            logger.info(
                "warm: sharded serving census: mesh=%s devices=%d "
                "partitioned_params=%d/%d param_shard_bytes=%d kv_shard=%d",
                dict(self.mesh.shape), self.mesh.devices.size,
                partitioned, len(leaves), self._param_shard_bytes,
                self._kv_shard,
            )
        # warm left garbage in cur_tok/pos; reset the host-visible lane
        # state so the first admissions start from a clean slate (the
        # device cache needs no scrub — see residue invariant above)
        self._cur_tok = jnp.zeros((self.slots,), jnp.int32)
        self._pos = jnp.zeros((self.slots,), jnp.int32)
        self._keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(self.slots))
        self._stops_dev = jnp.full((self.slots,), -1, jnp.int32)
        self._budget_dev = jnp.zeros((self.slots,), jnp.int32)
        self._fused_sync = False

    @caller_thread
    def close(self) -> None:
        if self.health != "dead":
            # a dead batcher stays "dead" (its unready latch is the
            # reconciler's replace signal); a serving/restarting one
            # records the deliberate shutdown
            self.health = "closed"
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self._drain_queue(self._dead_error())
        self._fail_pending_swap(self._dead_error())
        self._fail_pending_drain(self._dead_error())

    def _fail_pending_swap(self, err: Exception) -> None:
        with self._swap_lock:
            swap, self._pending_swap = self._pending_swap, None
        if swap is not None and not swap.future.done():
            swap.future.set_exception(err)

    def _release_tier_ckpt(self, req: GenRequest) -> None:
        """Release a request's host-tier checkpoint (if any): the
        request was cancelled, failed, or migrated away, so the entry
        would otherwise pin tier budget forever — prefix demotions can
        never evict checkpoints. Callable from any thread (the tier is
        lock-protected; ``pop`` makes the release idempotent)."""
        ck = req.resume
        if ck is None or self._kv_tier is None:
            return
        key = ck.pop("tier", None)
        if key is not None:
            self._kv_tier.drop_ckpt(key)

    def _drain_queue(self, err: Exception) -> None:
        while self._resume_queue:
            try:
                req = self._resume_queue.popleft()
            except IndexError:  # raced another drainer
                break
            self._release_tier_ckpt(req)
            if not req.future.done():
                req.future.set_exception(err)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if not req.future.done():
                req.future.set_exception(err)

    # -- scheduler loop --------------------------------------------------------

    def _chunk8_ok(self, bucket: int) -> bool:
        """m=8 batched prefill is admitted when its K/V slab stays small
        (the slab is a transient [L, 8, KV, bucket, Dh] x2 allocation on
        top of params + cache; 4 GB keeps flagship configs comfortably
        inside HBM)."""
        cfg = self.model.cfg
        slab = 2 * cfg.n_layers * 8 * cfg.n_kv_heads * bucket * cfg.head_dim * 2
        return slab <= 4 << 30

    def _bucket(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        if n <= self.max_seq:
            return self.max_seq
        # a too-long request must fail HERE with a clear, TYPED message
        # (413 / INVALID_ARGUMENT at the engine), not as an opaque
        # downstream broadcast/shape error when the prompt is packed
        # into a bucket-sized array it cannot fit
        raise PromptTooLong(
            f"request of {n} tokens exceeds the largest prefill bucket "
            f"({self.prefill_buckets[-1]}) and max_seq ({self.max_seq}); "
            "raise max_seq or shorten the prompt"
        )

    def _attn_need(self, hi: int) -> int:
        """Smallest attn_bucket multiple covering position ``hi`` (clamped
        to the cache length)."""
        ab = self.attn_bucket
        return min(self.max_seq, -(-hi // ab) * ab)

    @scheduler_only
    def _emit_span(self, req: GenRequest, operation: str, start_t: float,
                   end_t: float, tags: Optional[Dict[str, Any]] = None) -> None:
        """Retroactive per-request timeline span, parented under the trace
        context captured at submit(). No-op (one attribute check) for
        untraced requests, so the scheduler hot path stays clean with
        tracing off. Monotonic interval endpoints are placed on the wall
        clock via the request's submit anchor."""
        if req.trace is None:
            return
        from ..tracing import get_tracer

        start_us = req.submit_wall_us + int((start_t - req.submit_t) * 1e6)
        get_tracer().record_span(
            operation, req.trace[0], req.trace[1], start_us,
            int((end_t - start_t) * 1e6), tags=tags,
        )

    @scheduler_only
    def _plan_groups(self, adv: int):
        """Partition live lanes into <= depth_groups sub-bursts by
        attention-read bucket. Returns ``([(lanes, bucket)], need)`` with
        groups shallow-first; ``need[slot]`` is the lane's OWN bucket.

        Packing: one candidate group per distinct bucket, then adjacent
        groups merge shallow-into-deep while the modeled per-step cost of
        keeping them split (an extra param read — _group_split_bytes)
        exceeds the KV-read saving (lanes x bucket gap x _kv_key_bytes),
        or while the group count exceeds the cap. Merging always prefers
        filling the cheapest gap first, so a lane spills to a deeper
        bucket only when the cost model says the split isn't worth it."""
        need = {
            slot: self._attn_need(self._pos_host[slot] + adv)
            for slot in self._active
        }
        groups = [
            ([s for s in sorted(need) if need[s] == b], b)
            for b in sorted(set(need.values()))
        ]
        if self.depth_groups <= 1 or len(groups) == 1:
            if len(groups) > 1:
                groups = [(sorted(need), max(need.values()))]
            return groups, need
        while len(groups) > 1:
            best_i, best_delta = None, None
            for i in range(len(groups) - 1):
                lanes_s, b_s = groups[i]
                _, b_d = groups[i + 1]
                # per-step cost of MERGING group i into its deeper
                # neighbour, minus the param read the merge saves
                delta = (
                    len(lanes_s) * (b_d - b_s) * self._kv_key_bytes
                    - self._group_split_bytes
                )
                if best_delta is None or delta < best_delta:
                    best_i, best_delta = i, delta
            if len(groups) > self.depth_groups or best_delta < 0:
                lanes_s, _ = groups.pop(best_i)
                lanes_d, b_d = groups[best_i]
                groups[best_i] = (sorted(lanes_d + lanes_s), b_d)
            else:
                break
        return groups, need

    def _group_size_bucket(self, n: int) -> int:
        """pow2 group-size bucket (one sub-burst executable per size)."""
        g = 1
        while g < n:
            g <<= 1
        return min(g, self.slots)

    def _warm_group_sizes(self) -> List[int]:
        """Every pow2 group-size bucket a mixed-depth poll can dispatch,
        plus the whole batch — the ONE enumeration both warm()'s grouped
        sub-burst loop and the fused compile census iterate, so the two
        can never precompile different variant sets."""
        gb = 1
        gbs = [self.slots]
        while gb < self.slots:
            gbs.append(gb)
            gb <<= 1
        return sorted(set(gbs))

    @scheduler_only
    def _fused_plan(self, k_max=None):
        """Adaptive K for the stop-aware fused burst: ``(k, reason)``.

        Start from ``fused_steps_per_dispatch`` and shrink — never below
        the configured ``steps_per_poll`` burst (``self._k``), so the
        shrink can't reintroduce the tiny-burst-per-completion pathology
        the fixed-k design was built to avoid:

        * **stop_budget** — to the nearest lane's remaining token budget
          (pow2-floored): steps past the closest stop are wasted device
          work the done mask would only discard;
        * **pressure** — to ``steps_per_poll`` while the HBM ledger is
          latched: the reclaim ladder (and its preemption checkpoints)
          only runs between dispatches, so boundaries must come at the
          pre-fused cadence;
        * **poll_boundary** — to ``steps_per_poll`` while a weight swap
          or graceful drain is staged: both act at poll boundaries, and
          a K-step burst would stall the flip/checkpoint by K steps.

        The result is always a pow2 <= fused_steps_per_dispatch, so one
        precompiled executable exists per (K, attn bucket[, group size])
        and the shrink can never trigger an inline XLA compile.

        ``k_max``: the caller's snapshot of ``self._fused_k`` — the loop
        passes the same value that decided ``use_fused`` this poll, so a
        concurrent toggle (the modelbench fused probe flips the knob on
        a live server) can never tear between the mode decision and the
        plan and yield an unwarmed K."""
        if k_max is None:
            k_max = self._fused_k
        k, reason = k_max, None
        floor = min(self._k, k_max)
        rem = [
            r for r in (
                s.request.max_new_tokens - s.dispatched
                - (1 if s.first_pending else 0)
                for s in self._active.values()
            ) if r > 0
        ]
        if rem:
            tight = 1
            nearest = min(rem)
            while tight * 2 <= nearest:
                tight *= 2
            tight = max(tight, floor)
            if tight < k:
                k, reason = tight, "stop_budget"
        if (
            self._pressure.budget_bytes > 0 and self._pressure.active
            and floor < k
        ):
            k, reason = floor, "pressure"
        # unlocked reads, same discipline as the loop's swap sighting: a
        # one-poll-late shrink is harmless
        if (
            (self._pending_swap is not None or self._pending_drain is not None)
            and floor < k
        ):
            k, reason = floor, "poll_boundary"
        return max(1, min(k, k_max)), reason

    @scheduler_only
    def _draft_admit(self, slot: int, req: GenRequest) -> None:
        """Give the draft its prompt K/V prefix (speculation only). Draft
        prefixes are RE-DERIVED from the full prompt, never cached or
        chunked — the draft forward is cheap by construction."""
        self._draft_admit_tokens(slot, req.tokens)

    @scheduler_only
    def _draft_admit_tokens(self, slot: int, tokens: List[int]) -> None:
        """Draft prefill over an arbitrary token sequence — the prompt
        at admit, or prompt+generated-so-far when a preempted lane
        resumes (or rung 2's cancelled speculation re-enables): the
        draft's K/V is a pure function of the tokens, so re-derivation
        lands it in exactly the state incremental drafting left it."""
        import jax.numpy as jnp

        n = len(tokens)
        prompt = np.zeros((1, self._bucket(n)), np.int32)
        prompt[0, :n] = tokens
        dcache_one = self._draft_prefill_fn(
            self._draft_params, jnp.asarray(prompt),
            jnp.asarray([n - 1], jnp.int32),
        )
        self._draft_cache = self._draft_insert_fn(
            self._draft_cache, dcache_one, slot
        )

    def _new_slab(self, bucket: int):
        """Fresh staging slab in the cache_one layout the lane insert
        consumes: ``{"k","v"}`` of ``[L, 1, KV, bucket, Dh]`` — allocated
        pre-sharded under a mesh so chunked prefill writes shards in
        place instead of resharding on the first chunk."""
        import jax
        import jax.numpy as jnp

        cfg = self.model.cfg
        shape = (cfg.n_layers, 1, cfg.n_kv_heads, bucket, cfg.head_dim)
        dt = jnp.dtype(getattr(self.model, "compute_dtype", cfg.dtype))
        slab = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
        if self._slab_sharding is not None:
            slab = {
                name: jax.device_put(a, self._slab_sharding)
                for name, a in slab.items()
            }
        return slab

    def _upload_slab(self, host: Dict[str, Any]) -> Dict[str, Any]:
        """Host->device K/V slab upload (``[L, 1, KV, T, Dh]``) honoring
        the mesh slab layout. Every wire/tier slab arrives as contiguous
        host bytes (SKV1 and the host tier are layout-independent by
        contract); under a mesh the upload scatters each chip's KV-head
        shard directly so the downstream insert/splice executables see
        the same layout the persistent cache uses. Unmeshed this is the
        plain ``jnp.asarray`` H2D copy it always was."""
        import jax
        import jax.numpy as jnp

        if self._slab_sharding is None:
            return {"k": jnp.asarray(host["k"]), "v": jnp.asarray(host["v"])}
        return {
            "k": jax.device_put(host["k"], self._slab_sharding),
            "v": jax.device_put(host["v"], self._slab_sharding),
        }

    @scheduler_only
    def _start_chunked(self, slot: int, req: GenRequest, hit=None,
                       resume=None) -> None:
        """Reserve ``slot`` and queue the prompt for interleaved chunked
        prefill. On a prefix-cache hit the donor slab lands at the head
        of the staging slab and chunking starts at the splice point —
        rounded DOWN to the chunk grid: chunk offsets must stay at
        multiples of ``prefill_chunk`` so every (offset, attn_len)
        executable is one warm() precompiled (an off-grid start would
        jit-compile inline on the scheduler thread, stalling every decode
        lane mid-serving). The [aligned, match) overlap is recomputed and
        overwrites the donor splice with the same tokens at the same
        absolute positions — idempotent, at most one chunk's extra work."""
        bucket = self._bucket(len(req.tokens))
        t_admit = time.monotonic()
        req.admit_t = t_admit
        slab = self._new_slab(bucket)
        start = 0
        if hit is not None:
            # a real radix hit, even when alignment leaves nothing to
            # splice (match < one chunk): counted as a hit with its true
            # (aligned) savings so cache telemetry stays honest under
            # chunking
            m, donor = hit
            start = (m // self.prefill_chunk) * self.prefill_chunk
            if start > 0:
                with self._prof.measure(
                    "splice", variant=f"b{bucket}",
                    tenant=req.tenant or "",
                    bytes_read=start * self._kv_key_bytes,
                    tokens=start,
                ) as _m:
                    slab = self._splice_fn(slab, donor)
                    _m.sync(slab)
            req.cache_hit_tokens = start
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_saved"] += start
        elif self._prefix_index is not None:
            self.stats["prefix_misses"] += 1
        self._chunked[slot] = _ChunkJob(
            request=req, slot=slot, next_start=start, slab=slab,
            bucket=bucket, hit_tokens=start, resume=resume,
        )
        self._emit_span(
            req, "gen.queue_wait", req.submit_t, t_admit,
            tags={"lane": slot, "chunked": True,
                  "cache_hit_tokens": req.cache_hit_tokens},
        )

    @scheduler_only
    def _advance_chunks(self) -> None:
        """Run ONE prefill chunk for every pending chunked admission (the
        interleave: a chunk per job per decode poll). The final chunk
        samples the first token on device and the finished slab goes
        through the ORDINARY lane insert, so activation is exactly a
        whole-prompt admit (same deferred-first mechanics, same insert
        executable, bit-identical decode from there on)."""
        import jax.numpy as jnp

        C = self.prefill_chunk
        for slot in list(self._chunked):
            job = self._chunked[slot]
            req = job.request
            if req.future.cancelled():
                del self._chunked[slot]
                self.stats["cancelled"] += 1
                continue
            n = len(req.tokens)
            start = job.next_start
            is_last = start + C >= n
            if is_last:
                # the padded chunk must stay inside the slab; sliding the
                # start back re-writes identical K/V (same tokens, same
                # absolute positions) — idempotent by construction
                start = max(0, min(start, job.bucket - C))
            end = min(start + C, n)
            buf = np.zeros((1, C), np.int32)
            buf[0, : end - start] = req.tokens[start:end]
            attn_len = min(job.bucket, self._attn_need(start + C))
            t_chunk = time.monotonic()
            try:
                from ..tracing import device_trace

                with self._prof.measure(
                    "chunk_prefill", variant=f"b{job.bucket}",
                    tenant=req.tenant or "",
                    bytes_read=self._param_bytes + C * self._kv_key_bytes,
                    tokens=C,
                ) as _m, device_trace("gen.prefill_chunk"):
                    job.slab, first, lane_key = self._chunk_fn(
                        self.params, job.slab, jnp.asarray(buf),
                        jnp.int32(start), jnp.int32(n - 1 - start),
                        jnp.int32(req.seed), jnp.float32(req.temperature),
                        attn_len, is_last,
                    )
                    _m.sync(job.slab)
                if is_last:
                    if job.resume is not None:
                        # recompute-resume: the checkpointed continuation
                        # state replaces the chunk's own sample
                        import jax.numpy as _jnp

                        emitted_r, key_r = job.resume
                        first = _jnp.int32(int(emitted_r[-1]))
                        lane_key = key_r
                        insert_pos = n + len(emitted_r) - 1
                    else:
                        insert_pos = n
                    with self._prof.measure(
                        "insert", variant=f"b{job.bucket}",
                        tenant=req.tenant or "",
                        bytes_read=job.bucket * self._kv_key_bytes,
                        tokens=insert_pos,
                    ) as _m, device_trace("gen.lane_insert"):
                        self._cache, self._cur_tok, self._pos, self._keys = (
                            self._insert_fn(
                                self._cache, job.slab, slot, first,
                                insert_pos, lane_key,
                                self._cur_tok, self._pos, self._keys,
                            )
                        )
                        _m.sync(self._cur_tok)
            except Exception as e:  # noqa: BLE001 - bad request/device state
                logger.exception("chunked prefill failed")
                del self._chunked[slot]
                if not req.future.done():
                    req.future.set_exception(e)
                continue
            self.stats["prefill_steps"] += 1
            # positions COMPUTED, incl. pad and slide-back overlap — the
            # same convention as the bucketed full prefill (which counts
            # its whole bucket): prefill_tokens is a device-work proxy,
            # not a real-prompt-token count
            self.stats["prefill_tokens"] += C
            self.stats["prefill_chunks"] += 1
            self._emit_span(
                req, "gen.prefill_chunk", t_chunk, time.monotonic(),
                tags={"lane": slot, "start": start, "tokens": C,
                      "last": is_last, "dispatch": True},
            )
            if is_last:
                del self._chunked[slot]
                if job.resume is not None:
                    # shared resume tail: replay emitted K/V, draft
                    # re-derivation, lane re-activation with crediting
                    # continuing after the checkpoint
                    self._activate_resumed(slot, req, job.resume[0])
                    continue
                if self._spec_active():
                    # (suppressed speculation skips this: the lane gets
                    # its draft prefix at _resume_speculation instead)
                    self._draft_admit(slot, req)
                req.decode_start_t = time.monotonic()
                self._active[slot] = _Slot(request=req)
                self._pos_host[slot] = n
                self._masks_dirty = True
                self.stats["admitted"] += 1
            else:
                job.next_start = end

    @scheduler_only
    def _prefix_match(self, req: GenRequest):
        return self._prefix_match_tokens(req.tokens)

    @scheduler_only
    def _prefix_match_tokens(self, tokens: List[int]):
        """Longest usable cached prefix for this prompt: ``(m, slab)`` or
        None. Capped at n-1 (the last prompt token is always recomputed —
        its forward produces the logits the first new token samples from)
        and rejected when the suffix bucket would not fit the cache.
        Takes a raw token list so a recompute-resume (prompt + generated
        so far) can splice cached prompt prefixes exactly like a fresh
        admission."""
        if self._prefix_index is None:
            return None
        n = len(tokens)
        m, slab = self._prefix_index.match(tokens)
        m = min(m, n - 1)
        if (
            (slab is None or m < self.prefix_cache_min_tokens)
            and self._kv_tier is not None
        ):
            # device radix miss: consult the host tier — a demoted slab
            # promotes (device_put + re-insert) and serves this very
            # admission as an ordinary splice
            promoted = self._promote_tier_prefix(tokens)
            if promoted is not None:
                m, slab = promoted
                m = min(m, n - 1)
        if slab is None or m < self.prefix_cache_min_tokens:
            return None
        if m + self._bucket(n - m) > self.max_seq:
            # the traced-start suffix insert would clamp and corrupt the
            # lane; full prefill is the safe path for near-max prompts
            return None
        if slab["k"].shape[3] > self._bucket(n):
            # the hit's cost scales with the DONOR's bucket (splice bytes
            # + suffix attention over the combined cache): a short prompt
            # matching into a much longer cached prompt would pay more
            # than the full prefill it skips — not a win, decline
            return None
        return m, slab

    @scheduler_only
    def _maybe_publish(self, slot: int, s: "_Slot") -> None:
        """Publish the request's prompt K/V back into the radix pool (the
        prompt region [0, n) is fully written from admit onward and decode
        only appends, so extraction is valid at any free point). Skipped
        when an exact entry already covers the prompt — repeat-heavy
        traffic publishes each distinct prompt once."""
        idx = self._prefix_index
        if idx is None:
            return
        toks = s.request.tokens
        n = len(toks)
        if n < self.prefix_cache_min_tokens:
            return
        if idx.covered_len(toks) >= n:
            return
        _b = self._bucket(n)
        with self._prof.measure(
            "extract", variant=f"b{_b}",
            tenant=s.request.tenant or "",
            bytes_read=_b * self._kv_key_bytes, tokens=_b,
        ) as _m:
            slab = self._extract_fn(self._cache, slot, _b)
            _m.sync(slab)
        nbytes = int(slab["k"].nbytes) + int(slab["v"].nbytes)
        self.stats["prefix_evicted"] += idx.insert(toks, slab, nbytes)
        self.stats["prefix_cache_bytes"] = idx.total_bytes

    @scheduler_only
    def _admit_remote_lane(self, slot: int, req: GenRequest) -> None:
        """Splice a shipped prefill slab into ``slot`` (scheduler thread;
        the decode-side endpoint of the KV handoff). No prefill runs
        here — the slab carries the prompt K/V and the first sampled
        token; a full slab goes through the ORDINARY whole-prompt
        insert, a suffix-only slab re-matches the local radix index and
        goes through the prefix-splice insert, so decode state after a
        remote admit is bit-identical to the unified path's."""
        import jax.numpy as jnp

        from ..tracing import device_trace
        from .disagg import PrefixGone, WeightVersionMismatch

        r = req.remote
        n = len(req.tokens)
        t_admit = time.monotonic()
        req.admit_t = t_admit
        # re-validate at the poll boundary: a hot-swap that flipped while
        # this request sat in the queue makes the slab stale — the typed
        # refusal the progressive-delivery contract requires
        if r["version"] != self.weight_version:
            raise WeightVersionMismatch(
                f"weight swap landed mid-handoff: slab is "
                f"{r['version']!r}, serving {self.weight_version!r}"
            )
        covered = r["covered"]
        if covered:
            m, donor = self._prefix_index.match(req.tokens)
            if donor is None or m < covered:
                raise PrefixGone(
                    f"cached prefix covers {m} tokens but the slab "
                    f"assumes {covered} — donor evicted mid-handoff; "
                    "re-request with covered_len=0"
                )
            with self._prof.measure(
                "insert", variant=f"px{self._bucket(n)}",
                tenant=req.tenant or "",
                bytes_read=self._bucket(n) * self._kv_key_bytes, tokens=n,
            ) as _m, device_trace("gen.lane_insert"):
                self._cache, self._cur_tok, self._pos, self._keys = (
                    self._insert_prefix_fn(
                        self._cache, donor, r["slab"], slot,
                        jnp.int32(covered), jnp.int32(r["first"]), n,
                        r["key"], self._cur_tok, self._pos, self._keys,
                    )
                )
                _m.sync(self._cur_tok)
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_saved"] += covered
        else:
            with self._prof.measure(
                "insert", variant=f"b{self._bucket(n)}",
                tenant=req.tenant or "",
                bytes_read=self._bucket(n) * self._kv_key_bytes, tokens=n,
            ) as _m, device_trace("gen.lane_insert"):
                self._cache, self._cur_tok, self._pos, self._keys = (
                    self._insert_fn(
                        self._cache, r["slab"], slot, jnp.int32(r["first"]),
                        n, r["key"], self._cur_tok, self._pos, self._keys,
                    )
                )
                _m.sync(self._cur_tok)
            if self._prefix_index is not None:
                self.stats["prefix_misses"] += 1
        t_inserted = time.monotonic()
        req.decode_start_t = t_inserted
        self._emit_span(
            req, "gen.queue_wait", req.submit_t, t_admit,
            tags={"lane": slot, "remote": True,
                  "cache_hit_tokens": covered},
        )
        self._emit_span(
            req, "gen.lane_insert", t_admit, t_inserted,
            tags={"lane": slot, "remote": True, "dispatch": True},
        )
        self._active[slot] = _Slot(request=req)
        self._pos_host[slot] = n
        self._masks_dirty = True
        self.stats["admitted"] += 1
        self.stats["kv_imports"] += 1
        self.stats["kv_import_bytes"] += r["nbytes"]
        if covered:
            self.stats["kv_transfer_bytes_saved"] += (
                covered * self._slab_token_bytes
            )
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "remote_insert",
                "lane": slot,
                "tokens": n,
                "covered_len": covered,
                "bytes": r["nbytes"],
                "weight_version": self.weight_version,
            })
        # the slab's device arrays are spliced; drop the reference so the
        # upload buffer frees as soon as the insert's copy completes
        req.remote = None

    # -- tiered KV memory: host-RAM spill tier (serving/kvtier.py) ---------

    def sync_kv_tier_stats(self) -> None:
        """Mirror the tier's internal counters into the batcher's stats
        surface (flight dumps, server metric deltas). Tier counters are
        written under the tier lock by scheduler AND transport threads;
        these are plain int copies, safe from any thread."""
        tier = self._kv_tier
        if tier is None:
            return
        t = tier.stats
        self.stats["kv_tier_demotions"] = t["demotions"]
        self.stats["kv_tier_hits"] = t["hits"]
        self.stats["kv_tier_evictions"] = t["evictions"]
        self.stats["kv_tier_bytes"] = tier.total_bytes

    def kv_tier_summary(self) -> Optional[Dict[str, Any]]:
        return self._kv_tier.summary() if self._kv_tier is not None else None

    @property
    def tier_promote_gate(self) -> int:
        """Effective promote threshold: a tier match below
        ``prefix_cache_min_tokens`` could never serve an admission (the
        radix-hit gate would discard it right after the PCIe copy), so
        the promote gate is the max of the two knobs."""
        return max(self.kv_tier_promote_min_tokens,
                   self.prefix_cache_min_tokens)

    @scheduler_only
    def _demote_prefix_slabs(self, victims) -> None:
        """Demote reclaim-ladder prefix victims to the host tier:
        ``device_get`` each slab at this poll boundary (the one designed
        sync of the demote path — pressure reclaim is already a
        poll-boundary event and the copy IS the feature: a PCIe pull now
        buys back a whole re-prefill later), SKV1-encode, store keyed by
        (weight_version, token path)."""
        import jax

        from .disagg import prompt_hash

        tier = self._kv_tier
        for tokens, slab, _nbytes in victims:
            # refuse BEFORE the PCIe pull, not after: a victim below the
            # demote threshold, or already covered by a stored entry,
            # would be refused by put_prefix anyway — paying two
            # device_get syncs for it mid-pressure-event is the worst
            # possible time
            if (
                len(tokens) < tier.min_tokens
                or tier.prefix_covered_len(tokens, self.weight_version)
                >= len(tokens)
            ):
                continue
            host = {
                "k": jax.device_get(slab["k"]),  # seldon-lint: disable=host-sync-hot-path (tier demote: poll-boundary PCIe pull of an evicted prefix slab — the copy replaces a future re-prefill; reclaim is latched, not steady-state)
                "v": jax.device_get(slab["v"]),  # seldon-lint: disable=host-sync-hot-path (tier demote: second half of the same poll-boundary slab pull)
            }
            if tier.put_prefix(tokens, host, self.weight_version):
                if self.flight is not None and self.flight.enabled:
                    self.flight.record({
                        "type": "kv_demote", "kind": "prefix",
                        "tokens": len(tokens),
                        "phash": prompt_hash(tokens)[:8],
                        "bytes": int(host["k"].nbytes) + int(host["v"].nbytes),
                    })

    def tier_prefix_lookup(self, tokens, min_tokens: Optional[int] = None):
        """The ONE usable-hit probe of this member's host tier, shared
        by the scheduler's promote-on-miss, the decode role's
        transfer-dedup consult, and the KV-port listener's peer lookup
        — so the gate (promote threshold, donor-bucket cap, near-max
        suffix cap) can never drift between the side that SHIPS a slab
        and the side that must splice it. Returns ``(m, meta, host)``
        with host arrays CRC-verified, or None on miss / corruption
        (entry already dropped, logged) / caps. Thread-safe: pure host
        reads under the tier lock."""
        from .disagg import DisaggError

        tier = self._kv_tier
        if tier is None:
            return None
        tokens = [int(t) for t in tokens]
        n = len(tokens)
        try:
            hit = tier.match_prefix(tokens, self.weight_version)
        except DisaggError as e:
            logger.warning("kv tier prefix entry dropped: %s", e)
            return None
        if hit is None:
            return None
        depth, meta, host = hit
        m = min(depth, n - 1)
        if m < max(int(min_tokens or 0), self.tier_promote_gate):
            return None
        if (
            host["k"].shape[3] > self._bucket(n)
            or m + self._bucket(n - m) > self.max_seq
        ):
            # same caps the device-side match applies: a donor wider
            # than the prompt bucket (or a near-max suffix insert) costs
            # more than the prefill it skips
            return None
        return m, meta, host

    @scheduler_only
    def _promote_tier_prefix(self, tokens):
        """Tier consult on a device radix miss: decode the longest
        stored host prefix (CRC-verified), ``device_put`` it, re-insert
        it into the device radix index under its ENTRY path, and return
        ``(m, device_slab)`` ready for the ordinary splice — a warm hit
        that costs a PCIe copy instead of a re-prefill. None on miss,
        corruption (entry already dropped), or when the usability caps
        say the splice would not win (see :meth:`tier_prefix_lookup`)."""
        from .disagg import prompt_hash

        idx = self._prefix_index
        if idx is None:
            return None
        hit = self.tier_prefix_lookup(tokens)
        if hit is None:
            return None
        m, meta, host = hit
        entry_tokens = [int(t) for t in meta.get("tokens") or []]
        slab_dev = self._upload_slab(host)
        nbytes = int(host["k"].nbytes) + int(host["v"].nbytes)
        self.stats["prefix_evicted"] += idx.insert(
            entry_tokens, slab_dev, nbytes
        )
        self.stats["prefix_cache_bytes"] = idx.total_bytes
        self.stats["kv_tier_promotions"] += 1
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "tier_hit", "kind": "prefix", "source": "local",
                "tokens": m, "phash": prompt_hash(entry_tokens)[:8],
            })
            self.flight.record({
                "type": "kv_promote", "kind": "prefix", "source": "local",
                "tokens": m, "bytes": nbytes,
                "phash": prompt_hash(entry_tokens)[:8],
            })
        return m, slab_dev

    @caller_thread
    def consult_tier_covered_len(self, tokens) -> int:
        """Decode-role transfer-dedup consult of this member's OWN host
        tier: a demoted prefix that matches the prompt promotes into the
        device radix index right here (caller thread — the H2D upload
        overlaps whatever burst the scheduler is running, exactly like a
        remote admit's slab), and the refreshed ``remote_covered_len``
        is returned so the prefill request ships suffix-only. 0 on
        miss/corruption/caps — the full-slab path is always right
        behind."""
        if self._prefix_index is None:
            return 0
        hit = self.tier_prefix_lookup(tokens)
        if hit is None:
            return 0
        _m, meta, host = hit
        self.promote_peer_prefix(meta, host, source="local")
        return self.remote_covered_len(tokens)

    @caller_thread
    def promote_peer_prefix(self, meta: Dict[str, Any],
                            host: Dict[str, Any],
                            source: str = "peer") -> int:
        """Insert a prefix slab pulled from a PEER's host tier into the
        LOCAL device radix index (H2D upload on this caller thread,
        exactly like a remote admit's slab upload), so the ordinary
        match/splice machinery — and the transfer-dedup consult — serve
        it from here on. Returns the entry's token count."""
        from .disagg import prompt_hash

        idx = self._prefix_index
        if idx is None:
            return 0
        entry_tokens = [int(t) for t in meta.get("tokens") or []]
        if not entry_tokens:
            return 0
        slab_dev = self._upload_slab(host)
        nbytes = int(host["k"].nbytes) + int(host["v"].nbytes)
        evicted = idx.insert(entry_tokens, slab_dev, nbytes)
        with self._export_lock:
            self.stats["prefix_evicted"] += evicted
            self.stats["prefix_cache_bytes"] = idx.total_bytes
            self.stats["kv_tier_promotions"] += 1
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "kv_promote", "kind": "prefix", "source": source,
                "tokens": len(entry_tokens), "bytes": nbytes,
                "phash": prompt_hash(entry_tokens)[:8],
            })
        return len(entry_tokens)

    @scheduler_only
    def _checkpoint_kv_to_tier(self, slot: int, req: GenRequest) -> None:
        """Ladder rung 3's spill half: copy the preempted lane's exact
        cache columns ``[0, pos)`` to the host tier (when budget allows)
        so resume is a copy-back insert instead of prompt-recompute +
        teacher-forced replay. The extract is a device-side copy; the
        pull to host is the one designed sync — the pipeline is already
        drained (preemption's own requirement), and the bytes pulled
        here are exactly the recompute the resume no longer pays."""
        import jax

        from .disagg import prompt_hash

        tier = self._kv_tier
        ck = req.resume
        if tier is None or ck is None:
            return
        emitted = ck["emitted"]
        n = len(req.tokens)
        pos = n + len(emitted) - 1
        width = self._attn_need(pos)
        with self._prof.measure(
            "extract", variant="preempt", tenant=req.tenant or "",
            bytes_read=width * self._kv_key_bytes, tokens=width,
        ) as _m:
            slab = self._extract_fn(self._cache, slot, width)
            _m.sync(slab)
        host = {
            "k": jax.device_get(slab["k"]),  # seldon-lint: disable=host-sync-hot-path (tier checkpoint: poll-boundary pull of a preempted lane's K/V — pipeline already drained; this copy replaces the resume's whole recompute+replay)
            "v": jax.device_get(slab["v"]),  # seldon-lint: disable=host-sync-hot-path (tier checkpoint: second half of the same poll-boundary lane pull)
        }
        self._tier_ck_seq += 1
        key = self._tier_ck_seq
        stored = tier.put_ckpt(
            key,
            {"pos": pos, "width": width, "prompt_tokens": n,
             "emitted": len(emitted)},
            host, version=self.weight_version,
        )
        if stored:
            ck["tier"] = key
            if self.flight is not None and self.flight.enabled:
                self.flight.record({
                    "type": "kv_demote", "kind": "ckpt", "lane": slot,
                    "tokens": pos, "phash": prompt_hash(req.tokens)[:8],
                    "bytes": int(host["k"].nbytes) + int(host["v"].nbytes),
                })
        else:
            # belt and braces: a refused checkpoint leaves no key behind
            # (the checkpoint dict is freshly built per preemption, but
            # a stale key here would make the next resume count a
            # phantom replay fallback)
            ck.pop("tier", None)

    # -- HBM pressure: ledger, reclaim ladder, decode-lane preemption ------

    def pressure_summary(self) -> Optional[Dict[str, Any]]:
        """Ledger snapshot for metrics/flight dumps; None when the
        pressure subsystem is off (budget 0). Under a mesh the snapshot
        also carries the shard factors the ledger divided by, so an
        operator reading used_bytes knows it is PER-CHIP occupancy."""
        pc = self._pressure
        if pc.budget_bytes <= 0 and not pc.stats["budget_changes"]:
            return None
        out = pc.summary()
        if self.mesh is not None:
            out["kv_shard"] = self._kv_shard
            out["param_shard_bytes"] = self._param_shard_bytes
        return out

    def _spec_active(self) -> bool:
        """Speculation is configured AND not cancelled by the pressure
        ladder's rung 2."""
        return self._spec_burst_fn is not None and not self._spec_suppressed

    def _burst_tenant(self) -> str:
        """Tenant label for a whole-batch dispatch: the single tenant
        every active lane belongs to, or "" when mixed/untenanted (the
        weight pager serves one resident tenant at a time, so decode
        bursts are single-tenant in practice; attribution degrades to
        unlabeled rather than lying when lanes ever mix)."""
        tenant = ""
        for s in self._active.values():
            t = s.request.tenant
            if t is None:
                return ""
            if not tenant:
                tenant = t
            elif t != tenant:
                return ""
        return tenant

    @scheduler_only
    def _ledger_components(self) -> Dict[str, int]:
        """The unified HBM ledger, priced the way the reclaim ladder can
        free it: live decode footprint per lane (current attention-read
        bucket x per-token K/V bytes, draft cache included while
        resident), chunked-prefill staging slabs, the radix prefix
        cache's published bytes, and a staged hot-swap's double-buffered
        params. Pure host arithmetic over at most ``slots`` entries —
        cheap enough to run every poll.

        Under a mesh every component is priced **per shard**: array
        ``.nbytes`` is the GLOBAL byte count of a sharded buffer, but the
        watermark guards a single chip's HBM, so KV components divide by
        the cache's shard factor (model axis x seq when sharded — same
        factor for staging/prefix slabs, which carry the model-axis split)
        and a staged swap scales by the param layout's per-shard fraction.
        Unmeshed, every factor is 1 and the arithmetic is unchanged."""
        per_tok = self._kv_key_bytes
        if self.speculate_tokens > 0 and not self._spec_suppressed:
            per_tok += self._draft_kv_key_bytes
        decode = sum(
            self._attn_need(pos) for pos in self._pos_host.values()
        ) * per_tok // self._kv_shard
        staging = sum(
            job.bucket for job in self._chunked.values()
        ) * self._kv_key_bytes // self._kv_model_shard
        prefix = (
            self._prefix_index.total_bytes
            if self._prefix_index is not None else 0
        ) // self._kv_model_shard
        swap = self._pending_swap
        swap_bytes = getattr(swap, "nbytes", 0) if swap is not None else 0
        if swap_bytes and self._param_bytes:
            swap_bytes = (
                swap_bytes * self._param_shard_bytes // self._param_bytes
            )
        # multi-tenancy: the resident tenant's checkpoint occupies HBM
        # beyond the baseline single-model params the watermark already
        # assumes — the pager reports its residency so page-ins compete
        # with KV growth in the same ledger. Scaled per shard exactly
        # like a staged swap (same param layout).
        pager_bytes = 0
        if self.tenant_pager is not None:
            pager_bytes = int(
                getattr(self.tenant_pager, "resident_hbm_bytes", 0)
            )
            if pager_bytes and self._param_bytes:
                pager_bytes = (
                    pager_bytes * self._param_shard_bytes
                    // self._param_bytes
                )
        return {
            "decode": decode, "staging": staging,
            "prefix": prefix, "swap": swap_bytes, "pager": pager_bytes,
        }

    @scheduler_only
    def _drain_pending(self, pending) -> None:
        """Read every in-flight burst NOW (oldest first). Preemption
        checkpoints must see the lane's exact host state — emitted
        tokens and the device position they imply — so the pipeline is
        flushed before any victim is chosen. Preemption is rare; one
        flushed pipeline is its cheapest cost."""
        while pending:
            mode, payload = pending.popleft()
            if mode == "spec":
                self._process_spec_burst(*payload)
            elif mode == "fused":
                self._process_fused_burst(*payload)
            else:
                self._process_burst(*payload)

    @scheduler_only
    def _pressure_poll(self, pending) -> None:
        """Per-poll pressure work: apply the chaos hook's re-budget,
        refresh the ledger, and — while latched over the high watermark
        — run the reclaim ladder. With ``budget == 0`` and no hook this
        is two attribute checks: the no-pressure hot loop stays clean."""
        pc = self._pressure
        if self.pressure_hook is not None:
            nb = self.pressure_hook(self._work_poll_count)
            if nb is not None:
                if int(nb) < 0:
                    pc.restore_budget()
                else:
                    pc.set_budget(int(nb))
                if self._kv_tier is not None:
                    pc.host_bytes = self._kv_tier.total_bytes
                if self.flight is not None and self.flight.enabled:
                    self.flight.record({
                        "type": "pressure_budget",
                        "budget_bytes": pc.budget_bytes,
                        "restored": int(nb) < 0,
                        "host_tier_bytes": pc.host_bytes,
                    })
        if pc.budget_bytes <= 0:
            # a restore can land back on a ZERO boot budget (pressure
            # configured purely via the chaos hook): cancelled
            # speculation must still come back, or the fault window
            # would silently disable drafting for the process lifetime
            if self._spec_suppressed:
                self._resume_speculation()
            if pc.active:
                pc.update(self._ledger_components())
            return
        if self._kv_tier is not None:
            # host-RAM occupancy rides the summary/flight surface but
            # never the HBM ledger math (host RAM is not HBM — counting
            # it would double-bill every demotion). Refreshed only on
            # the budget>0 path: the no-pressure hot loop stays the two
            # attribute checks the method contract promises
            # (metrics()/flight_dump refresh on demand).
            pc.host_bytes = self._kv_tier.total_bytes
            self.sync_kv_tier_stats()
        pc.update(self._ledger_components())
        if not pc.active:
            if self._spec_suppressed:
                self._resume_speculation()
            return
        self._reclaim(pending, pc)

    @scheduler_only
    def _reclaim(self, pending, pc) -> None:
        """The reclaim ladder, cheapest rung first, until usage drops to
        the low watermark:

        1. **evict prefixes** — pure cache, zero work lost;
        2. **cancel speculation** — free the draft cache, decode falls
           back to plain bursts (greedy streams identical by the spec
           exactness contract; skipped while any stochastic lane is
           live — seeded-sampling byte-identity outranks this rung);
        3. **preempt lanes** — checkpoint a victim to host and requeue
           it for recompute-resume, freeing its slot and cache columns
           at this poll boundary (never the last lane: one lane always
           makes forward progress, so pressure cannot livelock);
        4. **shed admissions** — implicit: the latched ``active`` flag
           holds the wave loop and sheds/refuses new submits
           (:meth:`_shed_check`) until reclaim reaches the low
           watermark."""
        idx = self._prefix_index
        if pc.active and idx is not None and idx.total_bytes > 0:
            target = max(0, idx.total_bytes - pc.overshoot_bytes())
            # rung 1 is DEMOTE, not evict, when the host tier is on:
            # victims are collected under the index lock, then pulled to
            # host + SKV1-encoded into the tier out here (the slow part
            # must not hold readers off the radix walk)
            demoted: Optional[List] = (
                [] if self._kv_tier is not None else None
            )
            evicted = idx.evict_to(target, collect=demoted)
            if evicted:
                self.stats["prefix_evicted"] += evicted
                self.stats["pressure_prefix_evictions"] += evicted
                self.stats["prefix_cache_bytes"] = idx.total_bytes
                if demoted:
                    self._demote_prefix_slabs(demoted)
                if self.flight is not None and self.flight.enabled:
                    self.flight.record({
                        "type": "pressure_reclaim",
                        "action": "evict_prefix",
                        "evicted": evicted,
                        "demoted": len(demoted) if demoted else 0,
                        "used_bytes": pc.used,
                    })
                pc.update(self._ledger_components())
        if (
            pc.active
            and self._spec_burst_fn is not None
            and not self._spec_suppressed
            and all(
                s.request.temperature == 0.0 for s in self._active.values()
            )
            and all(
                j.request.temperature == 0.0 for j in self._chunked.values()
            )
        ):
            self._drain_pending(pending)
            self._suppress_speculation()
            pc.update(self._ledger_components())
        if pc.active and len(self._active) + len(self._chunked) > 1:
            self._drain_pending(pending)
            # the drain may have finished lanes outright
            pc.update(self._ledger_components())
            while pc.active and len(self._active) + len(self._chunked) > 1:
                victim = self._pick_victim()
                if victim is None:
                    break
                kind, slot = victim
                if kind == "chunked":
                    self._preempt_chunked(slot)
                else:
                    self._preempt_lane(slot)
                pc.update(self._ledger_components())

    @scheduler_only
    def _admit_cost_bytes(self, req: GenRequest) -> int:
        """Projected END-of-generation ledger footprint of admitting
        ``req``: the attention bucket its final position will need,
        priced per token. The watermark-aware admission check uses it so
        a lane that must inevitably trip the high watermark is held at
        the head of the line instead of admitted-then-preempted (the
        thrash the hysteresis gap exists to prevent)."""
        per_tok = self._kv_key_bytes
        if self.speculate_tokens > 0 and not self._spec_suppressed:
            per_tok += self._draft_kv_key_bytes
        end = min(self.max_seq, len(req.tokens) + req.max_new_tokens)
        return self._attn_need(end) * per_tok

    @scheduler_only
    def _pick_victim(self):
        """Deadline/progress-aware victim choice: chunked admissions
        first (no tokens emitted yet — preemption loses zero work and
        frees a whole staging slab), then decode lanes — best-effort
        SLO class before everything else (a multi-tenant server sheds
        its cheapest tenant's work first), deadline-free lanes before
        deadline-bearing ones (a lane that must answer soon is spared
        as long as anything else can give way), most remaining
        generation budget first within each class (the lane that would
        hold its slot longest yields it; lanes close to done are left
        to finish and free themselves).

        Tenant guard (extends the never-last-lane rule): while any
        best-effort tenant still has a preemptible lane, the ONLY live
        lane of a ``strict`` tenant is never chosen — preempting it
        would zero an SLO-critical tenant's progress to make room it
        could have taken from discountable work instead. If every
        candidate is protected (e.g. all lanes are strict singletons)
        the guard stands down and the base policy applies: pressure
        relief must still be possible."""
        if self._chunked:
            slot = max(
                self._chunked, key=lambda s: self._chunked[s].bucket
            )
            return ("chunked", slot)
        if len(self._active) <= 1:
            return None
        now = time.monotonic()

        lanes_per_tenant: Dict[Optional[str], int] = {}
        has_best_effort = False
        for s in self._active.values():
            req = s.request
            if req.tenant is not None:
                lanes_per_tenant[req.tenant] = (
                    lanes_per_tenant.get(req.tenant, 0) + 1
                )
            if req.slo == "best_effort":
                has_best_effort = True

        def protected(slot: int) -> bool:
            req = self._active[slot].request
            return (
                has_best_effort
                and req.slo == "strict"
                and req.tenant is not None
                and lanes_per_tenant.get(req.tenant, 0) <= 1
            )

        candidates = [s for s in self._active if not protected(s)]
        if not candidates:
            candidates = list(self._active)

        def order(slot: int):
            s = self._active[slot]
            req = s.request
            slack = (
                req.deadline_t - now if req.deadline_t is not None else None
            )
            return (
                # best_effort sorts lowest → preempted first; the
                # default "standard" keeps the pre-tenant ordering
                # byte-identical for single-tenant servers
                0 if req.slo == "best_effort" else 1,
                0 if slack is None else 1,
                -(slack if slack is not None else 0.0),
                -(req.max_new_tokens - len(s.emitted)),
            )

        return ("lane", min(candidates, key=order))

    @scheduler_only
    def _preempt_chunked(self, slot: int) -> None:
        """Preempt a mid-chunked-prefill admission: drop the staging
        slab and requeue the request whole (no tokens were emitted, so
        a fresh admit reproduces the identical stream from the seed)."""
        job = self._chunked.pop(slot)
        req = job.request
        self.stats["preemptions"] += 1
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "preempt", "lane": slot, "kind": "chunked",
                "prompt_tokens": len(req.tokens), "emitted": 0,
            })
        self._emit_span(req, "gen.preempt", time.monotonic(),
                        time.monotonic(), tags={"lane": slot,
                                                "kind": "chunked"})
        self._resume_queue.append(req)

    @scheduler_only
    def _checkpoint_lane(self, slot: int) -> Tuple[_Slot, GenRequest]:
        """Checkpoint one decode lane to host and free it: generated
        tokens + the lane's post-split RNG key + the sampling params
        already on the request — NOT its K/V. The slot and its cache
        columns free at this poll boundary. The caller has drained the
        pipeline, so ``emitted`` and the device state agree exactly;
        the one tiny host read here (an [2] uint32 key) is the whole
        checkpoint cost. Shared by pressure preemption
        (:meth:`_preempt_lane`), the hot-swap straggler bound, and
        graceful drain (:meth:`_do_drain`)."""
        s = self._active.pop(slot)
        req = s.request
        # the lane's CURRENT key — sampling resumes mid-stream from it,
        # which is what makes seeded-sampling output byte-identical
        # checkpoint-on vs off
        key = np.asarray(self._keys[slot]).astype(np.uint32).tolist()  # seldon-lint: disable=host-sync-hot-path (preemption/drain checkpoint: one 8-byte key read at a rare reclaim point, pipeline already drained)
        self._pos_host.pop(slot, None)
        self._masks_dirty = True
        if s.emitted:
            req.resume = {"emitted": list(s.emitted), "key": key}
        return s, req

    @scheduler_only
    def _preempt_lane(self, slot: int) -> None:
        """Preempt one decode lane (pressure ladder rung 3): checkpoint
        to host via :meth:`_checkpoint_lane` and requeue for resume.
        With the host KV tier on, the lane's exact cache columns spill
        there too (budget allowing) so the resume is a copy-back insert;
        without it — or when the tier refuses/evicts — resume falls back
        to recompute + teacher-forced replay, byte-identical either
        way."""
        s, req = self._checkpoint_lane(slot)
        if self._kv_tier is not None and req.resume is not None:
            # spill the K/V BEFORE anything can reuse the slot's columns
            # (same poll, scheduler thread — nothing dispatched since
            # the drain)
            self._checkpoint_kv_to_tier(slot, req)
        self.stats["preemptions"] += 1
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "preempt", "lane": slot, "kind": "lane",
                "prompt_tokens": len(req.tokens),
                "emitted": len(s.emitted),
                "remaining": req.max_new_tokens - len(s.emitted),
            })
        self._emit_span(
            req, "gen.preempt", time.monotonic(), time.monotonic(),
            tags={"lane": slot, "emitted": len(s.emitted)},
        )
        self._resume_queue.append(req)

    @scheduler_only
    def _suppress_speculation(self) -> None:
        """Reclaim rung 2: free the draft cache and decode with plain
        bursts. Greedy lanes keep byte-identical streams (spec greedy IS
        the target argmax decode); the caller guarantees no stochastic
        lane is live. Restored by :meth:`_resume_speculation` when
        pressure clears."""
        self._spec_suppressed = True
        self._draft_cache = None
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "pressure_reclaim", "action": "cancel_speculation",
            })
        logger.warning(
            "HBM pressure: speculation cancelled (draft cache freed); "
            "plain decode bursts until the ledger clears"
        )

    @scheduler_only
    def _resume_speculation(self) -> None:
        """Pressure cleared: reallocate the draft cache and re-derive
        every live lane's draft prefix from prompt + generated-so-far
        (the draft K/V is a pure function of the tokens). Runs BEFORE
        admissions resume in the same poll, so no lane is ever admitted
        into a half-restored draft world."""
        self._draft_cache = self._unstack_cache(
            self.draft_model,
            self._cache_sharding_for(self.draft_model.cfg.n_kv_heads),
        )
        for slot, s in self._active.items():
            full = (
                s.request.tokens + s.emitted[:-1]
                if s.emitted else s.request.tokens
            )
            self._draft_admit_tokens(slot, full)
        self._spec_suppressed = False
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "pressure_reclaim", "action": "resume_speculation",
                "lanes": len(self._active),
            })

    @scheduler_only
    def _replay_emitted(self, slot: int, start_pos: int,
                        replay_toks: List[int]) -> None:
        """Teacher-forced decode replay: rebuild positions
        ``[start_pos, start_pos + len(replay_toks))`` of ``slot``'s
        cache from the already-emitted tokens, through the SAME fused
        decode step that wrote them originally (see replay_burst — a
        prefill recompute differs at bf16 and breaks byte-identity).
        Chunked to the burst length ``k`` so one executable exists per
        (k, attn_len), never per resume length."""
        import jax.numpy as jnp

        if not replay_toks:
            return
        k = self._k
        attn_len = self._attn_need(start_pos + len(replay_toks))
        lane_ix = jnp.asarray([slot], jnp.int32)
        for off in range(0, len(replay_toks), k):
            chunk = replay_toks[off:off + k]
            toks = np.zeros((k,), np.int32)
            toks[: len(chunk)] = chunk
            act = np.zeros((k,), bool)
            act[: len(chunk)] = True
            with self._prof.measure(
                "replay", variant=f"k{k}b{attn_len}", tenant="",
                bytes_read=self._param_bytes
                + len(chunk) * self._kv_key_bytes,
                tokens=len(chunk),
            ) as _m:
                self._cache = self._replay_fn(
                    self.params, self._cache, lane_ix, jnp.asarray(toks),
                    jnp.asarray(act), jnp.int32(start_pos + off), attn_len,
                )
                _m.sync(self._cache["k"])
        self.stats["steps"] += -(-len(replay_toks) // k) * k
        self.stats["lane_steps"] += -(-len(replay_toks) // k) * k

    @scheduler_only
    def _activate_resumed(self, slot: int, req: GenRequest,
                          emitted: List[int], replay: bool = True) -> None:
        """Shared tail of the plain, chunked, and tier-copy-back resume
        paths: replay the emitted tokens' K/V (``replay=False`` when a
        tier checkpoint already restored the exact cache columns),
        re-derive the draft prefix (speculation), and re-activate the
        lane with crediting continuing AFTER the checkpoint
        (already-delivered stream spans are never re-sent;
        first_pending False keeps the insert's token — emitted[-1] —
        from being credited twice)."""
        n = len(req.tokens)
        if replay:
            self._replay_emitted(slot, n, emitted[:-1])
        if self._spec_active():
            self._draft_admit_tokens(slot, req.tokens + emitted[:-1])
        s = _Slot(request=req)
        s.emitted = list(emitted)
        s.first_pending = False
        s.dispatched = len(emitted)
        self._active[slot] = s
        self._pos_host[slot] = n + len(emitted) - 1
        self._masks_dirty = True
        req.resume = None
        self.stats["preempt_resumes"] += 1
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "preempt_resume", "lane": slot,
                "prompt_tokens": n,
                "replayed_tokens": max(0, len(emitted) - 1) if replay else 0,
                "copyback": not replay,
                "emitted": len(emitted),
                "cache_hit_tokens": req.cache_hit_tokens,
            })

    @scheduler_only
    def _resume_from_tier(self, slot: int, req: GenRequest,
                          emitted: List[int], first_tok, lane_key,
                          end_pos: int, tier_key) -> bool:
        """Copy-back resume: take the lane's tier checkpoint (one-shot),
        ``device_put`` the stored cache columns, and insert them with
        the checkpointed continuation registers. True when the lane is
        live again; False sends the caller down the recompute+replay
        fallback (entry evicted, stale version, or corrupt — the tier
        already dropped a corrupt entry, typed, before any lane state
        was touched)."""
        from ..tracing import device_trace
        from .disagg import DisaggError, prompt_hash

        try:
            ent = self._kv_tier.take_ckpt(tier_key, self.weight_version)
        except DisaggError as e:
            logger.warning("kv tier checkpoint dropped: %s", e)
            return False
        if ent is None:
            return False
        meta, host = ent
        if int(meta.get("pos", -1)) != end_pos:
            # a drifted checkpoint must never splice: the registers and
            # the cache would disagree on where the lane is
            logger.warning(
                "kv tier checkpoint position %s != lane end %d — replaying",
                meta.get("pos"), end_pos,
            )
            return False
        slab_dev = self._upload_slab(host)
        with self._prof.measure(
            "insert", variant="tier", tenant=req.tenant or "",
            bytes_read=int(meta.get("width", 0)) * self._kv_key_bytes,
            tokens=end_pos,
        ) as _m, device_trace("gen.lane_insert"):
            self._cache, self._cur_tok, self._pos, self._keys = (
                self._insert_fn(
                    self._cache, slab_dev, slot, first_tok, end_pos,
                    lane_key, self._cur_tok, self._pos, self._keys,
                )
            )
            _m.sync(self._cur_tok)
        self.stats["kv_tier_promotions"] += 1
        if self.flight is not None and self.flight.enabled:
            self.flight.record({
                "type": "tier_hit", "kind": "ckpt", "source": "local",
                "lane": slot, "tokens": end_pos,
                "phash": prompt_hash(req.tokens)[:8],
            })
            self.flight.record({
                "type": "kv_promote", "kind": "ckpt", "source": "local",
                "lane": slot, "tokens": end_pos,
                "bytes": int(host["k"].nbytes) + int(host["v"].nbytes),
                "phash": prompt_hash(req.tokens)[:8],
            })
        self._activate_resumed(slot, req, emitted, replay=False)
        return True

    @scheduler_only
    def _admit_resume(self, slot: int, req: GenRequest) -> None:
        """Recompute-resume a preempted request: rebuild the PROMPT K/V
        through the ordinary admission machinery (bucketed prefill, a
        prefix-cache hit splicing naturally, or the PR 3 staging-slab
        chunked path for long prompts), insert with the checkpointed
        continuation state instead of the prefill's own sample —
        ``cur_tok`` = the last emitted token, ``pos`` = the exact device
        position the preempted lane held, ``key`` = the checkpointed
        post-split RNG key — then replay the emitted tokens' K/V with
        the decode step itself (:meth:`_replay_emitted`). Decode from
        there is the same computation the uninterrupted lane would have
        run, so greedy AND seeded-sampling outputs are byte-identical
        preempt-on vs off."""
        import jax.numpy as jnp

        from ..tracing import device_trace

        ck = req.resume
        emitted = list(ck["emitted"])
        n = len(req.tokens)
        end_pos = n + len(emitted) - 1
        first_tok = jnp.int32(int(emitted[-1]))
        lane_key = jnp.asarray(np.asarray(ck["key"], np.uint32))
        t_admit = time.monotonic()
        # the tier key is POPPED here whatever happens next: take_ckpt
        # is one-shot, so a later re-preemption must re-checkpoint under
        # a fresh key — a stale key left behind would make the next
        # resume count a phantom replay fallback
        tier_key = (
            ck.pop("tier", None) if self._kv_tier is not None else None
        )
        if tier_key is not None:
            # copy-back fast path: the preemption spilled this lane's
            # exact cache columns to the host tier — device_put them
            # back through the ordinary insert executable (cur_tok/pos/
            # key restored to the checkpointed registers) and skip BOTH
            # the prompt recompute and the teacher-forced replay. The
            # restored bytes are the bytes the lane held, so decode from
            # here is the identical computation either way.
            if self._resume_from_tier(slot, req, emitted, first_tok,
                                      lane_key, end_pos, tier_key):
                self._emit_span(
                    req, "gen.resume", t_admit, time.monotonic(),
                    tags={"lane": slot, "emitted": len(emitted),
                          "copyback": True},
                )
                return
            # the tier evicted/refused/corrupted the entry: recompute +
            # replay below is the documented fallback — count it so the
            # "spill, don't destroy" win stays measurable
            self.stats["kv_tier_replay_fallbacks"] += 1
        hit = self._prefix_match(req)
        C = self.prefill_chunk
        if C and (
            (hit is None and self._bucket(n) > C)
            or (hit is not None and n - hit[0] > C)
        ):
            # long prompt: rebuild through the SAME staging-slab chunked
            # path the original admission used (byte-identity again —
            # chunked and whole prefill K/V need not agree at bf16)
            self._start_chunked(slot, req, hit=hit,
                                resume=(emitted, lane_key))
            self._emit_span(
                req, "gen.resume", t_admit, time.monotonic(),
                tags={"lane": slot, "emitted": len(emitted),
                      "chunked": True},
            )
            return
        if hit is not None:
            m, slab = hit
            wb = self._bucket(n - m)
            suffix = np.zeros((1, wb), np.int32)
            suffix[0, : n - m] = req.tokens[m:]
            with self._prof.measure(
                "prefill", variant=f"px{wb}", tenant=req.tenant or "",
                bytes_read=self._param_bytes + wb * self._kv_key_bytes,
                tokens=wb,
            ) as _m, device_trace("gen.prefill"):
                _f, suffix_slab, _k = self._prefix_prefill_fn(
                    self.params, slab, jnp.asarray(suffix), jnp.int32(m),
                    jnp.asarray([n - 1 - m], jnp.int32),
                    jnp.int32(req.seed), jnp.float32(req.temperature),
                )
                _m.sync(suffix_slab)
            with self._prof.measure(
                "insert", variant=f"px{wb}", tenant=req.tenant or "",
                bytes_read=(m + wb) * self._kv_key_bytes, tokens=end_pos,
            ) as _m, device_trace("gen.lane_insert"):
                self._cache, self._cur_tok, self._pos, self._keys = (
                    self._insert_prefix_fn(
                        self._cache, slab, suffix_slab, slot, jnp.int32(m),
                        first_tok, end_pos, lane_key,
                        self._cur_tok, self._pos, self._keys,
                    )
                )
                _m.sync(self._cur_tok)
            req.cache_hit_tokens = m
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_saved"] += m
            self.stats["prefill_steps"] += 1
            self.stats["prefill_tokens"] += wb
        else:
            bucket = self._bucket(n)
            prompt = np.zeros((1, bucket), np.int32)
            prompt[0, :n] = req.tokens
            with self._prof.measure(
                "prefill", variant=f"p{bucket}", tenant=req.tenant or "",
                bytes_read=self._param_bytes + bucket * self._kv_key_bytes,
                tokens=bucket,
            ) as _m, device_trace("gen.prefill"):
                _f, cache_one, _k = self._prefill_fn(
                    self.params, jnp.asarray(prompt),
                    jnp.asarray([n - 1], jnp.int32),
                    jnp.int32(req.seed), jnp.float32(req.temperature),
                )
                _m.sync(cache_one)
            with self._prof.measure(
                "insert", variant=f"b{bucket}", tenant=req.tenant or "",
                bytes_read=bucket * self._kv_key_bytes, tokens=end_pos,
            ) as _m, device_trace("gen.lane_insert"):
                self._cache, self._cur_tok, self._pos, self._keys = (
                    self._insert_fn(
                        self._cache, cache_one, slot, first_tok, end_pos,
                        lane_key, self._cur_tok, self._pos, self._keys,
                    )
                )
                _m.sync(self._cur_tok)
            if self._prefix_index is not None:
                self.stats["prefix_misses"] += 1
            self.stats["prefill_steps"] += 1
            self.stats["prefill_tokens"] += bucket
        self._activate_resumed(slot, req, emitted)
        self._emit_span(
            req, "gen.resume", t_admit, time.monotonic(),
            tags={"lane": slot, "emitted": len(emitted),
                  "cache_hit_tokens": req.cache_hit_tokens},
        )

    @scheduler_only
    def _admit(self, slot: int, req: GenRequest, hit=None) -> None:
        # ``hit``: a (match_len, slab) the wave-routing loop already
        # computed — passed through so the radix walk (and its LRU touch)
        # runs once per admission, not twice
        import jax.numpy as jnp

        from ..tracing import device_trace

        n = len(req.tokens)
        t_admit = time.monotonic()
        req.admit_t = t_admit
        if hit is None:
            hit = self._prefix_match(req)
        if hit is not None:
            # cache hit: splice the donor slab, prefill ONLY the suffix
            # (same bucketed machinery, on the shorter remainder)
            m, slab = hit
            wb = self._bucket(n - m)
            suffix = np.zeros((1, wb), np.int32)
            suffix[0, : n - m] = req.tokens[m:]
            with self._prof.measure(
                "prefill", variant=f"px{wb}", tenant=req.tenant or "",
                bytes_read=self._param_bytes + wb * self._kv_key_bytes,
                tokens=wb,
            ) as _m, device_trace("gen.prefill"):
                first, suffix_slab, lane_key = self._prefix_prefill_fn(
                    self.params,
                    slab,
                    jnp.asarray(suffix),
                    jnp.int32(m),
                    jnp.asarray([n - 1 - m], jnp.int32),
                    jnp.int32(req.seed),
                    jnp.float32(req.temperature),
                )
                _m.sync(suffix_slab)
            t_insert = time.monotonic()
            with self._prof.measure(
                "insert", variant=f"px{wb}", tenant=req.tenant or "",
                bytes_read=(m + wb) * self._kv_key_bytes, tokens=n,
            ) as _m, device_trace("gen.lane_insert"):
                self._cache, self._cur_tok, self._pos, self._keys = (
                    self._insert_prefix_fn(
                        self._cache, slab, suffix_slab, slot, jnp.int32(m),
                        first[0], n, lane_key,
                        self._cur_tok, self._pos, self._keys,
                    )
                )
                _m.sync(self._cur_tok)
            req.cache_hit_tokens = m
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_saved"] += m
            self.stats["prefill_steps"] += 1
            self.stats["prefill_tokens"] += wb
            self._emit_span(
                req, "gen.prefill", t_admit, t_insert,
                tags={"lane": slot, "bucket": wb, "cache_hit_tokens": m,
                      "dispatch": True},
            )
        else:
            bucket = self._bucket(n)
            prompt = np.zeros((1, bucket), np.int32)
            prompt[0, :n] = req.tokens
            with self._prof.measure(
                "prefill", variant=f"p{bucket}", tenant=req.tenant or "",
                bytes_read=self._param_bytes + bucket * self._kv_key_bytes,
                tokens=bucket,
            ) as _m, device_trace("gen.prefill"):
                first, cache_one, lane_key = self._prefill_fn(
                    self.params,
                    jnp.asarray(prompt),
                    jnp.asarray([n - 1], jnp.int32),
                    jnp.int32(req.seed),
                    jnp.float32(req.temperature),
                )
                _m.sync(cache_one)
            t_insert = time.monotonic()
            with self._prof.measure(
                "insert", variant=f"b{bucket}", tenant=req.tenant or "",
                bytes_read=bucket * self._kv_key_bytes, tokens=n,
            ) as _m, device_trace("gen.lane_insert"):
                self._cache, self._cur_tok, self._pos, self._keys = self._insert_fn(
                    self._cache, cache_one, slot, first[0], n, lane_key,
                    self._cur_tok, self._pos, self._keys,
                )
                _m.sync(self._cur_tok)
            if self._prefix_index is not None:
                self.stats["prefix_misses"] += 1
            self.stats["prefill_steps"] += 1
            self.stats["prefill_tokens"] += bucket
            self._emit_span(
                req, "gen.prefill", t_admit, t_insert,
                tags={"lane": slot, "bucket": bucket, "dispatch": True},
            )
        t_inserted = time.monotonic()
        req.decode_start_t = t_inserted
        self._emit_span(req, "gen.lane_insert", t_insert, t_inserted,
                        tags={"lane": slot, "dispatch": True})
        self._emit_span(
            req, "gen.queue_wait", req.submit_t, t_admit,
            tags={"lane": slot,
                  "cache_hit_tokens": req.cache_hit_tokens},
        )
        if self._spec_active():
            # the draft needs the prompt's K/V prefix too so its proposals
            # attend over the real context (see _draft_admit: re-derived
            # from the full prompt, never cached — the radix pool holds
            # only target K/V)
            self._draft_admit(slot, req)
        # no host read here: prefill + insert stay fully async; the first
        # token reaches the host with the next burst's sync
        self._active[slot] = _Slot(request=req)
        self._pos_host[slot] = n
        self._masks_dirty = True
        self.stats["admitted"] += 1

    @scheduler_only
    def _admit_many(self, slots: List[int], reqs: List[GenRequest], bucket: int) -> None:
        """Admit m same-bucket requests with ONE batched prefill forward +
        ONE batched insert (see prefill_many). Only used without
        speculation — the draft cache path stays per-request."""
        import jax.numpy as jnp

        from ..tracing import device_trace

        m = len(reqs)
        t_admit = time.monotonic()
        prompts = np.zeros((m, bucket), np.int32)
        last = np.zeros((m,), np.int32)
        seeds = np.zeros((m,), np.int32)
        temps = np.zeros((m,), np.float32)
        for i, req in enumerate(reqs):
            n = len(req.tokens)
            prompts[i, :n] = req.tokens
            last[i] = n - 1
            seeds[i] = req.seed
            temps[i] = req.temperature
        _wave_tenant = ""
        if self._prof.enabled:
            _ts = {req.tenant for req in reqs}
            if len(_ts) == 1 and None not in _ts:
                _wave_tenant = _ts.pop() or ""
        with self._prof.measure(
            "prefill", variant=f"m{m}p{bucket}", tenant=_wave_tenant,
            bytes_read=self._param_bytes + m * bucket * self._kv_key_bytes,
            tokens=m * bucket,
        ) as _pm, device_trace("gen.prefill"):
            firsts, slab, lane_keys = self._prefill_many_fn(
                self.params, jnp.asarray(prompts), jnp.asarray(last),
                jnp.asarray(seeds), jnp.asarray(temps),
            )
            _pm.sync(slab)
        with self._prof.measure(
            "insert", variant=f"m{m}b{bucket}", tenant=_wave_tenant,
            bytes_read=m * bucket * self._kv_key_bytes, tokens=m * bucket,
        ) as _im, device_trace("gen.lane_insert"):
            self._cache, self._cur_tok, self._pos, self._keys = self._insert_many_fn(
                self._cache, slab, jnp.asarray(np.asarray(slots, np.int32)),
                firsts, jnp.asarray(last + 1), lane_keys,
                self._cur_tok, self._pos, self._keys,
            )
            _im.sync(self._cur_tok)
        t_inserted = time.monotonic()
        for slot, req in zip(slots, reqs):
            req.admit_t = t_admit
            req.decode_start_t = t_inserted
            self._emit_span(
                req, "gen.queue_wait", req.submit_t, t_admit,
                tags={"lane": slot, "batched": m},
            )
            self._emit_span(
                req, "gen.prefill", t_admit, t_inserted,
                tags={"lane": slot, "bucket": bucket, "batched": m,
                      "dispatch": True},
            )
            self._active[slot] = _Slot(request=req)
            self._pos_host[slot] = len(req.tokens)
        self._masks_dirty = True
        self.stats["admitted"] += m
        self.stats["prefill_steps"] += 1
        self.stats["prefill_tokens"] += m * bucket
        if self._prefix_index is not None:
            self.stats["prefix_misses"] += m

    @scheduler_only
    def _resolve(self, s: _Slot) -> None:
        # a trailing eos token is kept in the output, like HF generate.
        # `finished` counts requests that ran to completion; `cancelled`
        # counts abandonments (queued or mid-decode) — disjoint, so
        # finished + cancelled = all requests ever resolved
        s.credit_done = True
        req = s.request
        now = time.monotonic()
        if req.future.cancelled():
            self.stats["cancelled"] += 1
            if req.admit_t:
                # the lane was reclaimed mid-decode (client disconnect /
                # deadline): the timeline still shows the residency it
                # burned, attributed as a cancellation
                self._emit_span(
                    req, "gen.decode", req.decode_start_t or req.admit_t, now,
                    tags={"outcome": "cancelled", "tokens": len(s.emitted)},
                )
            return
        # SLO sample: queue wait / TTFT / TPOT of this completed request.
        # TTFT and queue wait are submit-anchored (what the client saw);
        # TPOT averages the inter-token gap over the credited stream.
        # Recorded BEFORE set_result: resolving the future wakes the
        # predict thread, whose response path drains slo_pending via
        # metrics() — the sample and the gen.decode span must already
        # exist so a request's own response carries its own triple.
        if req.submit_t:
            n_tok = len(s.emitted)
            first = req.first_tok_t or now
            queue_wait = max(0.0, (req.admit_t or now) - req.submit_t)
            ttft = max(0.0, first - req.submit_t)
            # a 1-token generation has no inter-token interval: tpot is
            # None so the reservoir percentiles, the TIMER export, and
            # the span tag all skip it the same way instead of counting
            # a meaningless 0.0 in some views but not others
            tpot = (now - first) / (n_tok - 1) if n_tok > 1 else None
            self.stats["slo_samples"] += 1
            self.stats["queue_wait_s_sum"] += queue_wait
            self.stats["ttft_s_sum"] += ttft
            if tpot is not None:
                self.stats["tpot_s_sum"] += tpot
            self.slo_pending.append((queue_wait, ttft, tpot))
            self.slo_recent.append((queue_wait, ttft, tpot))
            if req.tenant is not None:
                # per-tenant split of the same triple: the TenantScheduler
                # reads tenant_slo_recent as its TTFT feedback signal and
                # the server drains tenant_slo_pending into tagged TIMER
                # metrics — one sample feeds both, recorded here so a
                # tenant's own response carries its own numbers
                sums = self.tenant_slo.setdefault(req.tenant, {
                    "slo_samples": 0.0, "queue_wait_s_sum": 0.0,
                    "ttft_s_sum": 0.0, "tpot_s_sum": 0.0, "finished": 0.0,
                })
                sums["slo_samples"] += 1
                sums["finished"] += 1
                sums["queue_wait_s_sum"] += queue_wait
                sums["ttft_s_sum"] += ttft
                if tpot is not None:
                    sums["tpot_s_sum"] += tpot
                self.tenant_slo_pending.setdefault(
                    req.tenant, collections.deque(maxlen=1024)
                ).append((queue_wait, ttft, tpot))
                self.tenant_slo_recent.setdefault(
                    req.tenant, collections.deque(maxlen=512)
                ).append((queue_wait, ttft, tpot))
            if req.admit_t:
                tags = {"outcome": "complete", "tokens": n_tok,
                        "ttft_ms": round(ttft * 1e3, 3)}
                if tpot is not None:
                    tags["tpot_ms"] = round(tpot * 1e3, 3)
                self._emit_span(
                    req, "gen.decode", req.decode_start_t or req.admit_t,
                    now, tags=tags,
                )
        if not req.future.done():
            req.future.set_result(req.tokens + s.emitted)
        self.stats["finished"] += 1
        # completion timestamp feeds the observed service rate that the
        # admit-queue shed uses for its expected-wait estimate
        self._finish_times.append(now)

    @scheduler_only
    def _finish(self, slot: int) -> None:
        s = self._active.pop(slot)
        # publish while the lane still holds this request's prompt K/V —
        # the next occupant's insert is dispatched after the extract, so
        # stream order keeps the slab coherent
        self._maybe_publish(slot, s)
        self._pos_host.pop(slot, None)
        self._masks_dirty = True
        self._resolve(s)

    @scheduler_only
    def _check_done(self) -> None:
        for slot in list(self._active):
            s = self._active[slot]
            req = s.request
            if req.future.cancelled():
                # caller gave up (client disconnect / deadline): reclaim the
                # lane instead of decoding the rest of its budget for no one
                self._finish(slot)
                continue
            if len(s.emitted) >= req.max_new_tokens or (
                req.eos_id is not None and s.emitted and s.emitted[-1] == req.eos_id
            ):
                self._finish(slot)

    @scheduler_only
    def _credit(self, s: _Slot, tokens) -> bool:
        """Append tokens to a request; True once it is done (budget/eos —
        the caller drops the rest of the burst's tokens for this lane)."""
        req = s.request
        start = len(s.emitted)
        if start == 0 and len(tokens) and req.first_tok_t == 0.0:
            # first span of credited tokens = the client-visible TTFT
            # moment (a float store per REQUEST, not per token)
            req.first_tok_t = time.monotonic()
        done = False
        for t in tokens:
            s.emitted.append(int(t))
            self.stats["tokens"] += 1
            if len(s.emitted) >= req.max_new_tokens or (
                req.eos_id is not None and int(t) == req.eos_id
            ):
                done = True
                break
        if req.on_tokens is not None and len(s.emitted) > start:
            try:
                req.on_tokens(list(s.emitted[start:]))
            except Exception:  # noqa: BLE001 - consumer bugs can't stall decode
                logger.exception("on_tokens callback failed")
        return done

    @scheduler_only
    def _process_burst(self, toks_dev, snapshot) -> None:
        """Credit one burst's tokens to the requests that occupied each lane
        AT DISPATCH TIME. Bursts execute on the device stream in dispatch
        order and any re-admission insert is dispatched after them, so the
        snapshot occupant is always the request the rows belong to — even
        when the lane was pre-freed and re-admitted before this read. A
        request whose output is already complete (``credit_done``) is
        skipped: its remaining rows are overshoot decode, dropped by
        design. ``snapshot[slot] = (s, start_row, col)`` — col is the
        lane's COLUMN in this burst's token matrix (its gathered row for
        a depth-group sub-burst, the slot id for a whole-batch burst)."""
        host_toks = np.asarray(toks_dev)  # the burst's one host sync
        for slot, (s, start, col) in snapshot.items():
            if s.credit_done:
                continue
            if self._credit(s, host_toks[start:, col]):
                if self._active.get(slot) is s:
                    self._finish(slot)
                else:
                    self._resolve(s)  # lane was pre-freed at dispatch time
        self._check_done()

    @scheduler_only
    def _process_fused_burst(self, toks_dev, counts_dev, done_dev, snapshot,
                             k) -> None:
        """Credit one stop-aware fused burst. Per lane, exactly
        ``counts[col]`` tokens were emitted before its on-device done
        mask froze it (stop token / budget), so — unlike
        :meth:`_process_burst` — no overshoot rows exist to drop; the
        host just credits the counted span (row 0 still carries the
        deferred prefill first token). ``done_dev`` is the device's own
        verdict; crediting re-derives it from the tokens (``_credit``
        checks eos/budget per token), so the two can never disagree
        without the identity tests catching it. Like
        :meth:`_process_spec_burst`, tightens the host position bound
        from the worst-case k advance to the lane's actual alive steps —
        a lane frozen early must not inflate the pressure ledger or the
        attention-bucket need until the host observes it."""
        host_toks = np.asarray(toks_dev)  # the burst's one host sync
        counts = np.asarray(counts_dev)
        for slot, (s, start, col) in snapshot.items():
            if self._active.get(slot) is s and slot in self._pos_host:
                self._pos_host[slot] -= k - int(counts[col])
            if s.credit_done:
                continue
            span = host_toks[start: 1 + int(counts[col]), col]
            if not len(span):
                continue
            if self._credit(s, span):
                if self._active.get(slot) is s:
                    self._finish(slot)
                else:
                    self._resolve(s)  # lane was pre-freed at dispatch time
        self._check_done()

    @scheduler_only
    def _process_spec_burst(self, start_tok_dev, toks_dev, counts_dev, snapshot, k) -> None:
        """Spec-mode crediting: per round, a lane emitted counts[r, slot]
        tokens (accepted drafts + the target's correction). Also tightens
        the host position bound from worst-case (k*(gamma+1)) to actual."""
        start_tok = np.asarray(start_tok_dev)
        host_toks = np.asarray(toks_dev)  # [k, S, gamma+1]
        counts = np.asarray(counts_dev)  # [k, S]
        worst = k * (self.speculate_tokens + 1)
        # acceptance telemetry over ALL lanes that ran rounds (device-true,
        # independent of host-side crediting cutoffs)
        ran = counts > 0
        self.stats["spec_rounds"] += int(ran.sum())
        self.stats["spec_emitted"] += int(counts.sum())
        for slot, (s, start) in snapshot.items():
            if self._active.get(slot) is not s:
                continue
            actual = int(counts[:, slot].sum())
            if slot in self._pos_host:
                self._pos_host[slot] -= worst - actual
            done = False
            if start == 0:
                done = self._credit(s, [int(start_tok[slot])])
            for r in range(k):
                if done:
                    break
                done = self._credit(s, host_toks[r, slot, : int(counts[r, slot])])
        self._check_done()

    def _run(self) -> None:
        """Scheduler thread entrypoint: the supervision shell around the
        poll loop. A clean ``close()`` exits; a loop death fails in-flight
        work with a typed :class:`BatcherDead` and — crash-loop budget
        permitting — rebuilds the device state and resumes, so a
        transient device/driver fault costs seconds, not a pod."""
        self._started.set()
        while not self._stop.is_set():
            if not self._loop():
                return

    @scheduler_only
    def _fail_inflight(self, pending, err: Exception) -> None:
        """Fail every request the dead loop had in flight: active lanes,
        pre-freed lanes living only in pending-burst snapshots (without
        this sweep their callers would block forever), and chunked
        admissions holding reserved lanes but no ``_active`` entry.
        Queued-not-admitted requests are NOT drained here — their prompts
        are host-side, so they survive a supervised restart and only fail
        once the batcher latches dead."""
        for slot in list(self._active):
            s = self._active.pop(slot)
            if not s.request.future.done():
                s.request.future.set_exception(err)
        for _mode, payload in pending:
            snap = payload[3] if _mode in ("spec", "fused") else payload[1]
            for entry in snap.values():
                s = entry[0]
                if not s.request.future.done():
                    s.request.future.set_exception(err)
        for slot in list(self._chunked):
            job = self._chunked.pop(slot)
            if not job.request.future.done():
                job.request.future.set_exception(err)

    @scheduler_only
    def _crash_recover(self, pending) -> bool:
        """Supervise one loop death (scheduler thread). True = the loop
        may resume on rebuilt device state; False = the batcher is done
        for good — the crash-loop budget is exhausted (``health``
        latches ``"dead"``, readiness goes red, the reconciler replaces
        this member) or ``close()`` landed mid-backoff. A failed rebuild
        (the device may still be sick) consumes another budget slot and
        backs off again."""
        while True:
            now = time.monotonic()
            if (self._last_crash_t
                    and now - self._last_crash_t > self.restart_window_s):
                self._restarts = 0  # served healthily long enough
            self._last_crash_t = now
            self._restarts += 1
            attempt = self._restarts
            exhausted = attempt > self.restart_budget
            backoff = min(
                self.restart_backoff_s * (2 ** (attempt - 1)), 30.0
            )
            if exhausted:
                self.health = "dead"
                err = self._dead_error()
            else:
                self.health = "restarting"
                err = BatcherDead(
                    f"continuous batcher died; restarting "
                    f"(attempt {attempt}/{self.restart_budget})",
                    retry_after_s=max(backoff, 0.5),
                )
            self._fail_inflight(pending, err)
            pending = ()  # later iterations have nothing new in flight
            self._fail_pending_swap(err)
            # a drain staged when the loop died cannot complete: fail it
            # typed (the supervisor's health writes below replace the
            # "draining" latch, so a successful restart resumes service)
            self._fail_pending_drain(err)
            if self.flight is not None and self.flight.enabled:
                self.flight.record({
                    "type": "batcher_restart",
                    "attempt": attempt,
                    "budget": self.restart_budget,
                    "backoff_s": round(backoff, 3),
                    "outcome": "latched_dead" if exhausted else "restarting",
                })
            if exhausted:
                logger.error(
                    "continuous batcher crash-loop budget exhausted after "
                    "%d restarts; latching unready for replacement",
                    self.restart_budget,
                )
                self._stop.set()
                self._drain_queue(err)
                return False
            if self._stop.wait(backoff):
                self._drain_queue(self._dead_error())
                return False  # close() landed while backing off
            try:
                self._rebuild()
            except Exception:  # noqa: BLE001 - rebuild on a sick device
                logger.exception("batcher rebuild failed (attempt %d)", attempt)
                continue
            self.stats["batcher_restarts"] += 1
            self.health = "serving"
            logger.warning(
                "continuous batcher restarted (%d/%d): fresh cache, prefix "
                "index reset, executables re-warmed",
                attempt, self.restart_budget,
            )
            return True

    @scheduler_only
    def _loop(self) -> bool:
        """One supervised run of the poll loop. Returns False on a clean
        ``close()`` stop, or :meth:`_crash_recover`'s verdict after a
        loop death (True = run again on rebuilt state)."""
        import collections

        import jax.numpy as jnp

        from ..tracing import device_trace

        temps = np.zeros((self.slots,), np.float32)
        # in-flight bursts, oldest first: (device tokens, lane snapshot)
        pending: "collections.deque" = collections.deque()
        try:
            while not self._stop.is_set():
                # chaos hook: an injected poll death here exercises the
                # REAL supervision path end to end (faults.py wires it
                # from the SELDON_FAULTS scheduler section)
                self._poll_count += 1
                if self.fault_hook is not None:
                    self.fault_hook(self._poll_count)
                # multi-tenancy: publish the poll clock to the
                # TenantScheduler so its starvation bound is measured in
                # scheduler polls, not wall time (weightpager.py)
                if self.tenant_hook is not None:
                    self.tenant_hook(self._poll_count)
                # HBM pressure: refresh the ledger and, over the high
                # watermark, run the reclaim ladder (may drain `pending`
                # and preempt lanes at this poll boundary). Two attribute
                # checks when the subsystem is off.
                if (
                    self._active or self._chunked or pending
                    or self._resume_queue or not self._queue.empty()
                ):
                    self._work_poll_count += 1
                if self.pressure_hook is not None or (
                    self._pressure.budget_bytes > 0
                ):
                    self._pressure_poll(pending)
                pressure_hold = (
                    self._pressure.budget_bytes > 0 and self._pressure.active
                )
                # flight recorder: counter snapshot at poll start so the
                # poll record carries DELTAS (what this poll did), plus the
                # decode plan captured at dispatch below. One small dict
                # per working poll — never per token.
                flight = self.flight if (
                    self.flight is not None and self.flight.enabled
                ) else None
                if flight is not None:
                    f0 = (
                        self.stats["admitted"], self.stats["prefill_chunks"],
                        self.stats["prefix_hits"], self.stats["prefix_evicted"],
                    )
                poll_plan: Optional[Dict[str, Any]] = None
                # -- live weight swap: drain, then flip at a poll boundary.
                # While a swap is staged, admissions HOLD (queued submits
                # wait) so in-flight lanes — decode, chunked prefill, and
                # every pipelined burst — finish on the OLD version; the
                # flip happens only when all three are empty, so no burst
                # ever mixes weight versions.
                # unlocked read: GIL-atomic, and a one-poll-late sighting
                # of a freshly staged swap is harmless — _do_swap
                # re-validates `self._pending_swap is not swap` under the
                # lock before flipping. Keeps the no-rollout hot loop free
                # of a per-poll mutex.
                # -- graceful drain: checkpoint everything at this poll
                # boundary and hand it to the caller for migration.
                # Admissions are already refused (health flipped to
                # "draining" on the caller thread), so after this the
                # loop simply idles until close().
                dj = self._pending_drain
                if dj is not None:
                    self._do_drain(dj, pending)
                    continue
                # -- planner retune: apply staged knob changes HERE, at
                # the top of the poll, before this poll's _fused_k
                # snapshot and admissions read any knob — so one poll
                # never sees a half-applied config. Unlocked read,
                # GIL-atomic, same re-validation discipline as swap.
                rj = self._pending_retune
                if rj is not None:
                    self._do_retune(rj)
                swap = self._pending_swap
                if swap is not None:
                    if swap.drain_lanes is None:
                        swap.drain_lanes = (
                            len(self._active) + len(self._chunked)
                        )
                    if self._active or self._chunked or pending:
                        swap.waited_polls += 1
                        if (
                            self.swap_drain_ms > 0
                            and swap.staged_t
                            and time.monotonic() - swap.staged_t
                            >= self.swap_drain_ms / 1e3
                        ):
                            # straggler bound: stop waiting on long
                            # generations — checkpoint them and flip
                            self._swap_preempt_stragglers(pending)
                    if not self._active and not self._chunked and not pending:
                        self._do_swap(swap)
                        swap = None
                # admit as many queued requests as there are free slots —
                # same-bucket admissions are grouped so m lanes share one
                # batched prefill forward (pow2 chunks bound executables)
                wave: List[GenRequest] = []
                busy = len(self._active) + len(self._chunked)
                wave_cost = 0
                while (
                    swap is None
                    and not pressure_hold
                    and busy + len(wave) < self.slots
                ):
                    # preempted requests resume AHEAD of newer work —
                    # their recompute is a price already paid once
                    if self._resume_queue:
                        req = self._resume_queue.popleft()
                    else:
                        try:
                            req = self._queue.get_nowait()
                        except queue.Empty:
                            break
                    if req.future.cancelled():
                        self.stats["cancelled"] += 1
                        # a preempted-then-cancelled request must not
                        # leave its K/V checkpoint pinning tier budget
                        self._release_tier_ckpt(req)
                        continue  # caller gave up while queued
                    if self._pressure.budget_bytes > 0:
                        # watermark-aware admission: if this request's
                        # end-of-generation footprint would trip the high
                        # watermark, hold it at the HEAD of the line until
                        # completions/reclaim open headroom — admitting it
                        # now would only earn it a preemption. With no
                        # other lane live it always admits: one lane of
                        # forward progress can never starve.
                        cost = self._admit_cost_bytes(req)
                        if (busy + len(wave)) and (
                            self._pressure.used + wave_cost + cost
                            >= self._pressure.high_bytes
                        ):
                            self._resume_queue.appendleft(req)
                            break
                        wave_cost += cost
                    wave.append(req)
                if wave:
                    free_iter = iter(
                        i for i in range(self.slots)
                        if i not in self._active and i not in self._chunked
                    )
                    chunk_size = self.prefill_chunk
                    by_bucket: Dict[int, List[GenRequest]] = {}
                    for req in wave:
                        if req.resume is not None:
                            # recompute-resume of a preempted lane:
                            # prefill over prompt+generated, continue the
                            # exact sampling stream from the checkpoint
                            slot = next(free_iter)
                            try:
                                self._admit_resume(slot, req)
                            except Exception as e:  # noqa: BLE001 - bad state
                                logger.exception("preemption resume failed")
                                self._release_tier_ckpt(req)
                                if not req.future.done():
                                    req.future.set_exception(e)
                            continue
                        if req.remote is not None:
                            # disaggregated handoff: the prompt K/V came
                            # over the wire — splice it, no local prefill
                            slot = next(free_iter)
                            try:
                                self._admit_remote_lane(slot, req)
                            except Exception as e:  # noqa: BLE001 - typed refusal
                                from .disagg import (
                                    PrefixGone,
                                    WeightVersionMismatch,
                                )

                                if isinstance(
                                    e, (PrefixGone, WeightVersionMismatch)
                                ):
                                    # expected, self-healing refusals (the
                                    # caller retries full-slab / re-prefills
                                    # under the new version): one info line,
                                    # no traceback — ERROR stays reserved
                                    # for corrupt slabs and real faults
                                    logger.info("remote admit refused: %s", e)
                                else:
                                    logger.exception("remote admit failed")
                                if not req.future.done():
                                    req.future.set_exception(e)
                            continue
                        hit = (
                            self._prefix_match(req)
                            if self._prefix_index is not None
                            else None
                        )
                        n = len(req.tokens)
                        if chunk_size and (
                            (hit is None and self._bucket(n) > chunk_size)
                            or (hit is not None and n - hit[0] > chunk_size)
                        ):
                            # long prefill: reserve the lane and trickle
                            # the prompt in between decode polls instead
                            # of stalling every lane for one forward
                            slot = next(free_iter)
                            try:
                                self._start_chunked(slot, req, hit=hit)
                            except Exception as e:  # noqa: BLE001 - bad request
                                logger.exception("chunked admit failed")
                                self._chunked.pop(slot, None)
                                if not req.future.done():
                                    req.future.set_exception(e)
                            continue
                        if hit is not None:
                            # prefix-cache hit: the suffix-only admit path
                            # (splice + short prefill) beats riding a
                            # batched FULL prefill with its bucket-mates
                            slot = next(free_iter)
                            try:
                                self._admit(slot, req, hit=hit)
                            except Exception as e:  # noqa: BLE001 - bad request
                                logger.exception("admit failed")
                                if not req.future.done():
                                    req.future.set_exception(e)
                            continue
                        by_bucket.setdefault(
                            self._bucket(len(req.tokens)), []
                        ).append(req)
                    for bucket, reqs in by_bucket.items():
                        while reqs:
                            # two batched variants exist per bucket (m=8
                            # where the slab fits, m=4) — remainders of
                            # 1-3 go through the single-admission path
                            # rather than compiling more executables.
                            # m=8 matters at LONG buckets: batched prefill
                            # roughly halves the per-request cost vs m=4
                            # (measured 39 -> 25.5 ms/req at 1792 on v5e),
                            # and prefill duty is the long tiers' largest
                            # non-decode cost
                            m = 1
                            if self.speculate_tokens == 0:
                                if len(reqs) >= 8 and self._chunk8_ok(bucket):
                                    m = 8
                                elif len(reqs) >= 4:
                                    m = 4
                            chunk, reqs = reqs[:m], reqs[m:]
                            slots_ = [next(free_iter) for _ in chunk]
                            try:
                                if m == 1:
                                    self._admit(slots_[0], chunk[0])
                                else:
                                    self._admit_many(slots_, chunk, bucket)
                            except Exception as e:  # noqa: BLE001 - bad request
                                logger.exception("admit failed")
                                for req in chunk:
                                    if not req.future.done():
                                        req.future.set_exception(e)
                if (
                    not self._active and not pending and not self._chunked
                    and not (self._resume_queue and not pressure_hold)
                ):
                    try:
                        req = self._queue.get(timeout=0.05)
                    except queue.Empty:
                        continue
                    self._queue.put(req)
                    continue
                if self._chunked:
                    # the interleave: one prefill chunk per pending long
                    # admission, then the decode burst below — decode
                    # lanes keep their cadence while long prompts land
                    self._advance_chunks()
                if self._active:
                    if self._masks_dirty:
                        for i in range(self.slots):
                            temps[i] = (
                                self._active[i].request.temperature
                                if i in self._active
                                else 0.0
                            )
                        active = np.zeros((self.slots,), bool)
                        for i in self._active:
                            active[i] = True
                        self._active_dev = jnp.asarray(active)
                        self._temps_dev = jnp.asarray(temps)
                        # static flag: a greedy-only burst compiles without
                        # the q/p softmax + sampling machinery
                        self._any_stoch = bool((temps > 0).any())
                        self._masks_dirty = False
                        self._fused_sync = False
                    active_dev = self._active_dev
                    temps_dev = self._temps_dev
                    # burst length. Step-at-a-time path: k is FIXED (one
                    # compiled variant) — lanes that hit max_new_tokens or
                    # eos mid-burst simply have their overshoot tokens
                    # dropped by _process_burst; clamping k to the tightest
                    # remaining budget (the pre-fused design) made staggered
                    # requests force tiny bursts on every lane, paying the
                    # sync RTT per token near each completion. Fused path:
                    # K is ADAPTIVE (never below self._k — see _fused_plan)
                    # and on-device done masks freeze lanes that stop
                    # mid-burst, so one dispatch safely covers many polls'
                    # worth of steps. Speculation keeps its own fused
                    # draft/verify rounds: the fused path degrades to it —
                    # and while the pressure ladder SUPPRESSES speculation,
                    # to the plain step-at-a-time burst (the path PR 9
                    # warmed and proved identical), never to cold fused
                    # executables.
                    fused_k = self._fused_k  # one snapshot per poll
                    use_fused = fused_k > 0 and self._spec_burst_fn is None
                    fused_reason = None
                    if use_fused:
                        k, fused_reason = self._fused_plan(fused_k)
                        if not self._fused_sync:
                            # per-lane stop tokens + remaining budgets:
                            # uploaded only when membership (or the
                            # dispatch mode) changed — the device
                            # decrements its own budget copy per step, so
                            # the steady-state fused loop uploads nothing
                            stops = np.full((self.slots,), -1, np.int32)
                            budget = np.zeros((self.slots,), np.int32)
                            for i, s in self._active.items():
                                if s.request.eos_id is not None:
                                    stops[i] = int(s.request.eos_id)
                                budget[i] = (
                                    s.request.max_new_tokens - s.dispatched
                                    - (1 if s.first_pending else 0)
                                )
                            self._stops_dev = jnp.asarray(stops)
                            self._budget_dev = jnp.asarray(budget)
                            self._fused_sync = True
                    else:
                        k = self._k
                        self._fused_sync = False
                    # per-burst worst-case position advance (spec rounds can
                    # emit up to gamma+1 tokens each)
                    adv = k * (
                        self.speculate_tokens + 1 if self._spec_active() else 1
                    )
                    # attention-read bucket: the smallest attn_bucket
                    # multiple covering every active lane's end-of-burst
                    # position (host-tracked, no sync). One executable per
                    # bucket. With depth grouping, each sub-burst narrows
                    # to ITS lanes' bucket instead (plan below).
                    attn_len = self._attn_need(
                        max(self._pos_host[i] for i in self._active) + adv
                    )
                    if self._spec_active():
                        # snapshot BEFORE dispatch: tokens of this burst
                        # belong to these occupants, whatever the host
                        # learns later. (Spec bursts stay whole-batch:
                        # their per-round advance is data-dependent and
                        # the verify pass already amortises param reads.)
                        snapshot = {}
                        for slot, s in self._active.items():
                            first = s.first_pending
                            snapshot[slot] = (s, 0 if first else 1)
                            s.first_pending = False
                            s.dispatched += k + (1 if first else 0)
                            self._pos_host[slot] += adv
                        caches = {
                            "k": self._cache["k"], "v": self._cache["v"],
                            "dk": self._draft_cache["k"],
                            "dv": self._draft_cache["v"],
                        }
                        with self._prof.measure(
                            "spec_burst",
                            variant=f"g{self.speculate_tokens}b{attn_len}",
                            tenant=self._burst_tenant()
                            if self._prof.enabled else "",
                            bytes_read=k * (
                                self._param_bytes
                                + self.slots * attn_len * self._kv_key_bytes
                            ),
                            tokens=k * self.slots,
                        ) as _m, device_trace("gen.decode_burst"):
                            (
                                start_tok, toks, counts, self._cur_tok,
                                self._pos, self._keys, nc,
                            ) = self._spec_burst_fn(
                                self.params, self._draft_params, caches,
                                self._cur_tok, self._pos, active_dev, temps_dev,
                                self._keys, k, attn_len, self._any_stoch,
                            )
                            _m.sync(toks)
                        if flight is not None:
                            poll_plan = {
                                "mode": "spec", "k": k, "attn_len": attn_len,
                                "lanes": len(self._active),
                            }
                        self._cache = {"k": nc["k"], "v": nc["v"]}
                        self._draft_cache = {"k": nc["dk"], "v": nc["dv"]}
                        self.stats["steps"] += k
                        self.stats["lane_steps"] += k * self.slots
                        for t in (start_tok, toks, counts):
                            try:
                                t.copy_to_host_async()
                            except AttributeError:
                                pass
                        pending.append(("spec", (start_tok, toks, counts, snapshot, k)))
                    else:
                        groups, need = self._plan_groups(adv)
                        if flight is not None:
                            # depth-group plan + cost-model verdict: the
                            # gap between distinct need-buckets and the
                            # dispatched group count IS how many splits
                            # the cost model merged away this poll. ONE
                            # composition record per fused poll (mode
                            # "fused", the adaptive K and why it shrank)
                            # — never per fused step, so the recorder's
                            # host cost stays per-poll as shipped.
                            poll_plan = {
                                "mode": "fused" if use_fused else "decode",
                                "k": k,
                                "groups": [
                                    {"lanes": len(lanes), "bucket": b}
                                    for lanes, b in groups
                                ],
                                "distinct_buckets": len(set(need.values())),
                                "merged": len(set(need.values())) - len(groups),
                            }
                            if use_fused:
                                poll_plan["k_max"] = fused_k
                                if fused_reason is not None:
                                    poll_plan["shrunk_by"] = fused_reason
                        # per-lane bookkeeping happens per SUB-burst: a
                        # lane's tokens are credited against the column it
                        # occupied in the burst that decoded it
                        burst_tenant = (
                            self._burst_tenant() if self._prof.enabled
                            else ""
                        )
                        for lanes, g_bucket in groups:
                            snapshot = {}
                            for col, slot in enumerate(lanes):
                                s = self._active[slot]
                                first = s.first_pending
                                snapshot[slot] = (s, 0 if first else 1, col)
                                s.first_pending = False
                                s.dispatched += k + (1 if first else 0)
                                self._pos_host[slot] += adv
                            counts = done_bits = None
                            if len(groups) == 1:
                                # single depth group: the exact pre-grouping
                                # whole-batch path — no gather, columns are
                                # lane ids
                                for slot in lanes:
                                    snapshot[slot] = (
                                        snapshot[slot][0], snapshot[slot][1],
                                        slot,
                                    )
                                rows = self.slots
                                if use_fused:
                                    with self._prof.measure(
                                        "fused_burst",
                                        variant=f"k{k}b{g_bucket}",
                                        tenant=burst_tenant,
                                        bytes_read=k * (
                                            self._param_bytes
                                            + rows * g_bucket
                                            * self._kv_key_bytes
                                        ),
                                        tokens=k * rows,
                                    ) as _m, device_trace(
                                        "gen.decode_burst"
                                    ):
                                        (
                                            toks, counts, done_bits,
                                            self._cur_tok, self._pos,
                                            self._cache, self._keys,
                                            self._budget_dev,
                                        ) = self._fused_burst_fn(
                                            self.params, self._cache,
                                            self._cur_tok, self._pos,
                                            active_dev, temps_dev,
                                            self._keys, self._stops_dev,
                                            self._budget_dev, k, g_bucket,
                                        )
                                        _m.sync(toks)
                                else:
                                    with self._prof.measure(
                                        "decode_burst",
                                        variant=f"b{g_bucket}",
                                        tenant=burst_tenant,
                                        bytes_read=k * (
                                            self._param_bytes
                                            + rows * g_bucket
                                            * self._kv_key_bytes
                                        ),
                                        tokens=k * rows,
                                    ) as _m, device_trace(
                                        "gen.decode_burst"
                                    ):
                                        toks, self._cur_tok, self._pos, self._cache, self._keys = (
                                            self._burst_fn(
                                                self.params, self._cache,
                                                self._cur_tok, self._pos,
                                                active_dev, temps_dev, self._keys,
                                                k, g_bucket,
                                            )
                                        )
                                        _m.sync(toks)
                            else:
                                gb = self._group_size_bucket(len(lanes))
                                pads = [
                                    i for i in range(self.slots)
                                    if i not in snapshot
                                ][: gb - len(lanes)]
                                lane_ix = jnp.asarray(
                                    lanes + pads, jnp.int32
                                )
                                rows = gb
                                if use_fused:
                                    with self._prof.measure(
                                        "group_burst",
                                        variant=f"k{k}r{gb}b{g_bucket}",
                                        tenant=burst_tenant,
                                        bytes_read=k * (
                                            self._param_bytes
                                            + rows * g_bucket
                                            * self._kv_key_bytes
                                        ),
                                        tokens=k * len(lanes),
                                    ) as _m, device_trace(
                                        "gen.decode_burst"
                                    ):
                                        (
                                            toks, counts, done_bits,
                                            self._cur_tok, self._pos,
                                            self._cache, self._keys,
                                            self._budget_dev,
                                        ) = self._fused_group_fn(
                                            self.params, self._cache,
                                            self._cur_tok, self._pos,
                                            temps_dev, self._keys,
                                            self._stops_dev,
                                            self._budget_dev, lane_ix,
                                            len(lanes), k, g_bucket,
                                        )
                                        _m.sync(toks)
                                else:
                                    with self._prof.measure(
                                        "group_burst",
                                        variant=f"r{gb}b{g_bucket}",
                                        tenant=burst_tenant,
                                        bytes_read=k * (
                                            self._param_bytes
                                            + rows * g_bucket
                                            * self._kv_key_bytes
                                        ),
                                        tokens=k * len(lanes),
                                    ) as _m, device_trace(
                                        "gen.decode_burst"
                                    ):
                                        toks, self._cur_tok, self._pos, self._cache, self._keys = (
                                            self._group_burst_fn(
                                                self.params, self._cache,
                                                self._cur_tok, self._pos,
                                                temps_dev, self._keys, lane_ix,
                                                len(lanes), k, g_bucket,
                                            )
                                        )
                                        _m.sync(toks)
                                self.stats["group_bursts"] += 1
                                self.stats["group_lanes"] += len(lanes)
                                self.stats["group_pad_lanes"] += gb - len(lanes)
                            self.stats["steps"] += k
                            self.stats["lane_steps"] += k * rows
                            self.stats["burst_reads"] += 1
                            self.stats["burst_read_bytes"] += k * (
                                self._param_bytes
                                + rows * g_bucket * self._kv_key_bytes
                            )
                            if use_fused:
                                self.stats["fused_dispatches"] += 1
                                self.stats["fused_steps"] += k
                            if self.trace_groups is not None:
                                self.trace_groups.append({
                                    "lanes": tuple(lanes),
                                    "attn_len": g_bucket,
                                    "need": {i: need[i] for i in lanes},
                                    "grouped": len(groups) > 1,
                                })
                            # start the device->host token copy NOW; by the
                            # time the host reads this burst (pipeline_depth
                            # dispatches later) the transfer has landed
                            if use_fused:
                                for t in (toks, counts, done_bits):
                                    try:
                                        t.copy_to_host_async()
                                    except AttributeError:
                                        pass
                                pending.append((
                                    "fused",
                                    (toks, counts, done_bits, snapshot, k),
                                ))
                            else:
                                try:
                                    toks.copy_to_host_async()
                                except AttributeError:  # non-jax (test doubles)
                                    pass
                                pending.append(("plain", (toks, snapshot)))
                        # PREDICTIVE FREE: a lane whose eos-less budget is
                        # now fully covered by dispatched bursts is done —
                        # the host needn't observe the tokens to know it.
                        # Freeing it here (instead of pipeline_depth bursts
                        # later) lets the next admission's prefill+insert
                        # queue behind the in-flight bursts, so the lane
                        # decodes a NEW request the very next burst rather
                        # than burning steps on overshoot. (Spec mode keeps
                        # the observed path: its per-round advance is
                        # data-dependent, so completion isn't predictable.)
                        freed = [
                            slot
                            for slot, s in self._active.items()
                            if s.request.eos_id is None
                            and s.dispatched >= s.request.max_new_tokens
                        ]
                        for slot in freed:
                            s = self._active.pop(slot)
                            # pre-freed lanes never reach _finish; this is
                            # the only point their prompt K/V can publish
                            # before the lane's next occupant splices over
                            self._maybe_publish(slot, s)
                            self._pos_host.pop(slot, None)
                        if freed:
                            self._masks_dirty = True
                if flight is not None:
                    admitted = self.stats["admitted"] - f0[0]
                    chunks = self.stats["prefill_chunks"] - f0[1]
                    hits = self.stats["prefix_hits"] - f0[2]
                    evicted = self.stats["prefix_evicted"] - f0[3]
                    if poll_plan is not None or admitted or chunks:
                        entry: Dict[str, Any] = {
                            "type": "poll",
                            "queue": self._queue.qsize(),
                            "active": len(self._active),
                            "chunked": len(self._chunked),
                            "pending_bursts": len(pending),
                        }
                        if admitted:
                            entry["admitted"] = admitted
                        if chunks:
                            entry["prefill_chunks"] = chunks
                        if hits:
                            entry["prefix_hits"] = hits
                        if evicted:
                            entry["prefix_evicted"] = evicted
                        if poll_plan is not None:
                            entry["plan"] = poll_plan
                        if self._prof.enabled:
                            # per-poll device-time ledger deltas ride the
                            # poll record; quiet-poll leftovers roll into
                            # the next recorded poll (flush clears)
                            dt_rows = self._prof.poll_flush()
                            if dt_rows:
                                entry["device_time"] = dt_rows
                        flight.record(entry)
                # read bursts oldest-first: always when the pipeline is full
                # (or nothing is left to dispatch) — and OPPORTUNISTICALLY
                # when a burst's token copy has already landed on the host
                # (is_ready -> np.asarray won't block). Eager reads shrink
                # the completion-observation lag for eos/temperature lanes
                # without ever stalling dispatch.
                while pending:
                    if not (len(pending) >= self.pipeline_depth or not self._active):
                        # last-initiated transfer of the oldest burst: counts
                        # for spec (start_tok/toks/counts copy in order),
                        # the done bitmap for fused (toks/counts/done), toks
                        # for plain — if IT landed, np.asarray of the
                        # earlier arrays won't block either
                        head_mode, head_payload = pending[0]
                        head = head_payload[
                            2 if head_mode in ("spec", "fused") else 0
                        ]
                        try:
                            if not head.is_ready():
                                break
                        except AttributeError:
                            pass  # non-jax array (test doubles): treat as ready
                    mode, payload = pending.popleft()
                    if mode == "spec":
                        self._process_spec_burst(*payload)
                    elif mode == "fused":
                        self._process_fused_burst(*payload)
                    else:
                        self._process_burst(*payload)
        except Exception:  # noqa: BLE001 - every loop death is supervised
            logger.exception("continuous batcher loop died")
            return self._crash_recover(pending)
        return False  # clean stop via close()
