"""Live-lane migration: the SGC1 generate-checkpoint codec + transports.

PR 9 proved a decode lane's whole resumable state is a few hundred
host-side bytes — the emitted tokens, the post-split RNG lane key, and
the sampling params; the K/V rebuilds byte-identically via recompute
plus teacher-forced replay (``ContinuousBatcher._admit_resume``). This
module makes that checkpoint a first-class, **wire-portable** object so
no generation ever restarts from token zero:

* **graceful drain** — ``ContinuousBatcher.drain()`` checkpoints every
  live lane at a poll boundary and ``GenerateServer.drain_to`` hands
  the checkpoints (plus queued requests) to a peer, which resumes them
  via the PR 9 recompute-resume path — rolling maintenance drops zero
  requests;
* **crash survival** — streams (and unary responses) optionally carry
  an opaque **resume token** (the SGC1 payload, base64) refreshed per
  emitted span; after a member death the token resumes the generation
  on any peer serving the same ``weight_version``, byte-identical, with
  already-delivered spans never re-sent.

Wire format (version ``SGC1``, sibling of PR 6's SKV1 — same CRC-framed
refusal discipline, same typed error classes)::

    b"SGC1" | u32 payload_len | u32 crc32(payload) | payload JSON

One frame: the checkpoint is a few hundred bytes, so the layer-major
streaming SKV1 needs for multi-MB slabs would be pure overhead here.
The CRC matters for the same reason SKV1's header CRC does: a flipped
bit in a still-valid-JSON checkpoint would seed a lane with silently
wrong output, not a crash. Corruption raises
:class:`~.disagg.ChecksumError`; a short buffer raises
:class:`~.disagg.TruncatedStream`; a checkpoint prefilled under another
weight version refuses with :class:`~.disagg.WeightVersionMismatch` —
all typed, all BEFORE any lane state exists (the SKV1 contract).

Checkpoint fields: prompt tokens, emitted tokens, RNG lane key (exact
when exported by a drain — ``None`` in crash tokens, where the resume
side re-derives it from ``seed`` + emitted count, see
:func:`derive_lane_key`), sampling params, ``weight_version``, the
remaining deadline budget, the cumulative queue-wait anchor
(``wait_s``/``submit_wall_us`` — so a migrated lane's
``seldon_engine_generate_queue_wait_seconds`` sample stays cumulative),
and the stream credit position (spans at or before it are never
re-sent).

Transports reuse the PR 6/7 conventions: loopback hands the checkpoint
dict to a live peer object (still round-tripping the full codec through
memory, so framing bugs can't hide), TCP ships base64 SGC1 frames to a
peer ENGINE's ``POST /drain`` route (``graph/service.py``), with the
peer's typed refusals surviving the wire as their HTTP statuses.
"""

from __future__ import annotations

import base64
import json
import logging
import struct
import time
import zlib
from typing import Any, Dict, List, Optional

from .disagg import (
    ChecksumError,
    DisaggError,
    TruncatedStream,
    WeightVersionMismatch,
)

logger = logging.getLogger(__name__)

MAGIC = b"SGC1"
CHECKPOINT_VERSION = 1


class MigrationError(DisaggError):
    """Base for migration failures; carries the 502 wire status through
    the same typed-refusal path the KV-slab codec uses."""


class ResumeTokenError(MigrationError):
    """A client-supplied resume token failed to parse (bad base64,
    corrupt frame, wrong magic/version). Client input, not a peer or
    wire fault — carries a **400** so the engine answers a client
    error instead of a retryable 502 (resubmitting the same broken
    token can never succeed)."""

    status = 400


def checkpoint_of(req, weight_version) -> Dict[str, Any]:
    """Build the wire checkpoint of one drained/checkpointed
    :class:`~.continuous.GenRequest`. The request's ``resume`` dict (set
    by the scheduler's checkpoint at a poll boundary) carries the exact
    emitted tokens + post-split RNG lane key; a request drained while
    still queued checkpoints with no emitted tokens — a plain re-admit
    on the peer reproduces the identical stream from the seed alone."""
    now = time.monotonic()
    resume = req.resume or {}
    emitted = [int(t) for t in resume.get("emitted") or []]
    key = resume.get("key")
    return {
        "v": CHECKPOINT_VERSION,
        "prompt": [int(t) for t in req.tokens],
        "emitted": emitted,
        "rng_key": [int(k) for k in key] if key is not None else None,
        "max_new_tokens": int(req.max_new_tokens),
        "temperature": float(req.temperature),
        "eos_id": req.eos_id,
        "seed": int(req.seed),
        "weight_version": weight_version,
        # cumulative queue-wait anchor: the peer re-bases submit_t so
        # the request's queue-wait histogram sample covers BOTH members
        "wait_s": round(max(0.0, now - req.submit_t), 6)
        if req.submit_t else 0.0,
        "submit_wall_us": int(req.submit_wall_us or 0),
        "deadline_s": (
            max(0.0, req.deadline_t - now)
            if req.deadline_t is not None else None
        ),
        # stream credit position: spans up to here were already
        # delivered to the client and must never be re-sent
        "stream_pos": len(emitted),
    }


def encode_checkpoint(ck: Dict[str, Any]) -> bytes:
    """One SGC1 frame: magic | length | CRC | JSON payload."""
    payload = json.dumps(ck, separators=(",", ":")).encode()
    return MAGIC + struct.pack(
        "<II", len(payload), zlib.crc32(payload)
    ) + payload


def decode_checkpoint(data: bytes) -> Dict[str, Any]:
    """Decode + validate one SGC1 frame. Typed refusals BEFORE any lane
    state can exist: bad magic / version → :class:`MigrationError`,
    short buffer → :class:`~.disagg.TruncatedStream`, CRC mismatch →
    :class:`~.disagg.ChecksumError`."""
    if len(data) < 12:
        raise TruncatedStream(
            f"checkpoint frame is {len(data)} bytes, need >= 12"
        )
    if data[:4] != MAGIC:
        raise MigrationError(
            f"bad checkpoint magic {data[:4]!r} (want {MAGIC!r})"
        )
    n, crc = struct.unpack("<II", data[4:12])
    payload = data[12:12 + n]
    if len(payload) < n:
        raise TruncatedStream(
            f"checkpoint payload is {len(payload)} of {n} bytes"
        )
    if zlib.crc32(payload) != crc:
        raise ChecksumError("checkpoint frame failed its checksum")
    ck = json.loads(payload)
    if ck.get("v") != CHECKPOINT_VERSION:
        raise MigrationError(
            f"unsupported checkpoint version {ck.get('v')!r}"
        )
    if not ck.get("prompt"):
        raise MigrationError("checkpoint carries no prompt tokens")
    return ck


def checkpoint_token(ck: Dict[str, Any]) -> str:
    """Opaque resume token: the SGC1 frame, base64url. CRC-protected —
    a client-side bit flip refuses typed instead of resuming wrong."""
    return base64.urlsafe_b64encode(encode_checkpoint(ck)).decode()


def parse_token(token: str) -> Dict[str, Any]:
    """Parse a client resume token. ANY parse failure — bad base64, a
    flipped bit (CRC), truncation, wrong magic/version — re-raises as
    :class:`ResumeTokenError` (400-class): the token is client input,
    and the 502-class wire errors would read as a retryable server
    fault for a request that can never succeed unchanged."""
    try:
        raw = base64.urlsafe_b64decode(token.encode())
        return decode_checkpoint(raw)
    except ResumeTokenError:
        raise
    except DisaggError as e:
        raise ResumeTokenError(f"bad resume token: {e}") from e
    except Exception as e:  # noqa: BLE001 - malformed client input
        raise ResumeTokenError(f"resume token is not base64: {e}") from e


def derive_lane_key(seed: int, emitted: int) -> List[int]:
    """Re-derive the post-split RNG lane key for a lane that has emitted
    ``emitted`` tokens, from the request seed alone.

    The scheduler's RNG chain is deterministic: every admission path
    (whole-prompt, batched, prefix-splice, chunked) derives
    ``key0 = split(PRNGKey(seed))[0]`` when it samples the first token,
    and each fused decode step advances ``key_{i+1} = split(key_i)[0]``
    — so after N emitted tokens the lane key has been split N-1 times
    past the prefill. Crash tokens ship without a key (reading it per
    span would cost a host sync per span on the hot path) and the
    resume side rebuilds it here — a handful of tiny host jax calls at
    a rare resume point. NOT valid under speculative decoding (spec
    rounds consume extra per-lane splits); the server refuses the
    ``resume_tokens`` knob with a draft configured."""
    import jax

    key = jax.random.PRNGKey(int(seed))
    for _ in range(max(1, int(emitted))):
        key, _sub = jax.random.split(key)
    import numpy as np

    return np.asarray(key).astype(np.uint32).tolist()


def post_drain(
    addr: str,
    checkpoints: List[Dict[str, Any]],
    timeout_s: float = 60.0,
) -> List[Any]:
    """TCP half of the drain handoff: POST the SGC1 frames (base64) to
    a peer ENGINE's ``/drain`` route and return the final token lists,
    positionally. The peer's typed refusals come back as HTTP statuses
    and are re-raised typed here (409 → WeightVersionMismatch, 503 →
    peer unready) so the caller's failure handling matches loopback."""
    import http.client

    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"drain peer must be host:port, got {addr!r}")
    body = json.dumps({
        "checkpoints": [checkpoint_token(ck) for ck in checkpoints],
    }).encode()
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout_s)
    try:
        conn.request("POST", "/drain", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        payload = resp.read()
        if resp.status == 409:
            raise WeightVersionMismatch(
                f"drain peer {addr} refused the checkpoints: "
                f"{payload[:200]!r}"
            )
        if resp.status != 200:
            raise MigrationError(
                f"drain peer {addr} answered {resp.status}: "
                f"{payload[:200]!r}"
            )
        out = json.loads(payload)
        results = out.get("results")
        if not isinstance(results, list) or len(results) != len(checkpoints):
            raise MigrationError(
                f"drain peer {addr} returned {len(results or [])} results "
                f"for {len(checkpoints)} checkpoints"
            )
        return results
    except OSError as e:
        raise MigrationError(
            f"drain handoff to {addr} failed: {e}"
        ) from e
    finally:
        conn.close()
