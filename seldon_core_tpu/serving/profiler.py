"""Device-time ledger: per-executable attribution for the serving loop.

Every warmed-executable dispatch the continuous batcher makes — prefill,
chunked prefill slice, decode burst, fused burst, spec burst,
depth-group variant, prefix splice/insert/extract, swap cast — is timed
and attributed per ``(kind, variant, tenant)``. The ledger turns the
offline modelbench numbers into live gauges: bytes-read per variant are
known statically (the same cost model ``modelbench.bench_generate``
prices MBU with — see ``DecoderLM.dispatch_read_bytes``), so live MBU
is a divide over a sliding window, not a profile run, and the
dispatch-floor percentage is the observed dispatch rate priced at the
measured per-dispatch floor.

What a "measurement" means under JAX async dispatch, honestly:

* **shallow (default)** times the host-side dispatch call with
  ``time.perf_counter``. A dispatch returns as soon as XLA enqueues the
  work, so an unloaded pipeline under-reports device time — but the
  batcher bounds in-flight bursts at ``pipeline_depth``, and once the
  pipeline is full every dispatch blocks until a device slot frees, so
  under load (the regime the numbers matter in) the per-kind shares
  converge to device-time shares. Zero extra synchronization, which is
  what keeps the on-vs-off overhead probe inside its 2% gate.
* **deep (sampled, every ``deep_every``-th measured dispatch)** blocks
  until the dispatched arrays are ready inside a
  ``jax.profiler.TraceAnnotation`` stamped with the attribution tags
  (``ledger.<kind>[<variant>]``), so an XLA device profile taken during
  a deep window carries the same vocabulary as the ledger. Deep samples
  drain the dispatch pipeline — a deliberate, bounded perturbation.

The ledger NEVER touches the dispatched computation: hooks wrap the
call, never its arguments or results, so profiler on vs off is
byte-identical (greedy and seeded) and compiles nothing new — the gate
``tests/test_profiler.py`` pins with jit-cache sizes.

Thread model: ``record`` runs on the scheduler thread (and, for
``export_prefill``, transport handler threads); ``poll_flush`` on the
scheduler thread; ``summary``/``gauges`` on serving/metrics threads.
One lock covers the accumulation maps — held for dict arithmetic only,
never across a dispatch.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["DeviceTimeLedger", "KINDS"]

# the executable-kind vocabulary (flight_report renders these; keep in
# sync with docs/operate.md "Observability")
KINDS = (
    "prefill",        # prefill_one/prefill_many + lane insert
    "chunk_prefill",  # one chunked-prefill slice
    "decode_burst",   # step-at-a-time whole-batch burst
    "fused_burst",    # stop-aware fused multi-step burst (per K)
    "group_burst",    # depth-group sub-burst variant (plain or fused)
    "spec_burst",     # speculative draft+verify round burst
    "splice",         # prefix/checkpoint donor slab splice into a slab
    "insert",         # prefilled slab insert into a lane of the cache
    "extract",        # prefix/checkpoint slab extract from the cache
    "replay",         # teacher-forced replay (preempt recompute-resume)
    "swap_cast",      # hot-swap weight cast/device_put
)
_KINDS_SET = frozenset(KINDS)


class _Measurement:
    """One in-flight measured dispatch; ``sync(arrays)`` is the deep-mode
    hook call sites feed the dispatched outputs to (no-op unless this
    dispatch was deep-sampled)."""

    __slots__ = ("_ledger", "kind", "variant", "tenant", "bytes_read",
                 "tokens", "_t0", "_deep", "_annot")

    def __init__(self, ledger, kind, variant, tenant, bytes_read, tokens,
                 deep):
        self._ledger = ledger
        self.kind = kind
        self.variant = variant
        self.tenant = tenant
        self.bytes_read = bytes_read
        self.tokens = tokens
        self._deep = deep
        self._annot = None
        self._t0 = 0.0

    def __enter__(self):
        if self._deep:
            try:
                import jax.profiler

                self._annot = jax.profiler.TraceAnnotation(
                    f"ledger.{self.kind}[{self.variant}]"
                )
                self._annot.__enter__()
            except ImportError:  # pragma: no cover - jax is baked in
                self._annot = None
        self._t0 = time.perf_counter()
        return self

    def sync(self, arrays: Any) -> None:
        """Deep mode only: block until the dispatched arrays are ready so
        the recorded duration covers the device work, not just the
        enqueue. Values are untouched — identity is preserved."""
        if self._deep:
            try:
                import jax

                jax.block_until_ready(arrays)
            except (ImportError, TypeError):  # non-jax test doubles
                pass

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        if self._annot is not None:
            self._annot.__exit__(exc_type, exc, tb)
        if exc_type is None:
            self._ledger._record(
                self.kind, self.variant, self.tenant, dt,
                self.bytes_read, self.tokens, self._deep,
            )
        return False


class _NoopMeasurement:
    __slots__ = ()

    def __enter__(self):
        return self

    def sync(self, arrays: Any) -> None:
        pass

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopMeasurement()


class DeviceTimeLedger:
    """Accumulates measured dispatch time per (kind, variant, tenant).

    Two accumulation levels: a cumulative map (``summary``/metrics
    deltas read it) and a since-last-flush map the scheduler attaches to
    each flight-recorder poll record (``poll_flush``). A bounded window
    of recent records backs the live MBU / dispatch-floor gauges.
    """

    WINDOW_S = 10.0

    def __init__(
        self,
        enabled: bool = False,
        deep_every: int = 0,
        hbm_gb_s: float = 0.0,
        dispatch_floor_us: float = 0.0,
    ):
        self.enabled = bool(enabled)
        self.deep_every = max(0, int(deep_every))
        # MBU / dispatch-floor denominators (0 = unknown: the gauges are
        # omitted rather than published as lies). Benches pass measured
        # values; servers take them as knobs.
        self.hbm_gb_s = float(hbm_gb_s)
        self.dispatch_floor_us = float(dispatch_floor_us)
        self._lock = threading.Lock()
        # (kind, variant, tenant) -> [seconds, dispatches, bytes, tokens]
        self._cum: Dict[Tuple[str, str, str], List[float]] = {}
        self._poll: Dict[Tuple[str, str, str], List[float]] = {}
        self._seq = 0          # measured dispatches (deep-mode sampler)
        self._deep_count = 0
        import collections

        # (mono_t, seconds, bytes, dispatches, tokens) per record
        self._window = collections.deque(maxlen=8192)

    # -- hot path -----------------------------------------------------------

    def measure(
        self,
        kind: str,
        variant: str = "",
        tenant: str = "",
        bytes_read: int = 0,
        tokens: int = 0,
    ):
        """Context manager timing one dispatch. Disabled ledgers return a
        shared no-op — one attribute check and one call, nothing else on
        the hot path."""
        if not self.enabled:
            return _NOOP
        if kind not in _KINDS_SET:
            # the kind vocabulary is a rendering contract (flight_report,
            # docs); a typo'd hook must fail loudly, not mint a series
            raise ValueError(f"unknown ledger kind {kind!r}")
        deep = False
        if self.deep_every > 0:
            self._seq += 1
            deep = (self._seq % self.deep_every) == 0
        return _Measurement(self, kind, variant, tenant, bytes_read,
                            tokens, deep)

    def _record(self, kind, variant, tenant, seconds, bytes_read, tokens,
                deep) -> None:
        key = (kind, variant, tenant)
        with self._lock:
            for m in (self._cum, self._poll):
                row = m.get(key)
                if row is None:
                    row = [0.0, 0.0, 0.0, 0.0]
                    m[key] = row
                row[0] += seconds
                row[1] += 1.0
                row[2] += bytes_read
                row[3] += tokens
            if deep:
                self._deep_count += 1
            self._window.append(
                (time.monotonic(), seconds, bytes_read, 1.0, tokens)
            )

    # -- flush / export -----------------------------------------------------

    @staticmethod
    def _rows(m: Dict[Tuple[str, str, str], List[float]]) -> List[Dict[str, Any]]:
        out = []
        for (kind, variant, tenant), (s, n, b, t) in sorted(m.items()):
            row = {
                "kind": kind, "variant": variant,
                "s": round(s, 6), "n": int(n),
                "bytes": int(b), "tokens": int(t),
            }
            if tenant:
                row["tenant"] = tenant
            out.append(row)
        return out

    def poll_flush(self) -> Optional[List[Dict[str, Any]]]:
        """Per-(kind,variant,tenant) deltas since the last flush, cleared
        on read — the scheduler attaches the result to its per-poll
        flight-recorder record. None when nothing was measured."""
        if not self.enabled:
            return None
        with self._lock:
            if not self._poll:
                return None
            rows = self._rows(self._poll)
            self._poll.clear()
        return rows

    def buckets(self) -> Dict[Tuple[str, str, str], Tuple[float, float, float, float]]:
        """Cumulative (seconds, dispatches, bytes, tokens) per
        (kind, variant, tenant) — the metrics() exporter window-diffs
        these through CounterDeltas."""
        with self._lock:
            return {k: tuple(v) for k, v in self._cum.items()}

    def _window_rates(self) -> Tuple[float, float, float, float]:
        """(span_s, bytes/s, dispatches/s, device_s/s) over the sliding
        window; zeros when the window is empty or degenerate."""
        now = time.monotonic()
        horizon = now - self.WINDOW_S
        with self._lock:
            live = [r for r in self._window if r[0] >= horizon]
        if len(live) < 2:
            return 0.0, 0.0, 0.0, 0.0
        span = max(1e-6, now - live[0][0])
        b = sum(r[2] for r in live)
        n = sum(r[3] for r in live)
        s = sum(r[1] for r in live)
        return span, b / span, n / span, s / span

    def gauges(self) -> Dict[str, float]:
        """Live derived gauges over the sliding window. ``mbu_pct`` needs
        ``hbm_gb_s``; ``dispatch_floor_pct`` needs ``dispatch_floor_us``
        — each is omitted when its denominator is unknown."""
        span, bytes_s, disp_s, busy = self._window_rates()
        out: Dict[str, float] = {}
        if span <= 0.0:
            return out
        out["device_busy_frac"] = round(min(1.0, busy), 4)
        if self.hbm_gb_s > 0:
            out["mbu_pct"] = round(
                100.0 * bytes_s / (self.hbm_gb_s * 1e9), 2
            )
        if self.dispatch_floor_us > 0:
            # fraction of wall time the measured per-dispatch floor alone
            # would consume at the observed dispatch rate: near 100 means
            # the workload is dispatch-bound (the modelbench roofline,
            # live)
            out["dispatch_floor_pct"] = round(
                min(100.0, 100.0 * disp_s * self.dispatch_floor_us * 1e-6),
                2,
            )
        return out

    def summary(self) -> Dict[str, Any]:
        """Cumulative rollup for /fleet, flight_dump and bench entries."""
        with self._lock:
            rows = self._rows(self._cum)
            deep = self._deep_count
        total_s = sum(r["s"] for r in rows)
        by_kind: Dict[str, float] = {}
        for r in rows:
            by_kind[r["kind"]] = round(
                by_kind.get(r["kind"], 0.0) + r["s"], 6
            )
        out: Dict[str, Any] = {
            "enabled": self.enabled,
            "device_time_s": round(total_s, 6),
            "by_kind": by_kind,
            "buckets": rows,
            "deep_samples": deep,
        }
        out.update(self.gauges())
        return out
