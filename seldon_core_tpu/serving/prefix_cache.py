"""Radix index for the cross-request prefix KV cache.

Production generate() traffic is dominated by shared prefixes (system
prompts, few-shot templates, graph-injected preambles — DeepServe,
arxiv 2501.14417), and prefill is the continuous batcher's dominant
non-decode device cost. This module is the HOST side of the prefix
cache: a radix tree over prompt token IDs whose slab-bearing nodes
reference device-resident K/V blocks (stacked per-layer slabs, the
``cache_one`` layout ``[L, 1, KV, Tb, Dh]``) published by completed
requests.

The index is deliberately device-agnostic — a "slab" is any opaque
object plus a byte count — so insert/match/split/evict and the LRU byte
budget are unit-testable on CPU without JAX. The scheduler thread owns
all mutation; eviction simply drops the tree's reference and lets the
device buffer die with Python refcounting, so an admit that matched a
slab moments before an evict keeps it alive for exactly as long as the
splice needs it (eviction can never race an admit into a dangling
buffer).

Invariant the batcher relies on: a slab stored for prompt ``t[0:n]``
holds valid K/V for EVERY prefix of ``t`` — so any match depth
``m <= n`` can be served by splicing the whole slab and overwriting
positions ``>= m`` (the splice target's residue beyond ``m`` is never
readable before being rewritten, the same residue invariant that lets
decode lanes be reused without scrubbing).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Tuple


def version_namespace(version) -> Optional[str]:
    """Tenant namespace of a weight version, or None for the legacy
    single-lineage form. Multi-tenant serving (serving/weightpager.py)
    keys versions as the STRING ``"{tenant}@{seq}"`` — strings, not
    tuples, because versions ride JSON metas (SKV1/SGC1) where a tuple
    round-trips as a list and silently breaks every equality check.
    ``rsplit`` so a tenant name may not, but a future seq scheme may,
    contain ``@``."""
    if isinstance(version, str) and "@" in version:
        return version.rsplit("@", 1)[0]
    return None


def version_retains(entry_version, new_version) -> bool:
    """Whether an entry keyed ``entry_version`` SURVIVES a switch to
    ``new_version`` — the one purge rule the radix index, the host KV
    tier, and any future version-keyed store share:

    * same version — the weights did not change (a tenant paging back
      in): the entry is valid again, keep it;
    * both namespaced, different tenants — the OTHER tenant's weights
      did not change either; keep it (per-entry version checks make it
      unmatchable while its tenant is not resident), so a page-in of
      tenant B never invalidates tenant A's cache;
    * anything else — same tenant's new weights, or a legacy
      un-namespaced lineage on either side: purge (the pre-multi-tenant
      hot-swap contract, unchanged)."""
    if entry_version == new_version:
        return True
    ns_new = version_namespace(new_version)
    ns_old = version_namespace(entry_version)
    return ns_new is not None and ns_old is not None and ns_old != ns_new


@dataclasses.dataclass
class _Node:
    """One radix edge: ``edge`` tokens leading from the parent. A node
    with ``slab`` is an eviction unit: it owns a published K/V block and
    its byte bill; interior nodes created by edge splits carry none."""

    edge: Tuple[int, ...]
    parent: Optional["_Node"] = None
    children: Dict[int, "_Node"] = dataclasses.field(default_factory=dict)
    slab: Any = None
    slab_bytes: int = 0
    slab_tokens: int = 0  # real prompt length the slab covers
    last_used: int = 0
    version: Any = 0  # weight version the slab's K/V was computed under


class RadixPrefixIndex:
    """Longest-prefix match + LRU byte budget over published K/V slabs.

    All methods are plain Python over host token lists; slabs are opaque.
    Public methods take an internal lock: mutation is dominated by the
    scheduler thread, but disaggregated decode pools also consult the
    index from server worker threads (``remote_covered_len`` before a
    KV transfer), so walks must never see a half-split edge. The lock is
    uncontended in the unified single-writer case; readers of
    ``total_bytes`` / ``node_count`` from other threads still see
    torn-but-harmless ints.
    """

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self.root = _Node(edge=())
        self.total_bytes = 0
        self._clock = 0
        self._lock = threading.Lock()
        # weight-version key: slabs are K/V computed under ONE set of
        # model weights. A live hot-swap (continuous.request_weight_swap)
        # bumps this via set_version, purging every stored slab — stale
        # K/V from the old weights can then never splice into a
        # new-weights prefill. match() double-checks per node (belt and
        # braces against any future partial-purge path).
        self.version: Any = 0

    # -- internals ---------------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    @staticmethod
    def _common(a, b) -> int:
        n = min(len(a), len(b))
        i = 0
        while i < n and a[i] == b[i]:
            i += 1
        return i

    def _slab_node(self, node: _Node) -> Optional[_Node]:
        """Slab-bearing node in ``node``'s subtree with the SMALLEST
        covered prompt (every descendant's slab covers the prefix ending
        at ``node``, so any is correct — but the splice cost scales with
        the donor slab's bucket, so the shortest covering slab is the
        cheapest donor)."""
        best = None
        stack = [node]
        while stack:
            n = stack.pop()
            if (
                n.slab is not None
                and n.version == self.version
                and (best is None or n.slab_tokens < best.slab_tokens)
            ):
                best = n
            stack.extend(n.children.values())
        return best

    def _slab_nodes(self) -> List[_Node]:
        out = []
        stack = [self.root]
        while stack:
            n = stack.pop()
            if n.slab is not None:
                out.append(n)
            stack.extend(n.children.values())
        return out

    def _node_tokens(self, node: _Node) -> Tuple[int, ...]:
        """Full token path of ``node`` (root -> node edge concat): the
        identity an evicted slab carries into the host KV tier."""
        parts: List[Tuple[int, ...]] = []
        cur: Optional[_Node] = node
        while cur is not None and cur is not self.root:
            parts.append(cur.edge)
            cur = cur.parent
        return tuple(t for edge in reversed(parts) for t in edge)

    def _prune(self, node: _Node) -> None:
        """Remove slab-less leaves up the ancestry (never the root)."""
        while (
            node is not None
            and node is not self.root
            and node.slab is None
            and not node.children
        ):
            parent = node.parent
            if parent is not None:
                parent.children.pop(node.edge[0], None)
            node = parent

    # -- queries -----------------------------------------------------------

    def _walk(self, tokens) -> Tuple[int, Optional[_Node], List[_Node]]:
        """Shared radix descent: ``(depth, carrier, path)`` where
        ``carrier`` is the deepest node whose subtree covers ``depth``
        (possibly entered mid-edge) and ``path`` is every node traversed.
        ``match`` and ``covered_len`` differ only in what they do with
        this — one walker keeps the edge-split/mid-edge subtleties in one
        place."""
        node, depth = self.root, 0
        carrier = None
        path: List[_Node] = []
        while depth < len(tokens):
            child = node.children.get(tokens[depth])
            if child is None:
                break
            k = self._common(child.edge, tokens[depth:])
            if k == 0:
                break
            depth += k
            carrier = child
            path.append(child)
            if k < len(child.edge):
                break
            node = child
        return depth, carrier, path

    def match(self, tokens) -> Tuple[int, Any]:
        with self._lock:
            return self._match_locked(tokens)

    def _match_locked(self, tokens) -> Tuple[int, Any]:
        """Longest cached prefix of ``tokens``: returns ``(depth, slab)``
        where ``slab`` holds valid K/V for positions ``[0, depth)``, or
        ``(0, None)``. Touches the LRU clock on the serving slab's node
        and its slab-bearing ancestors (their content was used too)."""
        depth, carrier, path = self._walk(tokens)
        if depth == 0 or carrier is None:
            return 0, None
        slab_node = self._slab_node(carrier)
        if slab_node is None:
            return 0, None
        stamp = self._tick()
        slab_node.last_used = stamp
        for n in path:
            if n.slab is not None:
                n.last_used = stamp
        return depth, slab_node.slab

    def covered_len(self, tokens) -> int:
        with self._lock:
            return self._covered_len_locked(tokens)

    def _covered_len_locked(self, tokens) -> int:
        """Longest prefix of ``tokens`` some stored slab covers, WITHOUT
        touching the LRU clock (the publish-dedup probe)."""
        depth, carrier, _path = self._walk(tokens)
        if carrier is None or self._slab_node(carrier) is None:
            return 0
        return depth

    # -- mutation ----------------------------------------------------------

    def insert(self, tokens, slab, nbytes: int) -> int:
        with self._lock:
            return self._insert_locked(tokens, slab, nbytes)

    def _insert_locked(self, tokens, slab, nbytes: int) -> int:
        """Publish ``slab`` (K/V for the whole of ``tokens``) under the
        radix path, splitting edges as needed, then evict LRU slab nodes
        until the byte budget holds. Returns the number of slabs evicted.
        Re-publishing an exact existing path is a no-op (the stored slab
        already holds identical K/V)."""
        tokens = tuple(tokens)
        node, depth = self.root, 0
        while depth < len(tokens):
            child = node.children.get(tokens[depth])
            if child is None:
                new = _Node(edge=tokens[depth:], parent=node)
                node.children[tokens[depth]] = new
                node = new
                depth = len(tokens)
                break
            k = self._common(child.edge, tokens[depth:])
            if k < len(child.edge):
                # split child's edge at k; `mid` ends exactly at depth+k
                mid = _Node(edge=child.edge[:k], parent=node)
                node.children[tokens[depth]] = mid
                child.edge = child.edge[k:]
                child.parent = mid
                mid.children[child.edge[0]] = child
                node = mid
                depth += k
                continue
            node = child
            depth += k
        if node.slab is not None and node.version == self.version:
            node.last_used = self._tick()
            return 0
        if node.slab is not None:
            # stale-version slab at this exact path (defensive; set_version
            # purges these): replace rather than serve old-weights K/V
            self.total_bytes -= node.slab_bytes
        node.slab = slab
        node.slab_bytes = int(nbytes)
        node.slab_tokens = len(tokens)
        node.version = self.version
        node.last_used = self._tick()
        self.total_bytes += node.slab_bytes
        return self._evict_to_budget()

    def _evict_to_budget(self) -> int:
        return self._evict_down_locked(self.budget_bytes)

    def _evict_down_locked(self, target_bytes: int, collect=None) -> int:
        evicted = 0
        while self.total_bytes > target_bytes:
            nodes = self._slab_nodes()
            if not nodes:
                break
            victim = min(nodes, key=lambda n: n.last_used)
            if collect is not None:
                collect.append((
                    self._node_tokens(victim), victim.slab,
                    victim.slab_bytes,
                ))
            self.total_bytes -= victim.slab_bytes
            victim.slab = None
            victim.slab_bytes = 0
            victim.slab_tokens = 0
            evicted += 1
            self._prune(victim)
        return evicted

    def evict_to(self, target_bytes: int, collect=None) -> int:
        """LRU-evict slabs until ``total_bytes <= target_bytes`` (the
        pressure ladder's first rung: the batcher demotes the cache
        below its own budget to reclaim HBM for live lanes). Returns the
        number of slabs evicted. Eviction only drops the tree's
        reference — an admit that matched a slab moments earlier keeps
        it alive exactly as long as the splice needs it. ``collect``
        (a list) receives ``(tokens, slab, nbytes)`` per victim so the
        caller can DEMOTE the slabs to the host KV tier instead of
        losing them — the append happens under the lock; the (slow)
        device pull belongs on the caller's side of it."""
        with self._lock:
            return self._evict_down_locked(max(0, int(target_bytes)), collect)

    def remove(self, tokens) -> bool:
        """Drop the slab stored at EXACTLY ``tokens`` (no prefix
        semantics). Returns True when an entry was removed. The host KV
        tier uses this to drop a corrupt entry by its recorded path."""
        tokens = tuple(tokens)
        with self._lock:
            for node in self._slab_nodes():
                if (
                    node.slab_tokens == len(tokens)
                    and self._node_tokens(node) == tokens
                ):
                    self.total_bytes -= node.slab_bytes
                    node.slab = None
                    node.slab_bytes = 0
                    node.slab_tokens = 0
                    self._prune(node)
                    return True
        return False

    def set_version(self, version) -> int:
        with self._lock:
            return self._set_version_locked(version)

    def _set_version_locked(self, version) -> int:
        """Key the pool to a new weight version, purging every stored
        slab the switch invalidates (K/V computed under replaced weights
        — serving one into a new-weights prefill would splice
        numerically wrong cache). Namespace-aware per
        :func:`version_retains`: a tenant page-in purges only that
        tenant's stale slabs and legacy un-namespaced ones; other
        tenants' slabs survive, invisible (``_slab_node`` requires
        ``node.version == self.version``) until their tenant pages back.
        Returns the number of slabs purged. No-op when the version is
        unchanged."""
        if version == self.version:
            return 0
        self.version = version
        purged = 0
        for node in self._slab_nodes():
            if version_retains(node.version, version):
                continue
            self.total_bytes -= node.slab_bytes
            node.slab = None
            node.slab_bytes = 0
            node.slab_tokens = 0
            purged += 1
            self._prune(node)
        return purged

    # -- introspection -----------------------------------------------------

    @property
    def node_count(self) -> int:
        n, stack = 0, [self.root]
        while stack:
            node = stack.pop()
            n += 1
            stack.extend(node.children.values())
        return n - 1  # root is bookkeeping, not content

    @property
    def slab_count(self) -> int:
        return len(self._slab_nodes())
