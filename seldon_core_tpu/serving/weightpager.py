"""Multi-tenant multi-model serving: the HBM weight pager + SLO scheduler.

One batcher still serves ONE weight set at a time — that invariant is
what makes the decode loop simple and the byte-identity contract
checkable. But "millions of users" economics (ROADMAP item 3) cannot
afford a chip per cold long-tail tenant. This module multiplexes N
tenants' checkpoints over that single-resident batcher:

* :class:`WeightPager` — a two-tier checkpoint store. Every tenant's
  params live in **host RAM** as an SWP1-framed, CRC-checked byte blob
  (the SKV1 framing idiom of :mod:`.disagg`, one frame per param leaf)
  under a byte budget with LRU eviction and a half-budget refusal,
  exactly the :class:`~.kvtier.HostKVTier` contract. At most one tenant
  is **HBM-resident**; paging a tenant in decodes + CRC-verifies the
  host blob and hands the tree to PR 5's double-buffered
  ``request_weight_swap`` (upload overlaps old-tenant serving, the flip
  lands at a poll boundary). Demotion is pure bookkeeping — the host
  copy never left, the old device params die with their last reference
  at the flip. Scale-to-zero follows DeepServe (PAPERS.md, arxiv
  2501.14417): all tenants share one architecture, so the batcher's
  warmed executables serve every tenant and a cold-start is a page-in,
  never a recompile.

* :class:`TenantScheduler` — tags every submission with a tenant id +
  SLO class and decides, against the batcher's poll loop, whether to
  keep **batching deeper** on the resident tenant or **time-slice** to
  a starved one (the decision model of "Batching or Multi-Tenancy?",
  arxiv 2308.13803: a switch is worth its drain+page cost only once a
  waiter's SLO-weighted wait exceeds it). Per-tenant TTFT feedback from
  PR 4's SLO samples biases the score, and a hard wait bound forces the
  flip so no tenant starves. The page-in driver runs on its own
  (caller-role) thread — ``request_weight_swap`` must never run on the
  scheduler thread — while a cheap per-poll hook on the batcher only
  wakes it.

Weight-version namespacing rides underneath (the PR 17 fix): tenant
versions are strings ``"{tenant}@{seq}"``, and the version-keyed purges
in :class:`~.prefix_cache.RadixPrefixIndex` / :class:`~.kvtier.HostKVTier`
retain entries whose namespace differs from the incoming version's — so
paging tenant B in never invalidates tenant A's prefix slabs or tier
checkpoints, and A's cache is warm again the moment A pages back.
"""

from __future__ import annotations

import collections
import json
import logging
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.roles import caller_thread, scheduler_only
from .disagg import ChecksumError, DisaggError, _read_exact
from .prefix_cache import version_namespace

__all__ = [
    "META_TENANT_KEY",
    "PagerEntryCorrupt",
    "PagerRefused",
    "TENANT_HEADER",
    "TenantScheduler",
    "TenantUnknown",
    "WeightPager",
    "parse_tenant_spec",
    "stamp_tenant_meta",
    "tenant_from_meta",
    "version_namespace",
]

logger = logging.getLogger(__name__)

# checkpoint-blob framing (SWP1 = Seldon Weight Pager v1): same
# magic/len/crc discipline as the SKV1 KV-slab codec, but leaf-major —
# a param tree is a list of arbitrary-shape leaves, not [L,1,KV,W,Dh]
_MAGIC = b"SWP1"
_END = b"SWPE"

# SLO classes, strictest first. "strict" is the victim policy's
# protected class; weights bias the scheduler's wait score.
SLO_CLASSES = ("strict", "standard", "best_effort")
_SLO_WEIGHT = {"strict": 4.0, "standard": 2.0, "best_effort": 1.0}

# engine routing: http_server lower-cases header keys at parse time
# (the Seldon-Deadline-Ms convention); the engine stamps the value into
# message meta so in-process hops see it without re-reading headers
TENANT_HEADER = "seldon-tenant"
META_TENANT_KEY = "tenant"


class TenantUnknown(DisaggError):
    """A request named a tenant the pager has no checkpoint for (never
    registered, or LRU-evicted from host staging). 404, not 500: the
    tenant may exist on another member — routing, not serving, decides."""

    status = 404


class PagerRefused(DisaggError):
    """A checkpoint could not enter host staging: larger than half the
    pager budget (a store that can hold one checkpoint thrashes instead
    of paging), or the budget cannot fit it even after evicting every
    cold tenant."""

    status = 507


class PagerEntryCorrupt(ChecksumError):
    """A staged checkpoint failed its SWP1 CRC on page-in. The entry is
    already dropped when this surfaces (it could never page again), so
    the caller fails the tenant's queued work typed instead of serving
    weights that are provably not the ones stored."""


def parse_tenant_spec(spec: str) -> List[Tuple[str, str, Optional[str]]]:
    """Parse the ``seldon.io/tenants`` grammar: comma-separated
    ``name=slo_class[@model_uri]`` entries, e.g.
    ``"acme=strict,globex=best_effort@/models/globex"``. Strict — a
    typo must refuse at admission, not misroute traffic at serve time.
    Returns ``[(name, slo_class, uri_or_None), ...]`` in declaration
    order (the FIRST tenant boots resident)."""
    out: List[Tuple[str, str, Optional[str]]] = []
    seen = set()
    for raw in str(spec).split(","):
        entry = raw.strip()
        if not entry:
            continue
        name, sep, rest = entry.partition("=")
        name = name.strip()
        if not sep or not name or not rest.strip():
            raise ValueError(
                f"tenant entry {entry!r} is not name=slo_class[@uri]"
            )
        slo, sep2, uri = rest.partition("@")
        slo = slo.strip()
        uri = uri.strip() if sep2 else ""
        if slo not in SLO_CLASSES:
            raise ValueError(
                f"tenant {name!r} has unknown SLO class {slo!r} "
                f"(one of {', '.join(SLO_CLASSES)})"
            )
        if not all(c.isalnum() or c in "-_." for c in name):
            raise ValueError(
                f"tenant name {name!r} has characters outside [A-Za-z0-9._-]"
            )
        if "@" in name:
            raise ValueError(f"tenant name {name!r} may not contain '@'")
        if name in seen:
            raise ValueError(f"tenant {name!r} declared twice")
        seen.add(name)
        out.append((name, slo, uri or None))
    if not out:
        raise ValueError("tenants spec declares no tenants")
    return out


def tenant_from_meta(meta) -> Optional[str]:
    """Tenant id from a message meta dict (stamped by the engine from
    the ``Seldon-Tenant`` header), or None."""
    if not isinstance(meta, dict):
        return None
    t = meta.get(META_TENANT_KEY)
    if t is None:
        return None
    t = str(t).strip()
    return t or None


def stamp_tenant_meta(message: Dict, tenant: Optional[str]) -> Dict:
    """Shallow-copy ``message`` with the tenant id in its meta — the
    deadline ``stamp_meta`` idiom, so the id rides serialization to
    remote units and the ``meta`` argument of in-process components."""
    if not tenant:
        return message
    out = dict(message)
    meta = dict(out.get("meta") or {})
    meta[META_TENANT_KEY] = str(tenant)
    out["meta"] = meta
    return out


# -- checkpoint blob codec (SWP1) -------------------------------------------


def _encode_ckpt(meta: Dict[str, Any], leaves: List[np.ndarray]) -> bytes:
    """Frame a flattened param tree: header JSON (meta + per-leaf
    shape/dtype), then one ``u32 len + u32 crc + payload`` frame per
    leaf, then an end frame carrying the running total CRC — the SKV1
    discipline, so corruption anywhere refuses typed before any leaf is
    half-trusted."""
    header = dict(meta)
    header["leaves"] = [
        {"shape": list(a.shape), "dtype": str(a.dtype)} for a in leaves
    ]
    hdr = json.dumps(header).encode()
    parts = [_MAGIC, struct.pack("<II", len(hdr), zlib.crc32(hdr)), hdr]
    total_crc = 0
    for arr in leaves:
        payload = np.ascontiguousarray(arr).tobytes()
        total_crc = zlib.crc32(payload, total_crc)
        parts.append(struct.pack("<II", len(payload), zlib.crc32(payload)))
        parts.append(payload)
    parts.append(_END + struct.pack("<I", total_crc))
    return b"".join(parts)


def _decode_ckpt(
    read: Callable[[int], bytes],
) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """Inverse of :func:`_encode_ckpt`; raises :class:`ChecksumError` /
    :class:`~.disagg.TruncatedStream` before returning partial data."""
    magic = _read_exact(read, 4)
    if magic != _MAGIC:
        raise DisaggError(f"bad pager magic {magic!r} (want {_MAGIC!r})")
    hdr_len, hdr_crc = struct.unpack("<II", _read_exact(read, 8))
    hdr = _read_exact(read, hdr_len)
    if zlib.crc32(hdr) != hdr_crc:
        raise ChecksumError("pager checkpoint header failed its checksum")
    meta = json.loads(hdr)
    leaves: List[np.ndarray] = []
    total_crc = 0
    for spec in meta["leaves"]:
        n, crc = struct.unpack("<II", _read_exact(read, 8))
        payload = _read_exact(read, n)
        if zlib.crc32(payload) != crc:
            raise ChecksumError(
                f"pager checkpoint leaf {len(leaves)} failed its checksum"
            )
        total_crc = zlib.crc32(payload, total_crc)
        leaves.append(
            np.frombuffer(payload, np.dtype(spec["dtype"]))
            .reshape(spec["shape"])
        )
    tail = _read_exact(read, 8)
    if tail[:4] != _END:
        raise DisaggError(f"missing pager end frame (got {tail[:4]!r})")
    (want,) = struct.unpack("<I", tail[4:])
    if want != total_crc:
        raise ChecksumError("pager checkpoint total checksum mismatch")
    return meta, leaves


class _PagerEntry:
    __slots__ = (
        "payload", "nbytes", "version", "treedef", "hbm_bytes", "slo",
        "last_used",
    )

    def __init__(self, payload: bytes, version: str, treedef,
                 hbm_bytes: int, slo: str):
        self.payload = payload
        self.nbytes = len(payload)
        self.version = version
        self.treedef = treedef  # host object; the blob stores leaves only
        self.hbm_bytes = int(hbm_bytes)
        self.slo = slo
        self.last_used = 0


class WeightPager:
    """N tenant checkpoints across host-RAM staging + one HBM residency.

    All public methods take the pager lock; ``promote`` decodes its
    O(checkpoint-bytes) blob OUTSIDE it (the tier's unlocked-decode
    idiom — stored payload bytes are immutable). ``stats`` counters are
    written under the lock; readers see torn-but-harmless ints.

    ``budget_bytes`` bounds HOST staging only. HBM residency is exactly
    one checkpoint (``resident_hbm_bytes``) and is accounted by the
    batcher's pressure ledger as its ``pager`` component — the PR 9
    co-tenant the controller's ``set_budget`` docstring promised.
    """

    def __init__(self, budget_bytes: int):
        self.budget_bytes = max(0, int(budget_bytes))
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, _PagerEntry]" = (
            collections.OrderedDict()
        )
        self._seq: Dict[str, int] = {}
        self._resident: Optional[str] = None
        self._clock = 0
        self.stats = {
            "page_ins": 0, "page_outs": 0, "evictions": 0, "refused": 0,
            "corrupt_dropped": 0, "host_bytes": 0,
        }

    # -- internals ----------------------------------------------------------

    def _host_bytes_locked(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _evict_cold_locked(self, need: int) -> None:
        """LRU-evict non-resident entries until ``need`` bytes fit. The
        resident tenant is never a victim: its host blob is the ONLY
        path back to a demotable state (evicting it would pin residency
        forever)."""
        while self._host_bytes_locked() + need > self.budget_bytes:
            victims = [
                (e.last_used, t) for t, e in self._entries.items()
                if t != self._resident
            ]
            if not victims:
                break
            _, cold = min(victims)
            del self._entries[cold]
            self.stats["evictions"] += 1

    # -- the two-tier store -------------------------------------------------

    @property
    def resident(self) -> Optional[str]:
        return self._resident

    @property
    def resident_hbm_bytes(self) -> int:
        with self._lock:
            e = self._entries.get(self._resident or "")
            return e.hbm_bytes if e is not None else 0

    @property
    def host_bytes(self) -> int:
        with self._lock:
            return self._host_bytes_locked()

    def tenants(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def slo_class(self, tenant: str) -> Optional[str]:
        with self._lock:
            e = self._entries.get(tenant)
            return e.slo if e is not None else None

    def put(self, tenant: str, params, slo: str = "standard") -> str:
        """Stage ``tenant``'s param tree into host RAM (already cast to
        the serving compute dtype — staging the serve-ready bytes halves
        host residency AND makes page-in a decode+upload, no cast).
        Returns the new namespaced weight version ``"{tenant}@{seq}"``;
        a re-put bumps ``seq`` (new weights for that tenant invalidate
        its old cache entries, nobody else's). Raises
        :class:`PagerRefused` when the blob cannot fit."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(params)
        host_leaves = [np.asarray(leaf) for leaf in leaves]
        hbm_bytes = sum(a.nbytes for a in host_leaves)
        with self._lock:
            seq = self._seq.get(tenant, 0) + 1
        version = f"{tenant}@{seq}"
        payload = _encode_ckpt(
            {"kind": "pager_ckpt", "tenant": tenant,
             "weight_version": version},
            host_leaves,
        )
        entry = _PagerEntry(payload, version, treedef, hbm_bytes, slo)
        with self._lock:
            # the half-budget refusal (the tier's anti-thrash rule): a
            # pager that can stage at most one checkpoint cannot page
            if not 0 < entry.nbytes <= self.budget_bytes // 2:
                self.stats["refused"] += 1
                raise PagerRefused(
                    f"tenant {tenant!r} checkpoint ({entry.nbytes} bytes) "
                    f"exceeds half the pager budget ({self.budget_bytes})"
                )
            old = self._entries.pop(tenant, None)
            self._evict_cold_locked(entry.nbytes)
            if self._host_bytes_locked() + entry.nbytes > self.budget_bytes:
                if old is not None:  # failed re-put must not lose the old
                    self._entries[tenant] = old
                self.stats["refused"] += 1
                raise PagerRefused(
                    f"tenant {tenant!r} checkpoint ({entry.nbytes} bytes) "
                    "does not fit even after evicting every cold tenant"
                )
            self._seq[tenant] = seq
            entry.last_used = self._tick()
            self._entries[tenant] = entry
            self.stats["host_bytes"] = self._host_bytes_locked()
        return version

    def promote(self, tenant: str):
        """Decode ``tenant``'s staged checkpoint for a page-in:
        ``(params, version)`` ready for ``request_weight_swap``. Raises
        :class:`TenantUnknown` (never staged / LRU-evicted) or
        :class:`PagerEntryCorrupt` (CRC failure — the entry is dropped
        FIRST, so it can never page again)."""
        import io

        import jax

        with self._lock:
            entry = self._entries.get(tenant)
            if entry is None:
                raise TenantUnknown(
                    f"tenant {tenant!r} has no staged checkpoint "
                    "(never registered, or evicted from host staging)"
                )
            entry.last_used = self._tick()
            payload, treedef, version = (
                entry.payload, entry.treedef, entry.version
            )
        # decode outside the lock: payload bytes are immutable once
        # stored, and an O(checkpoint) memcpy+CRC under the pager lock
        # would block every concurrent submit's residency check
        try:
            _meta, leaves = _decode_ckpt(io.BytesIO(payload).read)
        except DisaggError as e:
            with self._lock:
                if self._entries.get(tenant) is entry:
                    del self._entries[tenant]
                    self.stats["corrupt_dropped"] += 1
                    self.stats["host_bytes"] = self._host_bytes_locked()
            raise PagerEntryCorrupt(
                f"tenant {tenant!r} staged checkpoint failed its "
                f"checksum: {e}"
            ) from e
        return jax.tree_util.tree_unflatten(treedef, leaves), version

    def mark_resident(self, tenant: str) -> Optional[str]:
        """Record that the batcher's flip landed: ``tenant`` now owns
        the HBM residency; the previous owner (returned) is demoted to
        its host blob (scale-to-zero — no device work happens here, the
        old params die with their last reference)."""
        with self._lock:
            if tenant not in self._entries:
                raise TenantUnknown(f"tenant {tenant!r} is not staged")
            old, self._resident = self._resident, tenant
            self._entries[tenant].last_used = self._tick()
            self.stats["page_ins"] += 1
            if old is not None and old != tenant:
                self.stats["page_outs"] += 1
            return old if old != tenant else None

    def drop(self, tenant: str) -> bool:
        """Forget a tenant's staged checkpoint (offboarding). Refuses
        nothing: dropping the resident tenant only removes the page-back
        path, the served weights stay live until the next flip."""
        with self._lock:
            if self._entries.pop(tenant, None) is None:
                return False
            if self._resident == tenant:
                self._resident = None
            self.stats["host_bytes"] = self._host_bytes_locked()
            return True

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "host_bytes": self._host_bytes_locked(),
                "tenants": list(self._entries),
                "resident": self._resident,
                "resident_hbm_bytes": (
                    self._entries[self._resident].hbm_bytes
                    if self._resident in self._entries else 0
                ),
                **{k: v for k, v in self.stats.items() if k != "host_bytes"},
            }


class _QueuedGen:
    __slots__ = ("future", "tokens", "kwargs", "enqueued_t")

    def __init__(self, future, tokens, kwargs):
        self.future = future
        self.tokens = tokens
        self.kwargs = kwargs
        self.enqueued_t = time.monotonic()


class TenantScheduler:
    """Routes submissions by tenant and drives page-ins against the
    batcher's poll loop.

    The resident tenant's submissions pass straight through to
    ``batcher.submit`` (tagged with tenant + SLO class); every other
    tenant's queue per tenant. The driver thread — a CALLER-role thread,
    because ``request_weight_swap`` blocks on scheduler progress —
    periodically scores the waiters and, when a switch is worth its
    cost, pages the winner in:

    1. stop passthrough for the outgoing tenant (decided under the
       routing lock, so nothing new enters the batcher's admit queue),
    2. wait for the batcher's ingress (admit + resume queues) to drain —
       admitted lanes finish on the OLD weights during the swap drain,
       but a QUEUED submit would run under the new ones: wrong tenant,
       wrong bytes,
    3. ``promote`` (CRC-verified host decode) + ``request_weight_swap``
       (double-buffered upload, drain, poll-boundary flip),
    4. ``mark_resident`` + flush the winner's queue.

    Decision rule (arxiv 2308.13803): flip when the best waiter's
    SLO-weighted wait — biased up when its recent TTFT runs over its
    class target — exceeds the observed switch cost (EWMA of real
    page-in latencies), or unconditionally once it has waited
    ``max_wait_polls`` batcher polls (the starvation bound: every
    tenant advances within that many polls of arrival). An idle
    resident always yields.
    """

    TTFT_TARGET_S = {"strict": 0.5, "standard": 2.0, "best_effort": 8.0}

    def __init__(self, batcher, pager: WeightPager,
                 slo_classes: Dict[str, str],
                 tick_s: float = 0.02,
                 max_wait_polls: int = 256,
                 min_resident_s: float = 0.05,
                 swap_wait_s: float = 120.0):
        self.batcher = batcher
        self.pager = pager
        self._slo = dict(slo_classes)
        if not self._slo:
            raise ValueError("TenantScheduler needs at least one tenant")
        self._default = next(iter(self._slo))
        self.tick_s = max(0.001, float(tick_s))
        self.max_wait_polls = max(1, int(max_wait_polls))
        self.min_resident_s = max(0.0, float(min_resident_s))
        self.swap_wait_s = float(swap_wait_s)
        self._lock = threading.Lock()
        self._queues: Dict[str, "collections.deque[_QueuedGen]"] = {
            t: collections.deque() for t in self._slo
        }
        # batcher poll count at which each tenant's OLDEST queued
        # request arrived — the starvation clock (written by the router
        # under the lock, read by the driver; poll counts come from the
        # per-poll hook below)
        self._enqueue_poll: Dict[str, Optional[int]] = {
            t: None for t in self._slo
        }
        self._switching_to: Optional[str] = None
        self._resident_since = time.monotonic()
        self._switch_cost_s = 0.25  # prior until a real page-in lands
        self._poll_count = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        # NOT self._thread: roles._scheduler_thread would mistake the
        # driver for a scheduler thread and invert every assertion
        self._driver = threading.Thread(
            target=self._run, name="tenant-pager-driver", daemon=True
        )
        self.stats = {
            "switches": 0, "passthrough": 0, "queued_submits": 0,
            "forced_switches": 0, "switch_cost_s_sum": 0.0,
        }
        # cheap per-poll bookkeeping on the batcher's scheduler thread
        batcher.tenant_hook = self._on_poll

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "TenantScheduler":
        self._driver.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._driver.is_alive():
            self._driver.join(timeout=5.0)
        # fail queued work loudly: a silently dropped future would pin
        # its collector thread for the full collection timeout
        with self._lock:
            for q in self._queues.values():
                while q:
                    q.popleft().future.set_exception(
                        RuntimeError("tenant scheduler stopped")
                    )

    @scheduler_only
    def _on_poll(self, poll_count: int) -> None:
        """Batcher per-poll hook: publish the poll clock and wake the
        driver when anyone is waiting. Counter + event only — anything
        heavier would tax every poll of the no-waiter hot path."""
        self._poll_count = poll_count
        if any(self._queues.values()):
            self._wake.set()

    # -- routing ------------------------------------------------------------

    @caller_thread
    def submit(self, tokens, tenant: Optional[str] = None, **kwargs):
        """Tenant-routing front of ``batcher.submit``: same signature
        plus ``tenant`` (None routes to the first declared tenant — the
        single-tenant back-compat path). Returns a future; queued
        submissions resolve when their tenant pages in."""
        tenant = tenant or self._default
        slo = self._slo.get(tenant)
        if slo is None or self.pager.slo_class(tenant) is None:
            raise TenantUnknown(
                f"unknown tenant {tenant!r} (declared: "
                f"{', '.join(sorted(self._slo))})"
            )
        with self._lock:
            if (
                tenant == self.pager.resident
                and self._switching_to is None
            ):
                # passthrough under the routing lock: the driver takes
                # the same lock to flag a switch, so a submit can never
                # slip into the admit queue after the ingress-drain wait
                # began (it would decode under the WRONG weights)
                self.stats["passthrough"] += 1
                return self.batcher.submit(
                    tokens, tenant=tenant, slo=slo, **kwargs
                )
            from concurrent.futures import Future

            outer: "Future" = Future()
            self._queues[tenant].append(_QueuedGen(outer, tokens, kwargs))
            if self._enqueue_poll[tenant] is None:
                self._enqueue_poll[tenant] = self._poll_count
            self.stats["queued_submits"] += 1
        self._wake.set()
        return outer

    # -- the page-in driver -------------------------------------------------

    @caller_thread
    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.tick_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            target, forced = self._decide()
            if target is None:
                continue
            try:
                self._switch_to(target, forced)
            except Exception as e:  # noqa: BLE001 - fail queued work typed
                logger.exception("tenant page-in of %r failed", target)
                with self._lock:
                    self._switching_to = None
                    q = self._queues[target]
                    while q:
                        q.popleft().future.set_exception(e)
                    self._enqueue_poll[target] = None

    def _recent_ttft_s(self, tenant: str) -> Optional[float]:
        """Mean TTFT over the batcher's per-tenant SLO reservoir (PR 4's
        samples, split per tenant by ``_resolve``) — the feedback signal
        that biases a waiter whose class target is already blown."""
        recent = getattr(self.batcher, "tenant_slo_recent", {}).get(tenant)
        if not recent:
            return None
        ttfts = [s[1] for s in list(recent)[-32:]]
        return sum(ttfts) / len(ttfts) if ttfts else None

    def _decide(self) -> Tuple[Optional[str], bool]:
        """Score the waiters; ``(winner, forced)`` or ``(None, False)``
        to keep batching deeper on the resident tenant."""
        now = time.monotonic()
        with self._lock:
            if self._switching_to is not None:
                return None, False
            waiters = {t: q for t, q in self._queues.items() if q}
            if not waiters:
                return None, False
            resident = self.pager.resident
            poll = self._poll_count
            best, best_score, forced = None, 0.0, False
            for t, q in waiters.items():
                waited_s = now - q[0].enqueued_t
                weight = _SLO_WEIGHT.get(self._slo[t], 1.0)
                score = waited_s * weight
                ttft = self._recent_ttft_s(t)
                target = self.TTFT_TARGET_S.get(self._slo[t], 2.0)
                if ttft is not None and ttft > target:
                    # class target already blown: escalate
                    score *= 1.0 + min(4.0, ttft / target - 1.0)
                since = self._enqueue_poll[t]  # seeded by submit()
                if since is not None and poll - since >= self.max_wait_polls:
                    forced = True
                    score = float("inf")
                if score > best_score or best is None:
                    best, best_score = t, score
            if best is None:
                return None, False
            # batch-deeper rule: while the resident tenant still has
            # live or queued work and no waiter has outgrown the switch
            # cost, a flip would trade realized throughput for drain +
            # page latency (2308.13803's crossover)
            resident_busy = resident is not None and (
                bool(self.batcher._active)
                or bool(self.batcher._chunked)
                or not self.batcher._queue.empty()
            )
            if (
                not forced
                and resident_busy
                and (
                    best_score <= self._switch_cost_s
                    or now - self._resident_since < self.min_resident_s
                )
            ):
                return None, False
            self._switching_to = best
        return best, forced

    def _switch_to(self, tenant: str, forced: bool) -> None:
        b = self.batcher
        outgoing = self.pager.resident
        # ingress drain: everything already admitted finishes on the old
        # weights under the swap's own drain; everything still QUEUED
        # would run under the new ones — wait it out (passthrough is
        # already off: _switching_to is set)
        while not (b._queue.empty() and not b._resume_queue):
            if self._stop.is_set():
                with self._lock:
                    self._switching_to = None
                return
            time.sleep(0.002)
        t0 = time.monotonic()
        params, version = self.pager.promote(tenant)
        fut = b.request_weight_swap(params, version=version)
        fut.result(timeout=self.swap_wait_s)
        self.pager.mark_resident(tenant)
        cost_s = time.monotonic() - t0
        # EWMA of realized page-in cost: the decision threshold tracks
        # what a switch actually costs on THIS model/host
        self._switch_cost_s = 0.7 * self._switch_cost_s + 0.3 * cost_s
        if b.flight is not None and b.flight.enabled:
            if outgoing is not None:
                b.flight.record({
                    "type": "weight_page_out", "tenant": outgoing,
                    "host_bytes": self.pager.host_bytes,
                })
            b.flight.record({
                "type": "weight_page_in", "tenant": tenant,
                "version": version, "cost_ms": round(cost_s * 1e3, 3),
            })
            b.flight.record({
                "type": "tenant_switch", "from": outgoing, "to": tenant,
                "forced": forced, "cost_ms": round(cost_s * 1e3, 3),
                "queued": len(self._queues[tenant]),
            })
        with self._lock:
            self.stats["switches"] += 1
            self.stats["switch_cost_s_sum"] += cost_s
            if forced:
                self.stats["forced_switches"] += 1
            self._resident_since = time.monotonic()
            self._switching_to = None
            self._enqueue_poll[tenant] = None
            slo = self._slo[tenant]
            q = self._queues[tenant]
            # flush under the lock: concurrent submits for this tenant
            # now pass through, and FIFO order between the queue and
            # them only holds if the flush finishes first
            while q:
                item = q.popleft()
                try:
                    inner = self.batcher.submit(
                        item.tokens, tenant=tenant, slo=slo, **item.kwargs
                    )
                except Exception as e:  # noqa: BLE001 - typed to the caller
                    item.future.set_exception(e)
                    continue
                self._chain(inner, item.future)  # seldon-lint: disable=blocking-under-lock (registers a done callback; the .result() runs on the resolving thread, never here)

    @staticmethod
    def _chain(inner, outer) -> None:
        """Resolve a queued request's outer future from the batcher's
        inner one (result, exception, AND the ``gen_request`` attribute
        the server's response builder reads)."""
        gr = getattr(inner, "gen_request", None)
        if gr is not None:
            outer.gen_request = gr

        def _copy(f):
            if outer.cancelled():
                return
            e = f.exception()
            if e is not None:
                outer.set_exception(e)
            else:
                outer.set_result(f.result())

        inner.add_done_callback(_copy)

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "tenants": {t: self._slo[t] for t in self._slo},
                "resident": self.pager.resident,
                "switching_to": self._switching_to,
                "queued": {t: len(q) for t, q in self._queues.items() if q},
                "switch_cost_s": round(self._switch_cost_s, 6),
                **{k: v for k, v in self.stats.items()},
            }
