from .continuous import BatcherDead, ContinuousBatcher, GenRequest  # noqa: F401
from .prefix_cache import RadixPrefixIndex  # noqa: F401
