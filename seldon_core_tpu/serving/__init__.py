from .continuous import ContinuousBatcher, GenRequest  # noqa: F401
