"""Prefill/decode disaggregation: KV-slab wire codec + transports.

DeepServe-style decoupled serving (PAPERS.md, arxiv 2501.14417): prefill
and decode run in independently scaled pools, and the finished prompt
K/V slab crosses a transport instead of a lane insert. This module is
the wire half — the batcher half lives in ``continuous.py``
(``ContinuousBatcher.export_prefill`` / ``admit_remote``).

The unit of transfer is a **slab message**: the ``cache_one``-layout
prompt K/V stack ``{"k","v"}`` of ``[L, 1, KV, W, Dh]`` host arrays plus
a metadata dict (prompt tokens + hash, dtype/layout, the first sampled
token, the post-split RNG lane key, ``weight_version``, sampling
params, and ``covered_len`` — how many leading prompt tokens the decode
side already holds in its radix prefix cache, so only the suffix slab
is on the wire).

Wire format (version ``SKV1``), streamed **layer-major** so the decode
side can start uploading layer 0 while layer L-1 is still in flight::

    b"SKV1" | u32 header_len | u32 crc32(header) | header JSON
    per layer l in 0..L-1, for each of k, v:
        u32 payload_len | u32 crc32(payload) | payload bytes
    b"SKVE" | u32 total_crc32 (running crc over every payload)

The header carries its own CRC because a flipped bit there is the
nastiest corruption: a still-valid-JSON header with a wrong
``first_token`` or RNG key would seed a lane with silently wrong
output, not a crash.

Every frame is checksummed; a mismatch raises :class:`ChecksumError`
and a short read raises :class:`TruncatedStream` — both BEFORE any lane
state exists on the decode side (no half-admitted lane, the codec
satellite's contract). Errors from the prefill peer travel as a
``b"SKV!"``-prefixed JSON frame instead of a header.

Transports:

* :class:`LoopbackTransport` — in-process: the decode server holds a
  direct reference to the prefill server, but the slab still round-trips
  the full encode/decode codec through memory, so loopback exercises
  byte-identical framing to TCP (and the codec tests cover both).
* :class:`TcpKVClient` / :class:`PrefillTransportServer` — chunked
  TCP/DCN: the client sends one JSON request line, the server streams
  the slab back in ``chunk_bytes`` writes (the sender never materialises
  more than one chunk beyond the OS socket buffer — the bounded
  in-flight contract), deadline-aware per PR 2 (the remaining request
  budget becomes the socket timeout on both connect and read).
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import socket
import struct
import threading
import zlib
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MAGIC = b"SKV1"
END = b"SKVE"
ERR = b"SKV!"
WIRE_VERSION = 1


class DisaggError(RuntimeError):
    """Base for disaggregation failures; carries a wire status so the
    graph executor surfaces it as a typed UnitCallError."""

    status = 502


class ChecksumError(DisaggError):
    """A slab frame's CRC did not match — the stream is corrupt."""


class TruncatedStream(DisaggError):
    """The stream ended mid-frame — nothing was admitted."""


class WeightVersionMismatch(DisaggError):
    """The slab was prefilled under a different weight version than the
    decode pool is serving (a hot-swap landed between prefill and
    admit): the K/V would be stale, refuse the splice."""

    status = 409


class PrefixGone(DisaggError):
    """The decode-side radix entry that justified suffix-only transfer
    was evicted before the admit; the caller re-requests a full slab."""


class TierMiss(DisaggError):
    """A peer prefix-lookup found nothing usable in the listener's host
    KV tier (no entry, below the promote gate, caps, or a
    weight-version gap). Peer-SPECIFIC state, not peer death: the
    failover layer rotates the lookup to another peer's tier WITHOUT
    ejecting (the prefix may be warm one member over), and when every
    consulted tier misses the decode side simply prefills as usual — a
    cold tier must never look like a dead pool."""

    status = 404


class PeerBusy(DisaggError):
    """The prefill peer shed the transfer at its capacity bound — busy,
    not dead. The failover layer tries another peer WITHOUT ejecting
    this one (a loaded pool must not look like a dead pool)."""

    status = 503


class AllPeersDown(DisaggError):
    """Every configured prefill peer is currently ejected. The decode
    server catches this and degrades to LOCAL unified prefill (the
    batcher owns the full prefill path), counting the regression in
    ``degraded_local_prefill``."""

    status = 503


def prompt_hash(tokens) -> str:
    return hashlib.sha256(
        np.asarray(tokens, np.int32).tobytes()
    ).hexdigest()[:16]


def _read_exact(read: Callable[[int], bytes], n: int) -> bytes:
    """Read exactly n bytes or raise TruncatedStream."""
    chunks = []
    got = 0
    while got < n:
        b = read(n - got)
        if not b:
            raise TruncatedStream(
                f"stream ended after {got} of {n} expected bytes"
            )
        chunks.append(b)
        got += len(b)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


def encode_slab(
    meta: Dict[str, Any],
    slab: Dict[str, np.ndarray],
    chunk_bytes: int = 1 << 20,
) -> Iterator[bytes]:
    """Yield the wire frames of one slab message. ``slab`` holds host
    arrays ``[L, 1, KV, W, Dh]``; frames come out layer-major (k then v
    per layer) in writes of at most ``chunk_bytes`` so a streaming
    sender never holds more than one chunk in flight."""
    k, v = np.ascontiguousarray(slab["k"]), np.ascontiguousarray(slab["v"])
    if k.shape != v.shape:
        raise DisaggError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    header = dict(meta)
    header["wire_version"] = WIRE_VERSION
    header["shape"] = list(k.shape)
    header["slab_dtype"] = str(k.dtype)
    hdr = json.dumps(header).encode()
    yield MAGIC + struct.pack("<II", len(hdr), zlib.crc32(hdr)) + hdr
    total_crc = 0
    for layer in range(k.shape[0]):
        for arr in (k[layer], v[layer]):
            payload = arr.tobytes()
            total_crc = zlib.crc32(payload, total_crc)
            yield struct.pack("<II", len(payload), zlib.crc32(payload))
            for off in range(0, len(payload), chunk_bytes):
                yield payload[off:off + chunk_bytes]
    yield END + struct.pack("<I", total_crc)


def decode_slab(
    read: Callable[[int], bytes],
) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Consume one slab message from ``read`` (a ``recv``-style callable
    returning up to n bytes, b"" at EOF). Returns ``(meta, slab)``.
    Raises :class:`ChecksumError` / :class:`TruncatedStream` /
    :class:`DisaggError` — always before returning partial data."""
    magic = _read_exact(read, 4)
    if magic == ERR:
        (n,) = struct.unpack("<I", _read_exact(read, 4))
        err = json.loads(_read_exact(read, n))
        cls = {
            "weight_version": WeightVersionMismatch,
            "capacity": PeerBusy,
            "tier_miss": TierMiss,
        }.get(err.get("kind"), DisaggError)
        raise cls(err.get("error", "prefill peer error"))
    if magic != MAGIC:
        raise DisaggError(f"bad slab magic {magic!r} (want {MAGIC!r})")
    hdr_len, hdr_crc = struct.unpack("<II", _read_exact(read, 8))
    hdr = _read_exact(read, hdr_len)
    if zlib.crc32(hdr) != hdr_crc:
        raise ChecksumError("slab header failed its checksum")
    meta = json.loads(hdr)
    if meta.get("wire_version") != WIRE_VERSION:
        raise DisaggError(
            f"unsupported slab wire version {meta.get('wire_version')!r}"
        )
    shape = tuple(meta["shape"])
    dtype = np.dtype(meta["slab_dtype"])
    if len(shape) != 5:
        raise DisaggError(f"slab shape must be [L,1,KV,W,Dh], got {shape}")
    layer_bytes = int(np.prod(shape[1:])) * dtype.itemsize
    out = {
        "k": np.empty(shape, dtype),
        "v": np.empty(shape, dtype),
    }
    total_crc = 0
    for layer in range(shape[0]):
        for name in ("k", "v"):
            n, crc = struct.unpack("<II", _read_exact(read, 8))
            if n != layer_bytes:
                raise DisaggError(
                    f"layer {layer} {name} frame is {n} bytes, "
                    f"expected {layer_bytes} for shape {shape}"
                )
            payload = _read_exact(read, n)
            if zlib.crc32(payload) != crc:
                raise ChecksumError(
                    f"layer {layer} {name} frame failed its checksum"
                )
            total_crc = zlib.crc32(payload, total_crc)
            out[name][layer] = np.frombuffer(payload, dtype).reshape(shape[1:])
    tail = _read_exact(read, 8)
    if tail[:4] != END:
        raise TruncatedStream(f"missing end frame (got {tail[:4]!r})")
    (want,) = struct.unpack("<I", tail[4:])
    if want != total_crc:
        raise ChecksumError("stream total checksum mismatch")
    return meta, out


def encode_error(err: Exception, kind: Optional[str] = None) -> bytes:
    if kind is None:
        if isinstance(err, WeightVersionMismatch):
            kind = "weight_version"
        elif isinstance(err, PeerBusy):
            kind = "capacity"
        elif isinstance(err, TierMiss):
            kind = "tier_miss"
        else:
            kind = "error"
    body = json.dumps({"error": str(err), "kind": kind}).encode()
    return ERR + struct.pack("<I", len(body)) + body


def encode_pong() -> bytes:
    """Health-probe answer, riding the SKV1 error-frame path (no new
    wire machinery): ``kind == "pong"`` never raises — the probing
    client reads it directly."""
    return encode_error(DisaggError("pong"), kind="pong")


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------


class LoopbackTransport:
    """In-process transport: a direct reference to the prefill-side
    handler, with the slab still round-tripping the full codec through a
    memory buffer (framing bugs can't hide behind shared memory).
    ``fault`` (resilience.faults.KVFaults) perturbs the byte stream the
    same way it perturbs the TCP reads — chaos coverage without
    sockets."""

    name = "loopback"

    def __init__(self, prefill_server, chunk_bytes: int = 1 << 20,
                 fault=None):
        self._server = prefill_server
        self._chunk = int(chunk_bytes)
        self._fault = fault
        self.addr = f"loopback:{id(prefill_server):x}"

    def prefill(
        self, request: Dict[str, Any], deadline_s: Optional[float] = None
    ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
        if self._fault is not None:
            self._fault.before_connect()
        buf = io.BytesIO()
        try:
            meta, slab = self._server.prefill_export(request)
            for frame in encode_slab(meta, slab, self._chunk):
                buf.write(frame)
        except DisaggError as e:
            buf = io.BytesIO(encode_error(e))
        buf.seek(0)
        read = buf.read
        if self._fault is not None:
            read = self._fault.wrap_read(read)
        return decode_slab(read)

    def probe(self, timeout_s: float = 2.0) -> bool:
        """Loopback health probe: ask the in-process prefill server's
        ``kv_ping`` hook (False once its batcher is dead/closed)."""
        if self._fault is not None and not self._fault.connectable():
            return False
        ping = getattr(self._server, "kv_ping", None)
        if ping is None:
            return True
        try:
            return bool(ping())
        except Exception:  # noqa: BLE001 - an unhealthy peer must probe False
            return False

    def close(self) -> None:
        pass


class TcpKVClient:
    """Decode-side client for the chunked TCP/DCN transport: one
    connection per transfer (the slab dominates any handshake cost),
    deadline-aware — the remaining request budget is the socket timeout
    for connect and every read."""

    name = "tcp"

    def __init__(self, peer: str, connect_timeout_s: float = 10.0,
                 fault=None):
        host, _, port = peer.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"peer must be host:port, got {peer!r}")
        self.host, self.port = host, int(port)
        self.addr = f"{self.host}:{self.port}"
        self._connect_timeout = float(connect_timeout_s)
        self._fault = fault

    def prefill(
        self, request: Dict[str, Any], deadline_s: Optional[float] = None
    ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
        timeout = (
            min(self._connect_timeout, deadline_s)
            if deadline_s is not None else self._connect_timeout
        )
        import time as _time

        expires_at = (
            _time.monotonic() + deadline_s if deadline_s is not None else None
        )
        try:
            if self._fault is not None:
                self._fault.before_connect()
            sock = socket.create_connection(
                (self.host, self.port), timeout=timeout
            )
        except OSError as e:
            raise DisaggError(
                f"prefill peer {self.host}:{self.port} unreachable: {e}"
            ) from e

        def read(n: int) -> bytes:
            # the REMAINING budget bounds every read: a peer dripping one
            # chunk per almost-deadline must still finish the whole
            # transfer inside the request budget, not reset the clock
            # per recv
            if expires_at is not None:
                remaining = expires_at - _time.monotonic()
                if remaining <= 0:
                    raise socket.timeout("kv transfer budget exhausted")
                sock.settimeout(remaining)
            return sock.recv(n)

        if self._fault is not None:
            read = self._fault.wrap_read(read)
        try:
            sock.settimeout(
                max(0.001, expires_at - _time.monotonic())
                if expires_at is not None else 60.0
            )
            sock.sendall(json.dumps(request).encode() + b"\n")
            return decode_slab(read)
        except socket.timeout as e:
            raise DisaggError(
                f"kv transfer from {self.host}:{self.port} ran past the "
                "deadline"
            ) from e
        except OSError as e:
            # mid-stream connection loss (e.g. a prefill-pool resize
            # tearing the listener down under us) must surface with the
            # same typed status every other transport failure carries
            raise DisaggError(
                f"kv transfer from {self.host}:{self.port} failed "
                f"mid-stream: {e}"
            ) from e
        finally:
            sock.close()

    def probe(self, timeout_s: float = 2.0) -> bool:
        """Cheap KV-port health ping: one connection, one
        ``{"ping": true}`` line, one SKV1 error-frame pong back — no
        device work, no handler slot at the peer. True means the
        listener is up AND answering the wire protocol (a port held by
        a foreign process probes False)."""
        if self._fault is not None and not self._fault.connectable():
            return False
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=timeout_s
            )
        except OSError:
            return False
        try:
            sock.settimeout(timeout_s)
            sock.sendall(b'{"ping": true}\n')
            magic = _read_exact(sock.recv, 4)
            if magic != ERR:
                return False
            (n,) = struct.unpack("<I", _read_exact(sock.recv, 4))
            body = json.loads(_read_exact(sock.recv, n))
            return body.get("kind") == "pong"
        except (OSError, ValueError, DisaggError):
            return False
        finally:
            sock.close()

    def close(self) -> None:
        pass


class PrefillTransportServer:
    """Prefill-side TCP listener: accepts one JSON request line per
    connection and streams the slab back frame by frame (``chunk_bytes``
    per write — the sender-side in-flight bound). Runs accept + handler
    threads, at most ``max_inflight`` concurrently — each handler holds
    a device prefill plus a whole host-side slab, so an unbounded burst
    of decode-pool connections would collapse exactly the pool
    disaggregation is meant to isolate; over-limit connections get an
    immediate typed shed frame instead of queueing. ``close()`` unblocks
    the accept loop."""

    def __init__(
        self,
        prefill_server,
        port: int = 0,
        host: str = "0.0.0.0",
        chunk_bytes: int = 1 << 20,
        max_inflight: int = 8,
    ):
        self._server = prefill_server
        self._chunk = int(chunk_bytes)
        self._slots = threading.Semaphore(max(1, int(max_inflight)))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="kv-export", daemon=True
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # closed
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        # ONE bounded read classifies the connection (a ping is a tiny
        # single-packet line): pings are answered WITHOUT consuming a
        # handler slot — a pool at capacity is busy, not dead, and the
        # failover layer must be able to tell the two apart — while
        # everything else hits the capacity check BEFORE the rest of
        # its request uploads, preserving shed-before-work (N slow
        # clients may not pin N threads + 8 MiB buffers each just by
        # dribbling their request lines past an acquired slot).
        try:
            conn.settimeout(60.0)
            first = conn.recv(65536)
            if not first:
                conn.close()
                return
        except Exception:  # noqa: BLE001 - one bad peer must not kill accept
            conn.close()
            return
        if first.startswith(b'{"ping"'):
            try:
                conn.sendall(encode_pong())
            except OSError:
                pass
            finally:
                conn.close()
            return
        if not self._slots.acquire(blocking=False):
            # prefill-side shed-before-work: reject NOW, from this
            # connection's own thread, rather than stacking device
            # forwards and slab buffers behind the listener. The frame
            # carries the capacity kind so clients see PeerBusy (retry
            # another peer) instead of a dead-peer ejection.
            try:
                conn.sendall(encode_error(PeerBusy(
                    "prefill pool at capacity — retry"
                )))
            except OSError:
                pass
            finally:
                conn.close()
            return
        try:
            self._handle_locked(conn, first)
        finally:
            self._slots.release()

    def _handle_locked(self, conn: socket.socket, line: bytes) -> None:
        try:
            while not line.endswith(b"\n"):
                b = conn.recv(65536)
                if not b:
                    return
                line += b
                if len(line) > 8 << 20:
                    raise DisaggError("oversized prefill request")
            request = json.loads(line)
            if request.get("ping"):
                # unusually framed ping (multi-packet / leading space):
                # still answered, just from a slot
                conn.sendall(encode_pong())
                return
            try:
                meta, slab = self._server.prefill_export(request)
            except DisaggError as e:
                conn.sendall(encode_error(e))
                return
            except Exception as e:  # noqa: BLE001 - bad request params
                conn.sendall(encode_error(DisaggError(str(e))))
                return
            for frame in encode_slab(meta, slab, self._chunk):
                conn.sendall(frame)
        except (ConnectionResetError, BrokenPipeError) as e:
            # the client hung up mid-stream (deadline, corruption abort,
            # its own failover retry) — routine under chaos, one info
            # line; ERROR stays reserved for listener-side faults
            logger.info("kv export client disconnected mid-stream: %s", e)
        except Exception:  # noqa: BLE001 - one bad peer must not kill accept
            logger.exception("kv export connection failed")
        finally:
            conn.close()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)


class _PeerState:
    """One prefill peer inside a FailoverKVClient: its transport plus
    the ejection bookkeeping (consecutive failures drive an exponential
    re-probe backoff; a probe success resets it)."""

    __slots__ = ("transport", "addr", "healthy", "fails", "eject_until")

    def __init__(self, transport):
        self.transport = transport
        self.addr = getattr(transport, "addr", transport.name)
        self.healthy = True
        self.fails = 0
        self.eject_until = 0.0


class FailoverKVClient:
    """Decode-side transport over a prefill-peer LIST.

    Peers are tried round-robin. A transfer failure that smells like a
    dead peer (unreachable, truncated/corrupt stream, handler crash)
    **ejects** that peer — it sits out an exponential backoff, then the
    next selection **probes** it (cheap KV-port ping on the SKV1
    error-frame path) and readmits on success. A failed transfer is
    retried ONCE on the next healthy peer before surfacing, so one sick
    peer costs a retry, not an error. Typed refusals that are about the
    *request*, not the peer — :class:`WeightVersionMismatch`,
    :class:`PrefixGone` — pass straight through, and :class:`PeerBusy`
    (the capacity shed frame) rotates to another peer WITHOUT ejecting.
    When every peer is ejected, :class:`AllPeersDown` surfaces so the
    decode server can degrade to local unified prefill.

    ``on_eject(addr, reason)`` / ``on_readmit(addr)`` hooks feed the
    decode server's ``peer_ejections`` counters and ``peer_ejected``
    flight-recorder records."""

    name = "failover"

    def __init__(
        self,
        transports,
        eject_backoff_s: float = 1.0,
        max_backoff_s: float = 30.0,
        probe_timeout_s: float = 2.0,
        on_eject: Optional[Callable[[str, str], None]] = None,
        on_readmit: Optional[Callable[[str], None]] = None,
    ):
        if not transports:
            raise ValueError("FailoverKVClient needs at least one peer")
        self._peers = [_PeerState(t) for t in transports]
        self._eject_backoff = float(eject_backoff_s)
        self._max_backoff = float(max_backoff_s)
        self._probe_timeout = float(probe_timeout_s)
        self._on_eject = on_eject
        self._on_readmit = on_readmit
        self._cursor = 0
        self._lock = threading.Lock()

    @property
    def peers(self):
        return list(self._peers)

    def healthy_count(self) -> int:
        return sum(1 for p in self._peers if p.healthy)

    def _backoff_s(self, fails: int) -> float:
        """Exponential re-probe backoff for a peer with ``fails``
        consecutive failures. The exponent is clamped (the backoff caps
        at max_backoff_s anyway): a peer that stays dead for hours —
        e.g. a stale listener in a decode survivor's peer list after a
        prefill-pool scale-down — keeps growing ``fails``, and an
        unclamped ``2 ** fails`` would eventually overflow float and
        crash the request path instead of backing off."""
        return min(
            self._eject_backoff * (2 ** min(int(fails), 16)),
            self._max_backoff,
        )

    def _probe_failed(self, peer: _PeerState, now: float) -> None:
        """One failed re-probe: extend the ejection window and grow the
        failure streak (single home for the backoff bookkeeping)."""
        with self._lock:
            peer.eject_until = now + self._backoff_s(peer.fails)
            peer.fails += 1

    def _eject(self, peer: _PeerState, reason: str) -> None:
        import time as _time

        with self._lock:
            peer.fails += 1
            backoff = self._backoff_s(peer.fails - 1)
            peer.healthy = False
            peer.eject_until = _time.monotonic() + backoff
        logger.warning(
            "prefill peer %s ejected for %.1fs (failure %d): %s",
            peer.addr, backoff, peer.fails, reason,
        )
        if self._on_eject is not None:
            try:
                self._on_eject(peer.addr, reason)
            except Exception:  # noqa: BLE001 - telemetry must not break failover
                logger.exception("on_eject hook failed")

    def _readmit(self, peer: _PeerState) -> None:
        with self._lock:
            peer.healthy = True
            peer.fails = 0
            peer.eject_until = 0.0
        logger.info("prefill peer %s readmitted (probe ok)", peer.addr)
        if self._on_readmit is not None:
            try:
                self._on_readmit(peer.addr)
            except Exception:  # noqa: BLE001
                logger.exception("on_readmit hook failed")

    def probe_ejected(self) -> int:
        """Probe every backoff-expired ejected peer now; returns how many
        were readmitted. The selection path does this lazily per pick —
        this entry point exists for periodic probers and tests."""
        import time as _time

        now = _time.monotonic()
        readmitted = 0
        for peer in self._peers:
            if not peer.healthy and now >= peer.eject_until:
                if peer.transport.probe(self._probe_timeout):
                    self._readmit(peer)
                    readmitted += 1
                else:
                    self._probe_failed(peer, now)
        return readmitted

    def _pick(self, exclude) -> Optional[_PeerState]:
        """Next usable peer round-robin: healthy first; an ejected peer
        whose backoff expired is probed and readmitted inline (the
        "readmitted on probe success" half of the failover contract)."""
        import time as _time

        n = len(self._peers)
        now = _time.monotonic()
        # healthy pass
        for i in range(n):
            with self._lock:
                peer = self._peers[self._cursor % n]
                self._cursor += 1
            if peer in exclude:
                continue
            if peer.healthy:
                return peer
            if now >= peer.eject_until:
                if peer.transport.probe(self._probe_timeout):
                    self._readmit(peer)
                    return peer
                self._probe_failed(peer, now)
        return None

    def prefill(
        self, request: Dict[str, Any], deadline_s: Optional[float] = None
    ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
        tried: list = []
        busy_err: Optional[Exception] = None
        miss_err: Optional[Exception] = None
        while len(tried) < 2:
            peer = self._pick(exclude=tried)
            if peer is None:
                if miss_err is not None:
                    raise miss_err  # consulted tiers all missed
                if busy_err is not None:
                    raise busy_err  # every peer busy != every peer dead
                raise AllPeersDown(
                    f"all {len(self._peers)} prefill peers are ejected "
                    f"({', '.join(p.addr for p in self._peers)})"
                )
            try:
                out = peer.transport.prefill(request, deadline_s=deadline_s)
            except (WeightVersionMismatch, PrefixGone):
                raise  # about the request/version, not the peer
            except TierMiss as e:
                # peer-SPECIFIC state (that member's tier is cold), not
                # request state: rotate to another peer's tier WITHOUT
                # ejecting — the prefix may well be warm one peer over
                miss_err = e
                tried.append(peer)
                continue
            except PeerBusy as e:
                busy_err = e
                tried.append(peer)
                continue
            except ValueError:
                raise  # malformed request: no peer would serve it
            except Exception as e:  # noqa: BLE001 - peer-death class
                self._eject(peer, f"{type(e).__name__}: {e}")
                tried.append(peer)
                continue
            if peer.fails:
                with self._lock:
                    peer.fails = 0
            return out
        # two peers failed the SAME transfer: surface a typed error (the
        # unary caller maps it; the decode server may still fall back
        # locally when the pool then fully ejects)
        if miss_err is not None:
            raise miss_err  # both consulted tiers missed: a miss, typed
        if busy_err is not None:
            raise busy_err  # capacity, not death: 503-retry semantics
        if self.healthy_count() == 0:
            raise AllPeersDown(
                f"all {len(self._peers)} prefill peers are ejected "
                f"({', '.join(p.addr for p in self._peers)})"
            )
        raise DisaggError(
            f"kv transfer failed on {len(tried)} peers "
            f"({', '.join(p.addr for p in tried)}); retry"
        )

    def close(self) -> None:
        for peer in self._peers:
            try:
                peer.transport.close()
            except Exception:  # noqa: BLE001
                pass


def make_transport(peer, chunk_bytes: int = 1 << 20, fault=None):
    """``peer`` is either a live prefill-server object (loopback) or a
    ``"host:port"`` string (TCP)."""
    if isinstance(peer, str):
        return TcpKVClient(peer, fault=fault)
    return LoopbackTransport(peer, chunk_bytes=chunk_bytes, fault=fault)


def make_failover(
    peers,
    chunk_bytes: int = 1 << 20,
    fault_for: Optional[Callable[[str], Any]] = None,
    **failover_kw,
):
    """Build the decode side's transport from a peer LIST (each entry a
    live prefill-server object or ``host:port`` string; a lone
    ``"a:1,b:2"`` string is split). Always returns a
    :class:`FailoverKVClient` — a single peer is just a list of one, so
    ejection/degradation semantics are uniform across pool sizes.
    ``fault_for(addr)`` resolves the chaos injector's per-peer KV fault
    hook (None = no faults)."""
    if isinstance(peers, str):
        peers = [p.strip() for p in peers.split(",") if p.strip()]
    elif not isinstance(peers, (list, tuple)):
        peers = [peers]
    transports = []
    for p in peers:
        addr = p if isinstance(p, str) else f"loopback:{id(p):x}"
        fault = fault_for(addr) if fault_for is not None else None
        transports.append(make_transport(p, chunk_bytes=chunk_bytes,
                                         fault=fault))
    return FailoverKVClient(transports, **failover_kw)
