"""Prefill/decode disaggregation: KV-slab wire codec + transports.

DeepServe-style decoupled serving (PAPERS.md, arxiv 2501.14417): prefill
and decode run in independently scaled pools, and the finished prompt
K/V slab crosses a transport instead of a lane insert. This module is
the wire half — the batcher half lives in ``continuous.py``
(``ContinuousBatcher.export_prefill`` / ``admit_remote``).

The unit of transfer is a **slab message**: the ``cache_one``-layout
prompt K/V stack ``{"k","v"}`` of ``[L, 1, KV, W, Dh]`` host arrays plus
a metadata dict (prompt tokens + hash, dtype/layout, the first sampled
token, the post-split RNG lane key, ``weight_version``, sampling
params, and ``covered_len`` — how many leading prompt tokens the decode
side already holds in its radix prefix cache, so only the suffix slab
is on the wire).

Wire format (version ``SKV1``), streamed **layer-major** so the decode
side can start uploading layer 0 while layer L-1 is still in flight::

    b"SKV1" | u32 header_len | u32 crc32(header) | header JSON
    per layer l in 0..L-1, for each of k, v:
        u32 payload_len | u32 crc32(payload) | payload bytes
    b"SKVE" | u32 total_crc32 (running crc over every payload)

The header carries its own CRC because a flipped bit there is the
nastiest corruption: a still-valid-JSON header with a wrong
``first_token`` or RNG key would seed a lane with silently wrong
output, not a crash.

Every frame is checksummed; a mismatch raises :class:`ChecksumError`
and a short read raises :class:`TruncatedStream` — both BEFORE any lane
state exists on the decode side (no half-admitted lane, the codec
satellite's contract). Errors from the prefill peer travel as a
``b"SKV!"``-prefixed JSON frame instead of a header.

Transports:

* :class:`LoopbackTransport` — in-process: the decode server holds a
  direct reference to the prefill server, but the slab still round-trips
  the full encode/decode codec through memory, so loopback exercises
  byte-identical framing to TCP (and the codec tests cover both).
* :class:`TcpKVClient` / :class:`PrefillTransportServer` — chunked
  TCP/DCN: the client sends one JSON request line, the server streams
  the slab back in ``chunk_bytes`` writes (the sender never materialises
  more than one chunk beyond the OS socket buffer — the bounded
  in-flight contract), deadline-aware per PR 2 (the remaining request
  budget becomes the socket timeout on both connect and read).
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import socket
import struct
import threading
import zlib
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MAGIC = b"SKV1"
END = b"SKVE"
ERR = b"SKV!"
WIRE_VERSION = 1


class DisaggError(RuntimeError):
    """Base for disaggregation failures; carries a wire status so the
    graph executor surfaces it as a typed UnitCallError."""

    status = 502


class ChecksumError(DisaggError):
    """A slab frame's CRC did not match — the stream is corrupt."""


class TruncatedStream(DisaggError):
    """The stream ended mid-frame — nothing was admitted."""


class WeightVersionMismatch(DisaggError):
    """The slab was prefilled under a different weight version than the
    decode pool is serving (a hot-swap landed between prefill and
    admit): the K/V would be stale, refuse the splice."""

    status = 409


class PrefixGone(DisaggError):
    """The decode-side radix entry that justified suffix-only transfer
    was evicted before the admit; the caller re-requests a full slab."""


def prompt_hash(tokens) -> str:
    return hashlib.sha256(
        np.asarray(tokens, np.int32).tobytes()
    ).hexdigest()[:16]


def _read_exact(read: Callable[[int], bytes], n: int) -> bytes:
    """Read exactly n bytes or raise TruncatedStream."""
    chunks = []
    got = 0
    while got < n:
        b = read(n - got)
        if not b:
            raise TruncatedStream(
                f"stream ended after {got} of {n} expected bytes"
            )
        chunks.append(b)
        got += len(b)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


def encode_slab(
    meta: Dict[str, Any],
    slab: Dict[str, np.ndarray],
    chunk_bytes: int = 1 << 20,
) -> Iterator[bytes]:
    """Yield the wire frames of one slab message. ``slab`` holds host
    arrays ``[L, 1, KV, W, Dh]``; frames come out layer-major (k then v
    per layer) in writes of at most ``chunk_bytes`` so a streaming
    sender never holds more than one chunk in flight."""
    k, v = np.ascontiguousarray(slab["k"]), np.ascontiguousarray(slab["v"])
    if k.shape != v.shape:
        raise DisaggError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    header = dict(meta)
    header["wire_version"] = WIRE_VERSION
    header["shape"] = list(k.shape)
    header["slab_dtype"] = str(k.dtype)
    hdr = json.dumps(header).encode()
    yield MAGIC + struct.pack("<II", len(hdr), zlib.crc32(hdr)) + hdr
    total_crc = 0
    for layer in range(k.shape[0]):
        for arr in (k[layer], v[layer]):
            payload = arr.tobytes()
            total_crc = zlib.crc32(payload, total_crc)
            yield struct.pack("<II", len(payload), zlib.crc32(payload))
            for off in range(0, len(payload), chunk_bytes):
                yield payload[off:off + chunk_bytes]
    yield END + struct.pack("<I", total_crc)


def decode_slab(
    read: Callable[[int], bytes],
) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Consume one slab message from ``read`` (a ``recv``-style callable
    returning up to n bytes, b"" at EOF). Returns ``(meta, slab)``.
    Raises :class:`ChecksumError` / :class:`TruncatedStream` /
    :class:`DisaggError` — always before returning partial data."""
    magic = _read_exact(read, 4)
    if magic == ERR:
        (n,) = struct.unpack("<I", _read_exact(read, 4))
        err = json.loads(_read_exact(read, n))
        cls = {"weight_version": WeightVersionMismatch}.get(
            err.get("kind"), DisaggError
        )
        raise cls(err.get("error", "prefill peer error"))
    if magic != MAGIC:
        raise DisaggError(f"bad slab magic {magic!r} (want {MAGIC!r})")
    hdr_len, hdr_crc = struct.unpack("<II", _read_exact(read, 8))
    hdr = _read_exact(read, hdr_len)
    if zlib.crc32(hdr) != hdr_crc:
        raise ChecksumError("slab header failed its checksum")
    meta = json.loads(hdr)
    if meta.get("wire_version") != WIRE_VERSION:
        raise DisaggError(
            f"unsupported slab wire version {meta.get('wire_version')!r}"
        )
    shape = tuple(meta["shape"])
    dtype = np.dtype(meta["slab_dtype"])
    if len(shape) != 5:
        raise DisaggError(f"slab shape must be [L,1,KV,W,Dh], got {shape}")
    layer_bytes = int(np.prod(shape[1:])) * dtype.itemsize
    out = {
        "k": np.empty(shape, dtype),
        "v": np.empty(shape, dtype),
    }
    total_crc = 0
    for layer in range(shape[0]):
        for name in ("k", "v"):
            n, crc = struct.unpack("<II", _read_exact(read, 8))
            if n != layer_bytes:
                raise DisaggError(
                    f"layer {layer} {name} frame is {n} bytes, "
                    f"expected {layer_bytes} for shape {shape}"
                )
            payload = _read_exact(read, n)
            if zlib.crc32(payload) != crc:
                raise ChecksumError(
                    f"layer {layer} {name} frame failed its checksum"
                )
            total_crc = zlib.crc32(payload, total_crc)
            out[name][layer] = np.frombuffer(payload, dtype).reshape(shape[1:])
    tail = _read_exact(read, 8)
    if tail[:4] != END:
        raise TruncatedStream(f"missing end frame (got {tail[:4]!r})")
    (want,) = struct.unpack("<I", tail[4:])
    if want != total_crc:
        raise ChecksumError("stream total checksum mismatch")
    return meta, out


def encode_error(err: Exception) -> bytes:
    kind = "weight_version" if isinstance(err, WeightVersionMismatch) else "error"
    body = json.dumps({"error": str(err), "kind": kind}).encode()
    return ERR + struct.pack("<I", len(body)) + body


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------


class LoopbackTransport:
    """In-process transport: a direct reference to the prefill-side
    handler, with the slab still round-tripping the full codec through a
    memory buffer (framing bugs can't hide behind shared memory)."""

    name = "loopback"

    def __init__(self, prefill_server, chunk_bytes: int = 1 << 20):
        self._server = prefill_server
        self._chunk = int(chunk_bytes)

    def prefill(
        self, request: Dict[str, Any], deadline_s: Optional[float] = None
    ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
        buf = io.BytesIO()
        try:
            meta, slab = self._server.prefill_export(request)
            for frame in encode_slab(meta, slab, self._chunk):
                buf.write(frame)
        except DisaggError as e:
            buf = io.BytesIO(encode_error(e))
        buf.seek(0)
        return decode_slab(buf.read)

    def close(self) -> None:
        pass


class TcpKVClient:
    """Decode-side client for the chunked TCP/DCN transport: one
    connection per transfer (the slab dominates any handshake cost),
    deadline-aware — the remaining request budget is the socket timeout
    for connect and every read."""

    name = "tcp"

    def __init__(self, peer: str, connect_timeout_s: float = 10.0):
        host, _, port = peer.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"peer must be host:port, got {peer!r}")
        self.host, self.port = host, int(port)
        self._connect_timeout = float(connect_timeout_s)

    def prefill(
        self, request: Dict[str, Any], deadline_s: Optional[float] = None
    ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
        timeout = (
            min(self._connect_timeout, deadline_s)
            if deadline_s is not None else self._connect_timeout
        )
        import time as _time

        expires_at = (
            _time.monotonic() + deadline_s if deadline_s is not None else None
        )
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=timeout
            )
        except OSError as e:
            raise DisaggError(
                f"prefill peer {self.host}:{self.port} unreachable: {e}"
            ) from e

        def read(n: int) -> bytes:
            # the REMAINING budget bounds every read: a peer dripping one
            # chunk per almost-deadline must still finish the whole
            # transfer inside the request budget, not reset the clock
            # per recv
            if expires_at is not None:
                remaining = expires_at - _time.monotonic()
                if remaining <= 0:
                    raise socket.timeout("kv transfer budget exhausted")
                sock.settimeout(remaining)
            return sock.recv(n)

        try:
            sock.settimeout(
                max(0.001, expires_at - _time.monotonic())
                if expires_at is not None else 60.0
            )
            sock.sendall(json.dumps(request).encode() + b"\n")
            return decode_slab(read)
        except socket.timeout as e:
            raise DisaggError(
                f"kv transfer from {self.host}:{self.port} ran past the "
                "deadline"
            ) from e
        except OSError as e:
            # mid-stream connection loss (e.g. a prefill-pool resize
            # tearing the listener down under us) must surface with the
            # same typed status every other transport failure carries
            raise DisaggError(
                f"kv transfer from {self.host}:{self.port} failed "
                f"mid-stream: {e}"
            ) from e
        finally:
            sock.close()

    def close(self) -> None:
        pass


class PrefillTransportServer:
    """Prefill-side TCP listener: accepts one JSON request line per
    connection and streams the slab back frame by frame (``chunk_bytes``
    per write — the sender-side in-flight bound). Runs accept + handler
    threads, at most ``max_inflight`` concurrently — each handler holds
    a device prefill plus a whole host-side slab, so an unbounded burst
    of decode-pool connections would collapse exactly the pool
    disaggregation is meant to isolate; over-limit connections get an
    immediate typed shed frame instead of queueing. ``close()`` unblocks
    the accept loop."""

    def __init__(
        self,
        prefill_server,
        port: int = 0,
        host: str = "0.0.0.0",
        chunk_bytes: int = 1 << 20,
        max_inflight: int = 8,
    ):
        self._server = prefill_server
        self._chunk = int(chunk_bytes)
        self._slots = threading.Semaphore(max(1, int(max_inflight)))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="kv-export", daemon=True
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # closed
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        if not self._slots.acquire(blocking=False):
            # prefill-side shed-before-work: reject NOW, from this
            # connection's own thread, rather than stacking device
            # forwards and slab buffers behind the listener
            try:
                conn.sendall(encode_error(DisaggError(
                    "prefill pool at capacity — retry"
                )))
            except OSError:
                pass
            finally:
                conn.close()
            return
        try:
            self._handle_locked(conn)
        finally:
            self._slots.release()

    def _handle_locked(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(60.0)
            line = b""
            while not line.endswith(b"\n"):
                b = conn.recv(65536)
                if not b:
                    return
                line += b
                if len(line) > 8 << 20:
                    raise DisaggError("oversized prefill request")
            request = json.loads(line)
            try:
                meta, slab = self._server.prefill_export(request)
            except DisaggError as e:
                conn.sendall(encode_error(e))
                return
            except Exception as e:  # noqa: BLE001 - bad request params
                conn.sendall(encode_error(DisaggError(str(e))))
                return
            for frame in encode_slab(meta, slab, self._chunk):
                conn.sendall(frame)
        except Exception:  # noqa: BLE001 - one bad peer must not kill accept
            logger.exception("kv export connection failed")
        finally:
            conn.close()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)


def make_transport(peer, chunk_bytes: int = 1 << 20):
    """``peer`` is either a live prefill-server object (loopback) or a
    ``"host:port"`` string (TCP)."""
    if isinstance(peer, str):
        return TcpKVClient(peer)
    return LoopbackTransport(peer, chunk_bytes=chunk_bytes)
