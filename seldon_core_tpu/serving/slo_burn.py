"""SLO error budgets + multi-window burn-rate verdicts over the serving
latency feed.

The batcher already measures TTFT / TPOT / queue-wait per completed
request (``slo_pending`` / ``tenant_slo_pending``); this module turns
those raw samples into the SRE alerting primitive an autoscaler (and,
later, the planner) can act on:

* an **objective** says "``target`` of requests must land under
  ``threshold_s``" — e.g. 99% of TTFTs under 200ms. The error budget is
  ``1 - target``.
* the **burn rate** over a window is
  ``observed_error_rate / error_budget``: 1.0 means the deployment is
  spending budget exactly as fast as the objective allows; 14.4 means a
  30-day budget gone in 50 hours.
* the **multi-window verdict** (Google SRE workbook shape) compares the
  burn over a FAST window (is it happening *now*?) and a SLOW window
  (has it been happening long enough to matter?). Both high → ``page``;
  only slow high → ``warn`` (a real but not raging burn); else ``ok``.
  The two-window AND is what keeps one latency spike from paging and a
  slow leak from hiding.

Verdicts are typed dicts (one per (tenant, slo)) — the same feed is
exported as ``seldon_engine_slo_burn_*`` series, rendered by /fleet,
and consumed by the reconciler's scale signals: a ``page`` verdict
vetoes scale-down and counts toward scale-up pressure.

Thread model: ``observe`` runs on whatever thread drains the batcher's
SLO rings (the /metrics exporter); ``verdicts``/``summary`` on metrics
and fleet threads. One lock, held for ring arithmetic only.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["SloObjective", "SloBurnEngine", "SEVERITIES"]

# severity ladder, worst last; the reconciler compares by index
SEVERITIES = ("ok", "warn", "page")


class SloObjective:
    """One latency objective: ``target`` fraction of requests under
    ``threshold_s`` for the named SLO (``ttft``/``tpot``/``queue_wait``)."""

    __slots__ = ("slo", "threshold_s", "target")

    def __init__(self, slo: str, threshold_s: float, target: float = 0.99):
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"slo target must be in (0, 1), got {target!r} "
                "(1.0 leaves a zero error budget — burn would be "
                "infinite on the first slow request)"
            )
        if threshold_s <= 0.0:
            raise ValueError(f"slo threshold must be > 0s, got {threshold_s!r}")
        self.slo = str(slo)
        self.threshold_s = float(threshold_s)
        self.target = float(target)

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def spec(self) -> Dict[str, Any]:
        return {"slo": self.slo, "threshold_s": self.threshold_s,
                "target": self.target}

    @classmethod
    def parse(cls, spec: Dict[str, Any]) -> "List[SloObjective]":
        """``{"ttft": {"threshold_ms": 200, "target": 0.99}, ...}`` →
        objectives (``threshold_s`` also accepted; ms wins if both)."""
        out = []
        for slo, cfg in spec.items():
            if "threshold_ms" in cfg:
                thr = float(cfg["threshold_ms"]) * 1e-3
            else:
                thr = float(cfg["threshold_s"])
            out.append(cls(slo, thr, float(cfg.get("target", 0.99))))
        return out


class SloBurnEngine:
    """Per-(tenant, slo) sample rings + fast/slow burn-rate verdicts.

    ``fast_window_s``/``slow_window_s`` default to 60s/3600s — scaled
    down from the workbook's 5m/1h to serving-loop reality (a generate
    deployment's traffic shifts in seconds, not hours). ``page_burn``/
    ``warn_burn`` are the burn-rate thresholds for the two rungs.
    """

    def __init__(
        self,
        objectives: List[SloObjective],
        fast_window_s: float = 60.0,
        slow_window_s: float = 3600.0,
        page_burn: float = 14.4,
        warn_burn: float = 3.0,
        max_samples: int = 8192,
    ):
        self.objectives = list(objectives)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.page_burn = float(page_burn)
        self.warn_burn = float(warn_burn)
        self._max_samples = int(max_samples)
        self._lock = threading.Lock()
        self._by_slo = {o.slo: o for o in self.objectives}
        # (tenant, slo) -> list of (mono_t, breached) — pruned to the
        # slow window on every observe/verdict pass, capped at
        # max_samples so a hot tenant cannot grow host memory unbounded
        self._rings: Dict[Tuple[str, str], List[Tuple[float, bool]]] = {}
        # cumulative verdict evaluations per (tenant, slo, severity) —
        # exported as a counter through CounterDeltas, so it must only
        # ever grow
        self._verdict_counts: Dict[Tuple[str, str, str], int] = {}
        self.stats = {"samples": 0, "breaches": 0}

    # -- ingest -------------------------------------------------------------

    def observe(self, slo: str, value_s: Optional[float],
                tenant: str = "") -> None:
        """Record one request's latency sample against its objective.
        Samples for SLOs without an objective are dropped (no ring grows
        for series nobody budgets)."""
        obj = self._by_slo.get(slo)
        if obj is None or value_s is None:
            return
        breached = value_s > obj.threshold_s
        now = time.monotonic()
        key = (tenant or "", slo)
        with self._lock:
            ring = self._rings.setdefault(key, [])
            ring.append((now, breached))
            self.stats["samples"] += 1
            if breached:
                self.stats["breaches"] += 1
            if len(ring) > self._max_samples:
                del ring[: len(ring) - self._max_samples]

    # -- verdicts -----------------------------------------------------------

    def _burn(self, ring: List[Tuple[float, bool]], now: float,
              window_s: float, budget: float) -> Tuple[float, int]:
        """(burn_rate, n_samples) over the trailing window. An empty
        window burns nothing — absence of traffic is not an outage (the
        reconciler has its own idle-scale path)."""
        horizon = now - window_s
        n = bad = 0
        for t, breached in ring:
            if t >= horizon:
                n += 1
                if breached:
                    bad += 1
        if n == 0:
            return 0.0, 0
        return (bad / n) / budget, n

    def verdicts(self) -> List[Dict[str, Any]]:
        """One typed verdict per (tenant, slo) ring with data in the
        slow window — the feed /fleet ships and the reconciler's scale
        signals consume."""
        now = time.monotonic()
        out: List[Dict[str, Any]] = []
        with self._lock:
            horizon = now - self.slow_window_s
            for key in list(self._rings):
                ring = [r for r in self._rings[key] if r[0] >= horizon]
                if not ring:
                    del self._rings[key]  # tenant gone quiet: drop the ring
                    continue
                self._rings[key] = ring
                tenant, slo = key
                obj = self._by_slo[slo]
                fast, n_fast = self._burn(
                    ring, now, self.fast_window_s, obj.budget)
                slow, n_slow = self._burn(
                    ring, now, self.slow_window_s, obj.budget)
                if fast >= self.page_burn and slow >= self.page_burn:
                    severity = "page"
                elif slow >= self.warn_burn:
                    severity = "warn"
                else:
                    severity = "ok"
                # budget left in the slow window, as a fraction: 1.0 =
                # untouched, 0.0 = spent (burn 1.0 across the whole
                # window spends it exactly)
                remaining = max(0.0, 1.0 - slow)
                ck = (tenant, slo, severity)
                self._verdict_counts[ck] = self._verdict_counts.get(ck, 0) + 1
                out.append({
                    "tenant": tenant,
                    "slo": slo,
                    "threshold_s": obj.threshold_s,
                    "target": obj.target,
                    "fast_burn": round(fast, 4),
                    "slow_burn": round(slow, 4),
                    "fast_samples": n_fast,
                    "slow_samples": n_slow,
                    "budget_remaining": round(remaining, 4),
                    "severity": severity,
                })
        out.sort(key=lambda v: (v["tenant"], v["slo"]))
        return out

    def verdict_counts(self) -> Dict[Tuple[str, str, str], int]:
        """Cumulative verdict evaluations per (tenant, slo, severity)
        — counter totals for the CounterDeltas exporter."""
        with self._lock:
            return dict(self._verdict_counts)

    def worst(self) -> str:
        """Worst severity across every live ring (``ok`` when idle) —
        the one-word signal the reconciler's scale loop branches on."""
        worst = 0
        for v in self.verdicts():
            worst = max(worst, SEVERITIES.index(v["severity"]))
        return SEVERITIES[worst]

    def summary(self) -> Dict[str, Any]:
        """Rollup for /fleet and flight dumps."""
        return {
            "objectives": [o.spec() for o in self.objectives],
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "page_burn": self.page_burn,
            "warn_burn": self.warn_burn,
            "samples": self.stats["samples"],
            "breaches": self.stats["breaches"],
            "verdicts": self.verdicts(),
        }
