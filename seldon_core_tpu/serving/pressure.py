"""HBM-pressure management: the unified ledger + watermark controller.

PR 7 made the serving plane survive crashes; this module makes it
survive *success*. A burst of long generations grows the live decode
footprint (deepening attention buckets), staging slabs pile up behind
chunked admissions, the radix prefix cache sits on its byte budget, and
a pending weight swap double-buffers a whole extra param set — and
before this module the only levers were a shed at admission or a wedge.
DeepServe (PAPERS.md, arxiv 2501.14417) treats preemption-with-recompute
as table stakes; InferLine (arxiv 1812.01776) argues SLO-holding
pipelines need explicit pressure policies, not fixed pools.

The :class:`PressureController` tracks one **unified HBM ledger** over
the components the continuous batcher actually grows at runtime:

* ``decode`` — the *live* decode-cache footprint: each active lane
  priced at its current attention-read bucket times the per-token K/V
  byte cost (plus the draft cache's, under speculation). The fixed
  allocation never changes, but the bytes every burst actually touches
  — and the bytes a reclaim can win back — follow the live prefix, so
  the ledger prices lanes the way the reclaim ladder can free them.
* ``staging`` — chunked-prefill staging slabs (PR 3) held by pending
  long-prompt admissions.
* ``prefix`` — the radix prefix cache's published slab bytes (PR 1).
* ``swap`` — a staged hot-swap's double-buffered param bytes (PR 5).

Two watermarks with hysteresis: crossing ``high`` *latches* pressure
(``active = True``) and the batcher starts the **reclaim ladder**
(evict prefixes → cancel speculation → preempt lanes → shed
admissions — see ``ContinuousBatcher._pressure_poll``); dropping back
to ``low`` clears it and admissions resume. The gap between the
watermarks is the thrash guard: a resumed lane must fit inside it or it
would re-trip pressure on admission.

``budget_bytes == 0`` disables the whole subsystem — the scheduler hot
loop then never consults the controller, byte-identical to a
pre-pressure build. The chaos harness shrinks the budget mid-run
(``SELDON_FAULTS`` ``pressure`` section) to drive the ladder under
test.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

__all__ = ["PressureController", "PressureRefused"]


class PressureRefused(RuntimeError):
    """A decode pool refused a remote admit because its HBM ledger is
    over the high watermark. Typed and carrying ``retry_after_s`` so the
    refusal pushes back to the prefill/caller side exactly like PR 2's
    shed contract: the engine maps it to **503 + Retry-After** (gRPC
    ``UNAVAILABLE``) and clients back off instead of re-shipping slabs
    at a pool that cannot splice them."""

    status = 503

    def __init__(self, info: str, retry_after_s: float = 1.0):
        super().__init__(info)
        self.info = info
        self.retry_after_s = float(retry_after_s)


class PressureController:
    """Unified HBM ledger with high/low watermark hysteresis.

    Host-side bookkeeping only: ``update()`` is a handful of integer
    adds per scheduler poll (and is skipped entirely at ``budget == 0``).
    All fields are plain ints/bools written by the scheduler thread;
    concurrent readers (metrics export, ``_shed_check`` on submitting
    threads) see torn-but-harmless values — a one-poll-stale ``active``
    flag only shifts *when* a shed lands, never correctness.
    """

    def __init__(
        self,
        budget_bytes: int = 0,
        high: float = 0.90,
        low: float = 0.75,
    ):
        high = float(high)
        low = float(low)
        if not (0.0 < high <= 1.0):
            raise ValueError(f"pressure high watermark {high} not in (0, 1]")
        if not (0.0 < low <= high):
            raise ValueError(
                f"pressure low watermark {low} must be in (0, high={high}]"
            )
        self.budget_bytes = max(0, int(budget_bytes))
        # the boot-time budget: the chaos harness restores to this after
        # a shrink window (faults.pressure_hook's -1 sentinel)
        self.base_budget_bytes = self.budget_bytes
        self.high_frac = high
        self.low_frac = low
        self.used = 0
        self.components: Dict[str, int] = {}
        # host KV tier occupancy (serving/kvtier.py): HOST RAM, not HBM
        # — tracked for the summary/flight surface but deliberately
        # OUTSIDE ``used`` and the watermark math (counting it would
        # double-bill a demotion: the ledger's whole point is that
        # demoted bytes stopped costing HBM)
        self.host_bytes = 0
        self.active = False
        self.stats = {
            "updates": 0,
            "activations": 0,
            "budget_changes": 0,
        }

    # -- watermarks ---------------------------------------------------------

    @property
    def high_bytes(self) -> int:
        return int(self.budget_bytes * self.high_frac)

    @property
    def low_bytes(self) -> int:
        return int(self.budget_bytes * self.low_frac)

    def set_budget(self, budget_bytes: int) -> None:
        """Re-budget the ledger live (the chaos harness's mid-run shrink;
        also an operator lever when a co-tenant — e.g. the future weight
        pager — needs HBM back). The next ``update()`` re-evaluates the
        watermarks against the new budget."""
        self.budget_bytes = max(0, int(budget_bytes))
        self.stats["budget_changes"] += 1

    def restore_budget(self) -> None:
        self.set_budget(self.base_budget_bytes)

    # -- accounting ---------------------------------------------------------

    def update(self, components: Dict[str, int]) -> bool:
        """Refresh the ledger from a fresh component breakdown and
        re-evaluate the watermark latch. Returns the (possibly new)
        ``active`` state."""
        self.components = components
        self.used = sum(components.values())
        self.stats["updates"] += 1
        if self.budget_bytes <= 0:
            self.active = False
        elif self.used >= self.high_bytes:
            if not self.active:
                self.stats["activations"] += 1
            self.active = True
        elif self.used <= self.low_bytes:
            self.active = False
        return self.active

    def overshoot_bytes(self) -> int:
        """Bytes above the LOW watermark — what the reclaim ladder must
        win back before pressure clears (0 when under it)."""
        return max(0, self.used - self.low_bytes)

    def retry_after_s(self) -> float:
        """Backoff hint for pressure sheds/refusals: scale with how far
        over budget the ledger is (bounded — a hint, not a promise)."""
        if self.budget_bytes <= 0 or not self.active:
            return 1.0
        over = self.used / max(1, self.high_bytes)
        return min(10.0, max(1.0, over))

    def summary(self) -> Dict[str, Any]:
        """JSON-shaped snapshot for flight dumps and diagnostics."""
        return {
            "budget_bytes": self.budget_bytes,
            "used_bytes": self.used,
            "high_bytes": self.high_bytes,
            "low_bytes": self.low_bytes,
            "active": self.active,
            "components": dict(self.components),
            # host KV tier bytes ride OUTSIDE ``components``: they are
            # host RAM, not HBM — ``used_bytes`` must never count them
            "host_tier_bytes": self.host_bytes,
            "activations": self.stats["activations"],
            "budget_changes": self.stats["budget_changes"],
        }
