"""Tiered KV memory: the pinned host-RAM spill tier between HBM and the wire.

PR 9's reclaim ladder *destroys* state under HBM pressure: prefix slabs
are evicted outright and preempted lanes drop their K/V, paying a full
prompt recompute + teacher-forced replay on resume. DeepServe (PAPERS.md,
arxiv 2501.14417) argues host/remote checkpoint tiering is what lets
serverless-scale serving survive exactly this; InferLine-style
provisioning (arxiv 1812.01776) prices reclaim as a *copy*, not a
recompute. This module is that tier: a host-RAM store with its own byte
budget, LRU, and lock discipline, holding **SKV1-serialized** KV slabs
(PR 6's CRC-framed wire codec — every entry is already a valid wire
message, so corruption refuses typed and a peer can stream an entry
without re-framing) keyed by ``(weight_version, token-prefix)``.

Two entry kinds share the budget:

* **prefix entries** — prompt-K/V slabs demoted from the device radix
  cache by the reclaim ladder (``ContinuousBatcher._reclaim`` rung 1
  becomes *demote, not evict*) or published by a prefill-role export
  (the slab is already host-side there — zero extra device cost). They
  promote back (``device_put`` + splice) on a later prefix match,
  locally or from a *peer's* tier over the PR 6/7 KV transport: a
  post-pressure warm hit costs a PCIe copy instead of a re-prefill.
* **checkpoint entries** — a preempted decode lane's exact cache
  columns (ladder rung 3), stored when budget allows so
  ``_admit_resume`` does a copy-back insert instead of prompt-recompute
  + replay. One-shot: taken on resume. Replay stays the fallback when
  the tier evicted (or refused) the entry.

Internal structure: prefix entries live in a :class:`RadixPrefixIndex`
whose "slabs" are the SKV1 payload *bytes* (the index is deliberately
device-agnostic, so insert/match/split/LRU reuse PR 1's machinery and
the version keying reuses PR 5's ``set_version`` purge); checkpoint
entries live in an insertion-ordered dict. Eviction policy, cheapest
loss first: LRU prefix entries (pure cache) go before checkpoint
entries (paid-for work), and a checkpoint never evicts a *newer*
checkpoint. A single entry larger than half the budget is refused — a
tier that can hold at most one such slab would thrash, not cache.

Thread discipline: every public method takes the tier lock. The
scheduler thread demotes/promotes at poll boundaries; disagg transport
handler threads answer peer prefix lookups concurrently. All payloads
are host bytes — no method ever touches a device.

``budget_bytes == 0`` disables the subsystem (the batcher then never
constructs one) — the off-by-default convention every serving subsystem
here follows.
"""

from __future__ import annotations

import io
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .disagg import ChecksumError, DisaggError, encode_slab, decode_slab
from .prefix_cache import RadixPrefixIndex, version_retains

__all__ = ["HostKVTier", "TierEntryCorrupt"]

# the inner radix index must never evict on its own — the TIER owns the
# byte budget (prefix + checkpoint entries share it)
_UNBOUNDED = 1 << 62


class TierEntryCorrupt(ChecksumError):
    """A stored tier entry failed its SKV1 CRC on read. Raised BEFORE
    any lane state exists (the codec contract); the corrupt entry is
    already dropped from the tier when this surfaces, so callers treat
    it as a miss (prefix promote, checkpoint copy-back falls back to
    replay) and a peer lookup answers a typed error frame."""


class _CkptEntry:
    __slots__ = ("payload", "nbytes", "version")

    def __init__(self, payload: bytes, version: Any):
        self.payload = payload
        self.nbytes = len(payload)
        self.version = version


class HostKVTier:
    """Host-RAM KV store: SKV1-serialized slabs under one byte budget.

    ``min_tokens`` is the demote threshold — prefixes shorter than it
    are not worth a tier slot (mirrors ``prefix_cache_min_tokens``).
    ``stats`` counters are written under the tier lock; readers see
    torn-but-harmless ints (same contract as the batcher's stats).
    """

    def __init__(self, budget_bytes: int, min_tokens: int = 16,
                 version: Any = 0):
        self.budget_bytes = max(0, int(budget_bytes))
        self.min_tokens = max(1, int(min_tokens))
        self.version: Any = version
        self._lock = threading.Lock()
        # prefix entries: radix tree whose "slab" payload is
        # ("tier", entry_tokens, skv1_bytes) — the tokens ride along so
        # a corrupt entry can be removed without decoding its header
        self._index = RadixPrefixIndex(_UNBOUNDED)
        self._index.version = version
        # checkpoint entries, insertion-ordered (oldest evicts first)
        self._ckpts: Dict[Any, _CkptEntry] = {}
        self.stats = {
            "demotions": 0, "hits": 0, "misses": 0, "evictions": 0,
            "refused": 0, "released": 0,
        }

    # -- internals ----------------------------------------------------------

    def _ckpt_bytes(self) -> int:
        # callers hold self._lock (iterating _ckpts unlocked would race
        # cross-thread put/take/drop mutations)
        return sum(e.nbytes for e in self._ckpts.values())

    def _total_bytes_locked(self) -> int:
        return self._index.total_bytes + self._ckpt_bytes()

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._total_bytes_locked()

    @property
    def entry_count(self) -> int:
        with self._lock:
            return self._index.slab_count + len(self._ckpts)

    @staticmethod
    def _encode(meta: Dict[str, Any], slab: Dict[str, np.ndarray]) -> bytes:
        return b"".join(encode_slab(meta, slab))

    @staticmethod
    def _decode(payload: bytes) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
        return decode_slab(io.BytesIO(payload).read)

    def _fits(self, nbytes: int) -> bool:
        """A single entry above half the budget is refused: a tier that
        can hold at most one such slab thrashes instead of caching."""
        return 0 < nbytes <= self.budget_bytes // 2

    def _evict_prefixes_locked(self, target_bytes: int) -> None:
        evicted = self._index.evict_to(max(0, target_bytes))
        self.stats["evictions"] += evicted

    # -- prefix entries -----------------------------------------------------

    def put_prefix(self, tokens, slab: Dict[str, np.ndarray],
                   version: Any, extra_meta: Optional[Dict] = None) -> bool:
        """Demote one prompt-K/V slab (host ``{"k","v"}`` arrays in the
        stacked cache_one layout) into the tier under its token path.
        Returns False when refused (too short, too large, or a stale
        weight version). May LRU-evict older prefix entries to fit —
        never checkpoints (cache must not displace paid-for work)."""
        tokens = tuple(int(t) for t in tokens)
        if len(tokens) < self.min_tokens:
            self.stats["refused"] += 1
            return False
        with self._lock:
            # pre-checks BEFORE the O(slab-bytes) encode: a stale
            # version, or a stored entry already covering this whole
            # path (it serves any match this one would), makes the
            # encode pure waste — repeat-prefix export traffic must not
            # pay a host memcpy+CRC per request for a no-op
            if version != self.version:
                self.stats["refused"] += 1
                return False
            if self._index.covered_len(tokens) >= len(tokens):
                return False
        meta = {
            "kind": "tier_prefix",
            "tokens": list(tokens),
            "weight_version": version,
            **(extra_meta or {}),
        }
        payload = self._encode(meta, slab)
        with self._lock:
            # re-validate: the encode ran unlocked
            if (
                version != self.version
                or self._index.covered_len(tokens) >= len(tokens)
            ):
                return False
            # prefix entries may never displace checkpoints, so the
            # space prefixes can ever claim is budget - ckpt_bytes: an
            # entry larger than that would only evict ITSELF after
            # insertion — refuse up front instead of counting a
            # demotion for a slab that is already gone
            avail = self.budget_bytes - self._ckpt_bytes()
            if not self._fits(len(payload)) or len(payload) > avail:
                self.stats["refused"] += 1
                return False
            self._index.insert(
                tokens, ("tier", tokens, payload), len(payload)
            )
            # the new entry carries the freshest LRU stamp, so evicting
            # down to `avail` always victimizes older entries first and
            # can never drop the entry just stored
            self._evict_prefixes_locked(avail)
            self.stats["demotions"] += 1
            return True

    def prefix_covered_len(self, tokens, version: Any) -> int:
        """Longest stored prefix of ``tokens`` under ``version`` WITHOUT
        decoding, LRU-touching, or paying anything O(slab): the cheap
        probe a demote path uses to skip the device pull for a slab the
        tier would refuse anyway (already covered)."""
        with self._lock:
            if version != self.version:
                return 0
            return self._index.covered_len([int(t) for t in tokens])

    def match_prefix(
        self, tokens, version: Any
    ) -> Optional[Tuple[int, Dict[str, Any], Dict[str, np.ndarray]]]:
        """Longest stored prefix of ``tokens`` under ``version``:
        ``(depth, meta, slab)`` with host arrays decoded (CRC-verified),
        or None. The returned slab covers the ENTRY's full token path
        (``meta["tokens"]``) — valid K/V for every prefix of it, so the
        caller re-inserts it device-side under the entry path and lets
        the ordinary radix match serve ``depth``. Raises
        :class:`TierEntryCorrupt` (typed, entry already dropped) when
        the stored bytes fail their checksum."""
        tokens = [int(t) for t in tokens]
        with self._lock:
            if version != self.version:
                self.stats["misses"] += 1
                return None
            depth, stored = self._index.match(tokens)
            if stored is None or depth < self.min_tokens:
                self.stats["misses"] += 1
                return None
            _tag, entry_tokens, payload = stored
        # decode OUTSIDE the lock (mirror of put_prefix's unlocked
        # encode): the payload bytes are immutable once stored, and an
        # O(slab) memcpy+CRC under the global tier lock would block the
        # scheduler's per-poll occupancy reads behind every peer lookup
        try:
            meta, slab = self._decode(payload)
        except DisaggError as e:
            # drop the corrupt entry NOW so it can never re-hit, then
            # refuse typed — before any lane state, per the SKV1 codec
            # contract
            with self._lock:
                self._index.remove(entry_tokens)
                self.stats["evictions"] += 1
            raise TierEntryCorrupt(
                f"tier prefix entry ({len(entry_tokens)} tokens) "
                f"failed its checksum: {e}"
            ) from e
        with self._lock:
            self.stats["hits"] += 1
        return depth, meta, slab

    # -- checkpoint entries -------------------------------------------------

    def put_ckpt(self, key: Any, meta: Dict[str, Any],
                 slab: Dict[str, np.ndarray], version: Any) -> bool:
        """Checkpoint a preempted lane's cache columns under ``key``
        ("when budget allows": LRU prefix entries and OLDER checkpoints
        may be evicted to fit, a larger-than-half-budget slab is
        refused). One-shot — taken by :meth:`take_ckpt` on resume."""
        payload = self._encode(
            {"kind": "tier_ckpt", "weight_version": version, **meta}, slab
        )
        n = len(payload)
        with self._lock:
            if version != self.version or not self._fits(n):
                self.stats["refused"] += 1
                return False
            # cheapest loss first: prefix entries (pure cache), then
            # the oldest checkpoints — never a newer one
            self._evict_prefixes_locked(
                max(0, self.budget_bytes - self._ckpt_bytes() - n)
            )
            while (
                self._ckpts
                and self._total_bytes_locked() + n > self.budget_bytes
            ):
                oldest = next(iter(self._ckpts))
                self.stats["evictions"] += 1
                del self._ckpts[oldest]
            if self._total_bytes_locked() + n > self.budget_bytes:
                self.stats["refused"] += 1
                return False
            self._ckpts[key] = _CkptEntry(payload, version)
            self.stats["demotions"] += 1
            return True

    def take_ckpt(
        self, key: Any, version: Any
    ) -> Optional[Tuple[Dict[str, Any], Dict[str, np.ndarray]]]:
        """Pop and decode the checkpoint stored under ``key`` —
        ``(meta, slab)``, or None when evicted/never stored/stale
        version (the caller falls back to recompute + replay). Raises
        :class:`TierEntryCorrupt` on a CRC failure (entry already
        popped — replay fallback again)."""
        with self._lock:
            ent = self._ckpts.get(key)
            if ent is None or ent.version != version:
                # version-gated WITHOUT popping: a stale-version lookup
                # must not destroy an entry another tenant's page-back
                # would make valid again (version_retains kept it alive)
                self.stats["misses"] += 1
                return None
            del self._ckpts[key]
        # decode unlocked (the entry is already popped — no other
        # thread can observe or mutate it)
        try:
            meta, slab = self._decode(ent.payload)
        except DisaggError as e:
            with self._lock:
                self.stats["evictions"] += 1
            raise TierEntryCorrupt(
                f"tier checkpoint {key!r} failed its checksum: {e}"
            ) from e
        with self._lock:
            self.stats["hits"] += 1
        return meta, slab

    def drop_ckpt(self, key: Any) -> bool:
        """Release a checkpoint without decoding it — the owner request
        was cancelled, failed, or migrated away, so the entry is dead
        weight that must not keep occupying budget prefix demotions can
        never reclaim (checkpoints outrank prefixes in the eviction
        order precisely because they are normally still owed a
        resume)."""
        with self._lock:
            if self._ckpts.pop(key, None) is None:
                return False
            self.stats["released"] += 1
            return True

    # -- versioning + introspection -----------------------------------------

    def set_version(self, version: Any) -> int:
        """Key the tier to a new weight version, purging every stored
        entry the switch invalidates (K/V computed under replaced
        weights — exactly the radix cache's hot-swap contract).
        Namespace-aware per :func:`~.prefix_cache.version_retains`: a
        tenant page-in (serving/weightpager.py) purges only that
        tenant's stale entries and legacy un-namespaced ones — another
        tenant's prefix slabs and lane checkpoints survive, unreachable
        (every lookup gates on ``version == self.version``) until their
        tenant pages back in. Returns entries purged."""
        with self._lock:
            if version == self.version:
                return 0
            self.version = version
            purged = self._index.set_version(version)
            dead = [
                k for k, e in self._ckpts.items()
                if not version_retains(e.version, version)
            ]
            for k in dead:
                del self._ckpts[k]
            purged += len(dead)
            self.stats["evictions"] += purged
            return purged

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "used_bytes": self._total_bytes_locked(),
                "prefix_entries": self._index.slab_count,
                "ckpt_entries": len(self._ckpts),
                "version": self.version,
                **{k: v for k, v in self.stats.items()},
            }
