"""Scheduler flight recorder: a bounded ring of per-poll decision records.

The continuous batcher (serving/continuous.py) makes a scheduling
decision every poll — which requests admit, how live lanes partition
into depth-grouped sub-bursts, whether the cost model merged groups,
which long prompts advanced a prefill chunk, what got shed — and none of
it used to survive the poll. This recorder keeps the last ``capacity``
decisions as plain dicts in a ``collections.deque`` ring so a
tail-latency regression can be attributed after the fact (queue wait vs
prefill interleave vs group re-packing vs eviction) without re-running
traffic under a profiler.

Cost model: recording must be cheap enough to leave ON in production.
One small dict is built per *poll* (device-burst cadence, milliseconds),
never per token; ``deque.append`` with ``maxlen`` drops the oldest entry
under pressure without locking (the scheduler thread writes poll records;
shed records arrive concurrently from submitting threads, and both
``deque.append`` and the ``itertools.count`` sequence stamp are atomic
under the GIL); readers snapshot with ``list(...)`` and never block the
scheduler. ``enabled = False`` short-circuits to a single attribute
check on the hot path.

Consumed by the engine's ``/flightrecorder`` route (graph/service.py)
and ``tools/flight_report.py``, which turns a dump into a human-readable
diagnosis.
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Any, Dict, List, Optional

from ..tracing import wall_us


class FlightRecorder:
    """Bounded, drop-oldest ring buffer of scheduler decision records."""

    def __init__(self, capacity: int = 512, enabled: bool = True):
        self.capacity = int(capacity)
        self.enabled = bool(enabled) and self.capacity > 0
        self._ring: deque = deque(maxlen=max(1, self.capacity))
        # monotonically growing record count: next(self._seq) is atomic
        # under the GIL, so concurrent writers (scheduler polls + shed
        # events off submitting threads) never duplicate a seq
        self._seq = itertools.count()

    def record(self, entry: Dict[str, Any]) -> None:
        """Append one record. The caller owns ``entry`` (it is stored, not
        copied); ``seq``/``t_us`` are stamped here so every record is
        orderable and wall-clock attributable."""
        if not self.enabled:
            return
        entry["seq"] = next(self._seq)
        # monotonic-anchored wall stamp: flight_report diffs t_us between
        # records to attribute poll gaps — an NTP step under a raw
        # time.time() would turn those intervals into lies
        entry.setdefault("t_us", wall_us())
        self._ring.append(entry)

    def snapshot(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Most-recent-last copy of the ring (the scheduler keeps writing
        while we read; list() of a deque is safe under the GIL)."""
        entries = list(self._ring)
        if limit is not None and limit >= 0:
            entries = entries[-limit:] if limit else []
        return entries

    def dump(self, limit: Optional[int] = None) -> Dict[str, Any]:
        """JSON-shaped export for the ``/flightrecorder`` route."""
        entries = self.snapshot(limit)
        # total ever recorded = the newest entry's seq + 1 (the counter
        # itself is not readable without consuming it)
        try:
            recorded = self._ring[-1]["seq"] + 1
        except (IndexError, KeyError):
            recorded = 0
        return {
            "capacity": self.capacity,
            "enabled": self.enabled,
            "recorded_total": recorded,
            "dropped": max(0, recorded - len(self._ring)),
            "entries": entries,
        }

    def clear(self) -> None:
        self._ring.clear()
