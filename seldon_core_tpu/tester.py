"""Contract-driven testers + serving-test generator.

Parity with the reference's three test tools:
  * ``seldon-core-microservice-tester`` — fuzz one wrapped component from a
    contract JSON (reference: python/seldon_core/microservice_tester.py:83-264)
  * ``seldon-core-api-tester`` — same contracts against the external API
    (reference: python/seldon_core/api_tester.py:104)
  * ``seldon-core-tester`` test-file generator from a dataset
    (reference: python/seldon_core/serving_test_gen.py:61)

Contract format (unchanged from the reference so existing contracts work):
``{"features": [{name, ftype: continuous|categorical, dtype, range|values,
shape?, repeat?}], "targets": [...]}``.
"""

from __future__ import annotations

import argparse
import json
import logging
from typing import Any, Dict, List, Optional

import numpy as np

from .client import SeldonClient, SeldonClientResponse

logger = logging.getLogger(__name__)


class ContractError(ValueError):
    pass


def unfold_contract(contract: Dict[str, Any]) -> Dict[str, Any]:
    """Expand `repeat` shorthand into concrete feature/target entries
    (reference: microservice_tester.py:112-140)."""
    out: Dict[str, Any] = {"features": [], "targets": []}
    for section in ("features", "targets"):
        for feature in contract.get(section, []):
            repeat = feature.get("repeat")
            if repeat is None:
                out[section].append(dict(feature))
            else:
                for i in range(int(repeat)):
                    f = dict(feature)
                    del f["repeat"]
                    f["name"] = f"{feature['name']}{i + 1}"
                    out[section].append(f)
    return out


def _gen_continuous(rng: np.random.Generator, f_range, shape) -> np.ndarray:
    lo = -1e3 if f_range[0] in ("inf", "-inf") else float(f_range[0])
    hi = 1e3 if f_range[1] == "inf" else float(f_range[1])
    return rng.uniform(lo, hi, size=shape)


def generate_batch(contract: Dict[str, Any], n: int, field: str = "features",
                   seed: Optional[int] = None) -> np.ndarray:
    """Random batch matching the contract's feature defs
    (reference: microservice_tester.py:83-110)."""
    rng = np.random.default_rng(seed)
    cols: List[np.ndarray] = []
    dtypes = set()
    for fdef in contract[field]:
        ftype = fdef.get("ftype")
        if ftype == "continuous":
            shape = [n] + list(fdef.get("shape", [1]))
            batch = np.around(_gen_continuous(rng, fdef.get("range", ["inf", "inf"]), shape), 3)
            if fdef.get("dtype") == "INT":
                batch = batch.astype(int)
            dtypes.add("num")
        elif ftype == "categorical":
            batch = rng.choice(np.asarray(fdef["values"], dtype=object), size=(n, 1))
            dtypes.add("cat")
        else:
            raise ContractError(f"unknown feature type {ftype!r} for {fdef.get('name')}")
        cols.append(batch.reshape(n, -1))
    out = np.concatenate(cols, axis=1)
    return out if len(dtypes) == 1 else out.astype(object)


def feature_names(contract: Dict[str, Any], field: str = "features") -> List[str]:
    return [f["name"] for f in contract[field]]


def validate_response(contract: Dict[str, Any], response: Dict[str, Any]) -> List[str]:
    """Check a response's data block against the contract's targets;
    returns a list of violations (empty = pass)."""
    problems: List[str] = []
    data = response.get("data")
    if data is None:
        if "strData" in response or "jsonData" in response or "binData" in response:
            return problems
        return ["response has no data block"]
    from .payload import json_data_to_array

    try:
        arr = np.asarray(json_data_to_array(data))
    except Exception as e:  # noqa: BLE001
        return [f"undecodable response data: {e}"]
    targets = contract.get("targets", [])
    if targets and arr.dtype != object:
        widths = [int(np.prod(t.get("shape", [1]))) for t in targets]
        width = sum(widths)
        if arr.ndim == 2 and arr.shape[1] != width:
            problems.append(f"response width {arr.shape[1]} != contract targets width {width}")
        elif arr.ndim == 2:
            col = 0
            for t, w in zip(targets, widths):
                block = arr[:, col:col + w]
                col += w
                if t.get("ftype") == "continuous" and "range" in t:
                    lo, hi = t["range"]
                    lo = -np.inf if lo in ("inf", "-inf") else float(lo)
                    hi = np.inf if hi == "inf" else float(hi)
                    if block.size and (block.min() < lo or block.max() > hi):
                        problems.append(f"target {t['name']}: values outside [{lo}, {hi}]")
    return problems


def run_contract_test(
    client: SeldonClient,
    contract: Dict[str, Any],
    n_requests: int = 1,
    batch_size: int = 1,
    endpoint: str = "predict",
    external: bool = False,
    seed: Optional[int] = None,
    validate: bool = True,
) -> Dict[str, Any]:
    """Fire contract-generated traffic; returns a summary dict."""
    contract = unfold_contract(contract)
    names = feature_names(contract)
    ok = fail = 0
    violations: List[str] = []
    for i in range(n_requests):
        batch = generate_batch(contract, batch_size, seed=None if seed is None else seed + i)
        if endpoint == "send-feedback":
            request = {"data": {"names": names, "ndarray": batch.tolist()}}
            truth = generate_batch(contract, batch_size, field="targets",
                                   seed=None if seed is None else seed + i)
            response = {"data": {"ndarray": truth.tolist()}}
            if external:
                resp = client.feedback(request, response, reward=1.0)
            else:
                resp = client.microservice_feedback(request, response, reward=1.0)
        elif external:
            resp = client.predict(batch, names=names)
        else:
            resp = client.microservice(batch, method=endpoint, names=names)
        if resp.success:
            probs = validate_response(contract, resp.response or {}) if (
                validate and endpoint in ("predict", "transform-input", "transform-output")
            ) else []
            if probs:
                violations.extend(probs)
                fail += 1
            else:
                ok += 1
        else:
            fail += 1
            violations.append(resp.msg)
    return {"requests": n_requests, "ok": ok, "failed": fail, "violations": violations[:20]}


# -- serving-test generator -------------------------------------------------


def generate_contract_from_data(
    X: np.ndarray,
    names: Optional[List[str]] = None,
    targets: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """Infer a contract from a sample batch (reference:
    serving_test_gen.py:61 create_seldon_api_testing_file, column ranges
    from the dataframe)."""
    X = np.asarray(X)
    if X.ndim == 1:
        X = X.reshape(-1, 1)
    features = []
    for j in range(X.shape[1]):
        name = names[j] if names and j < len(names) else f"f{j}"
        col = X[:, j]
        if col.dtype.kind in "OUS":
            features.append(
                {"name": name, "ftype": "categorical",
                 "dtype": "STRING", "values": sorted({str(v) for v in col})}
            )
        else:
            col = col.astype(float)
            features.append(
                {"name": name, "ftype": "continuous",
                 "dtype": "INT" if np.allclose(col, col.astype(int)) else "FLOAT",
                 "range": [float(col.min()), float(col.max())]}
            )
    return {"features": features, "targets": targets or []}


# -- CLI --------------------------------------------------------------------


def main(argv=None) -> None:
    parser = argparse.ArgumentParser("seldon-tpu-tester")
    parser.add_argument("contract", help="path to contract JSON")
    parser.add_argument("host")
    parser.add_argument("port", type=int)
    parser.add_argument("--endpoint", default="predict",
                        choices=["predict", "transform-input", "transform-output",
                                 "route", "send-feedback"])
    parser.add_argument("-n", "--n-requests", type=int, default=1)
    parser.add_argument("-b", "--batch-size", type=int, default=1)
    parser.add_argument("--grpc", action="store_true")
    parser.add_argument("--api", action="store_true",
                        help="drive the external engine/gateway API instead of a microservice")
    parser.add_argument("--deployment", help="deployment name (gateway mode)")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("-p", "--prnt", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(level="INFO")
    with open(args.contract) as f:
        contract = json.load(f)
    endpoint_addr = f"{args.host}:{args.port}"
    if args.api and args.deployment:
        if args.grpc:
            parser.error(
                "the gateway serves REST only; point --api at an engine "
                "host:port (drop --deployment) for gRPC"
            )
        client = SeldonClient(
            deployment_name=args.deployment, namespace=args.namespace,
            gateway_endpoint=endpoint_addr,
            transport="grpc" if args.grpc else "rest",
        )
    elif args.api:
        client = SeldonClient(engine_endpoint=endpoint_addr,
                              transport="grpc" if args.grpc else "rest")
    else:
        client = SeldonClient(microservice_endpoint=endpoint_addr,
                              transport="grpc" if args.grpc else "rest")
    summary = run_contract_test(
        client, contract,
        n_requests=args.n_requests, batch_size=args.batch_size,
        endpoint=args.endpoint, external=args.api, seed=args.seed,
    )
    print(json.dumps(summary))
    if summary["failed"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
