"""Literal FlatBuffers transport (reference: fbs/prediction.fbs:1-60 and
the ``seldon-core-microservice <Class> FBS`` CLI choice,
microservice.py:186).

In the reference tree the FBS transport is vestigial — the schema ships
but the Python implementation does not. Here it is real: a length-prefixed
TCP framing carrying ``SeldonRPC { method, SeldonMessage }`` flatbuffers,
hand-built against the schema with the ``flatbuffers`` runtime (no flatc
codegen — the schema is 9 small tables, and generated code would be the
only generated Python in the repo).

Framing: 4-byte little-endian payload length, then the flatbuffer. The
response is a ``SeldonRPC`` with ``method = RESPONSE``.

This transport exists for wire parity; the TPU-native preferred encoding
is binary protobuf with ``RawTensor`` (payload.py) — the fbs schema's
``Tensor.values:[double]`` costs 4x the bytes of bf16 raw and cannot
carry extended dtypes, which is why the reference's own successor
abandoned it.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

try:
    import flatbuffers
    from flatbuffers.table import Table
except ImportError:  # pragma: no cover - flatbuffers is in the image
    flatbuffers = None
    Table = None

SELDON_PROTOCOL_V1 = 134361921  # fbs/prediction.fbs SeldonProtocolVersion.V1
METHOD_PREDICT = 0
METHOD_RESPONSE = 1
STATUS_SUCCESS = 0
STATUS_FAILURE = 1
# union Data { DefaultData = 1, ByteData = 2, StrData = 3 } (union types
# are 1-indexed in flatbuffers; 0 = NONE)
DATA_DEFAULT = 1
DATA_BYTES = 2
DATA_STR = 3
PAYLOAD_SELDON_MESSAGE = 1

def _require():
    if flatbuffers is None:
        raise RuntimeError("flatbuffers runtime unavailable in this build")


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------


def _build_tensor(b, arr: np.ndarray) -> int:
    arr = np.ascontiguousarray(arr, dtype=np.float64)
    # values vector (doubles) — schema Tensor.values:[double]
    values = b.CreateNumpyVector(arr.ravel())
    shape_list = list(arr.shape)
    b.StartVector(4, len(shape_list), 4)
    for s in reversed(shape_list):
        b.PrependInt32(s)
    shape = b.EndVector()
    b.StartObject(2)
    b.PrependUOffsetTRelativeSlot(0, shape, 0)
    b.PrependUOffsetTRelativeSlot(1, values, 0)
    return b.EndObject()


def _build_default_data(b, arr: np.ndarray, names) -> int:
    name_offs = [b.CreateString(str(n)) for n in (names or [])]
    names_vec = 0
    if name_offs:
        b.StartVector(4, len(name_offs), 4)
        for off in reversed(name_offs):
            b.PrependUOffsetTRelative(off)
        names_vec = b.EndVector()
    tensor = _build_tensor(b, arr)
    b.StartObject(2)
    if names_vec:
        b.PrependUOffsetTRelativeSlot(0, names_vec, 0)
    b.PrependUOffsetTRelativeSlot(1, tensor, 0)
    return b.EndObject()


def _build_status(b, code: int, info: str, flag: int) -> int:
    info_off = b.CreateString(info) if info else 0
    b.StartObject(4)
    b.PrependInt32Slot(0, code, 0)
    if info_off:
        b.PrependUOffsetTRelativeSlot(1, info_off, 0)
    b.PrependInt8Slot(3, flag, 0)
    return b.EndObject()


def _build_meta(b, puid: str) -> int:
    puid_off = b.CreateString(puid) if puid else 0
    b.StartObject(3)
    if puid_off:
        b.PrependUOffsetTRelativeSlot(0, puid_off, 0)
    return b.EndObject()


def encode_message(
    arr: Optional[np.ndarray] = None,
    names=None,
    *,
    str_data: Optional[str] = None,
    bin_data: Optional[bytes] = None,
    puid: str = "",
    status: Optional[Tuple[int, str, int]] = None,
    method: int = METHOD_PREDICT,
) -> bytes:
    """numpy/str/bytes -> length-prefixed SeldonRPC flatbuffer."""
    _require()
    b = flatbuffers.Builder(1024)
    data_off, data_type = 0, 0
    if arr is not None:
        data_off = _build_default_data(b, np.asarray(arr), names)
        data_type = DATA_DEFAULT
    elif str_data is not None:
        s = b.CreateString(str_data)
        b.StartObject(1)
        b.PrependUOffsetTRelativeSlot(0, s, 0)
        data_off = b.EndObject()
        data_type = DATA_STR
    elif bin_data is not None:
        vec = b.CreateByteVector(bin_data)
        b.StartObject(1)
        b.PrependUOffsetTRelativeSlot(0, vec, 0)
        data_off = b.EndObject()
        data_type = DATA_BYTES
    status_off = _build_status(b, *status) if status else 0
    meta_off = _build_meta(b, puid)
    # SeldonMessage: protocol s0, status s1, meta s2, data_type s3, data s4
    b.StartObject(5)
    b.PrependInt32Slot(0, SELDON_PROTOCOL_V1, 0)
    if status_off:
        b.PrependUOffsetTRelativeSlot(1, status_off, 0)
    if meta_off:
        b.PrependUOffsetTRelativeSlot(2, meta_off, 0)
    if data_type:
        b.PrependUint8Slot(3, data_type, 0)
        b.PrependUOffsetTRelativeSlot(4, data_off, 0)
    msg = b.EndObject()
    # SeldonRPC: method s0, message_type s1, message s2
    b.StartObject(3)
    b.PrependInt8Slot(0, method, 0)
    b.PrependUint8Slot(1, PAYLOAD_SELDON_MESSAGE, 0)
    b.PrependUOffsetTRelativeSlot(2, msg, 0)
    rpc = b.EndObject()
    b.Finish(rpc)
    payload = bytes(b.Output())
    return struct.pack("<I", len(payload)) + payload


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------


class _T:
    """Thin reader over a flatbuffers table position."""

    def __init__(self, tab: "Table"):
        self.tab = tab

    def _off(self, slot: int) -> int:
        return self.tab.Offset(4 + 2 * slot)

    def i32(self, slot: int, default: int = 0) -> int:
        o = self._off(slot)
        if not o:
            return default
        return self.tab.Get(flatbuffers.number_types.Int32Flags, o + self.tab.Pos)

    def i8(self, slot: int, default: int = 0) -> int:
        o = self._off(slot)
        if not o:
            return default
        return self.tab.Get(flatbuffers.number_types.Int8Flags, o + self.tab.Pos)

    def u8(self, slot: int, default: int = 0) -> int:
        o = self._off(slot)
        if not o:
            return default
        return self.tab.Get(flatbuffers.number_types.Uint8Flags, o + self.tab.Pos)

    def string(self, slot: int) -> Optional[str]:
        o = self._off(slot)
        if not o:
            return None
        return self.tab.String(o + self.tab.Pos).decode("utf-8")

    def table(self, slot: int) -> Optional["_T"]:
        o = self._off(slot)
        if not o:
            return None
        pos = self.tab.Indirect(o + self.tab.Pos)
        return _T(Table(self.tab.Bytes, pos))

    # a union value slot stores an offset to the member table, exactly
    # like a table field — one reader serves both
    union_table = table

    def vector_len(self, slot: int) -> int:
        o = self._off(slot)
        return self.tab.VectorLen(o) if o else 0

    def vector_np(self, slot: int, dtype) -> np.ndarray:
        o = self._off(slot)
        if not o:
            return np.zeros((0,), dtype)
        n = self.tab.VectorLen(o)
        start = self.tab.Vector(o)
        return np.frombuffer(self.tab.Bytes, dtype=dtype, count=n, offset=start)

    def string_vector(self, slot: int):
        o = self._off(slot)
        if not o:
            return []
        n = self.tab.VectorLen(o)
        start = self.tab.Vector(o)
        out = []
        for i in range(n):
            out.append(
                self.tab.String(start + i * 4).decode("utf-8")
            )
        return out


def decode_message(blob: bytes, *, prefixed: bool = True) -> Dict[str, Any]:
    """SeldonRPC flatbuffer -> dict with keys method, data
    (np.ndarray | None), names, strData, binData, puid,
    status {code, info, status}.

    Framing is explicit: ``prefixed=True`` (the default — everything
    in-repo, including ``encode_message``, uses 4-byte length-prefixed
    frames) reads the root after the prefix; ``prefixed=False`` parses a
    bare flatbuffer. The prefix is never guessed from the first word — a
    bare buffer whose root offset happens to equal len-4 must not be
    silently misparsed from the wrong base."""
    _require()
    base = 0
    if prefixed:
        if len(blob) < 4:
            raise ValueError("length-prefixed flatbuffer shorter than 4 bytes")
        (ln,) = struct.unpack_from("<I", blob)
        if ln != len(blob) - 4:
            raise ValueError(
                f"flatbuffer length prefix {ln} != payload size {len(blob) - 4}"
            )
        # offsets are relative, so the root is simply shifted by the prefix —
        # no slice/copy of the (possibly 64MB) frame is needed
        base = 4
    buf = blob if isinstance(blob, (bytes, bytearray)) else bytes(blob)
    root_pos = base + struct.unpack_from("<I", buf, base)[0]
    rpc = _T(Table(buf, root_pos))
    out: Dict[str, Any] = {
        "method": rpc.i8(0),
        "data": None, "names": [], "strData": None, "binData": None,
        "puid": "", "status": None,
    }
    if rpc.u8(1) != PAYLOAD_SELDON_MESSAGE:
        return out
    msg = rpc.union_table(2)
    if msg is None:
        return out
    protocol = msg.i32(0)
    if protocol and protocol != SELDON_PROTOCOL_V1:
        raise ValueError(f"unknown fbs protocol version {protocol}")
    st = msg.table(1)
    if st is not None:
        out["status"] = {
            "code": st.i32(0), "info": st.string(1) or "",
            "status": "FAILURE" if st.i8(3) == STATUS_FAILURE else "SUCCESS",
        }
    meta = msg.table(2)
    if meta is not None:
        out["puid"] = meta.string(0) or ""
    dtype_tag = msg.u8(3)
    data = msg.union_table(4)
    if data is None:
        return out
    if dtype_tag == DATA_DEFAULT:
        out["names"] = data.string_vector(0)
        tensor = data.table(1)
        if tensor is not None:
            shape = tensor.vector_np(0, np.int32)
            values = tensor.vector_np(1, np.float64)
            arr = np.array(values, dtype=np.float64)
            if shape.size:
                arr = arr.reshape([int(s) for s in shape])
            out["data"] = arr
    elif dtype_tag == DATA_STR:
        out["strData"] = data.string(0)
    elif dtype_tag == DATA_BYTES:
        out["binData"] = bytes(data.vector_np(0, np.int8).tobytes())
    return out


# ---------------------------------------------------------------------------
# TCP server (the FBS microservice front)
# ---------------------------------------------------------------------------


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes or None on EOF (shared by server and client)."""
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return buf


class FBSServer:
    """Length-prefixed FlatBuffers predict server: one SeldonRPC in, one
    SeldonRPC (method=RESPONSE) out, connection kept alive. Runs the user
    object's predict through the same dispatch the REST front uses."""

    MAX_FRAME = 64 << 20  # same OOM guard as the HTTP fronts

    def __init__(self, user_object, host: str = "0.0.0.0", port: int = 5000,
                 reuse_port: bool = False):
        self.user_object = user_object
        self.host, self.port = host, port
        self.reuse_port = reuse_port
        self._srv: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    def start(self) -> "FBSServer":
        _require()
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if self.reuse_port:
            # --workers multi-process contract: every worker binds the
            # same port, the kernel load-balances accepts (microservice.py)
            self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self._srv.bind((self.host, self.port))
        if self.port == 0:
            self.port = self._srv.getsockname()[1]
        self._srv.listen(16)
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="fbs-accept").start()
        return self

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="fbs-conn").start()


    def _serve_conn(self, conn: socket.socket):
        from .seldon_methods import predict

        try:
            while not self._stop.is_set():
                head = _recv_exact(conn, 4)
                if head is None:
                    return
                (ln,) = struct.unpack("<I", head)
                if ln > self.MAX_FRAME:
                    # drain (bounded) before responding: closing with the
                    # frame still inbound RSTs the socket and destroys the
                    # 413 before the client reads it (http_server._bail twin)
                    conn.settimeout(1.0)
                    remaining = ln
                    try:
                        while remaining > 0:
                            chunk = conn.recv(min(65536, remaining))
                            if not chunk:
                                break
                            remaining -= len(chunk)
                    except socket.timeout:
                        pass
                    conn.sendall(encode_message(
                        status=(413, f"frame {ln} exceeds {self.MAX_FRAME}",
                                STATUS_FAILURE),
                        method=METHOD_RESPONSE,
                    ))
                    return
                payload = _recv_exact(conn, ln)
                if payload is None:
                    return
                try:
                    req = decode_message(head + payload)
                    if req["method"] != METHOD_PREDICT:
                        conn.sendall(encode_message(
                            status=(400, f"unsupported method {req['method']}"
                                    " (only PREDICT is served)", STATUS_FAILURE),
                            method=METHOD_RESPONSE,
                        ))
                        continue
                    body: Dict[str, Any] = {}
                    if req["data"] is not None:
                        body["data"] = {"ndarray": req["data"].tolist(),
                                        "names": req["names"]}
                    elif req["strData"] is not None:
                        body["strData"] = req["strData"]
                    elif req["binData"] is not None:
                        import base64

                        body["binData"] = base64.b64encode(
                            req["binData"]).decode("ascii")
                    out = predict(self.user_object, body)
                    data = out.get("data") or {}
                    arr = None
                    if "ndarray" in data:
                        arr = np.asarray(data["ndarray"])
                    elif "tensor" in data:
                        t = data["tensor"]
                        arr = np.asarray(t.get("values", [])).reshape(
                            t.get("shape", [-1])
                        )
                    elif "raw" in data:
                        from .payload import json_data_to_array

                        arr = json_data_to_array(data)
                    str_out = out.get("strData")
                    bin_out = None
                    if out.get("binData") is not None:
                        import base64

                        bin_out = base64.b64decode(out["binData"])
                    elif str_out is None and out.get("jsonData") is not None:
                        # the fbs schema predates jsonData; carry it as a
                        # JSON string in StrData (documented deviation)
                        import json as _json

                        str_out = _json.dumps(out["jsonData"])
                    resp = encode_message(
                        arr,
                        data.get("names"),
                        str_data=str_out,
                        bin_data=bin_out,
                        puid=(out.get("meta") or {}).get("puid", ""),
                        status=(200, "", STATUS_SUCCESS),
                        method=METHOD_RESPONSE,
                    )
                except Exception as e:  # noqa: BLE001 - wire errors back
                    resp = encode_message(
                        status=(500, f"{type(e).__name__}: {e}", STATUS_FAILURE),
                        method=METHOD_RESPONSE,
                    )
                conn.sendall(resp)
        except OSError:
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        """Stop accepting AND unblock live handlers (a bare listener close
        would leave keep-alive connections parked in recv forever)."""
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        with self._conns_lock:
            live = list(self._conns)
        for conn in live:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


def fbs_predict(host: str, port: int, arr, names=None, timeout: float = 10.0):
    """Client helper: one predict round-trip over the FBS transport."""
    _require()
    with socket.create_connection((host, port), timeout) as conn:
        conn.sendall(encode_message(np.asarray(arr), names))
        head = _recv_exact(conn, 4)
        if head is None:
            raise ConnectionError("fbs server closed mid-response")
        (ln,) = struct.unpack("<I", head)
        payload = _recv_exact(conn, ln)
        if payload is None:
            raise ConnectionError("fbs server closed mid-response")
    return decode_message(head + payload)
