"""Custom-metrics helpers shipped in ``Meta.metrics``.

Parity with reference: python/seldon_core/metrics.py:8-88 (COUNTER/GAUGE/
TIMER dicts validated then merged into the response meta), consumed by the
engine's metrics sink (reference:
engine/src/main/java/io/seldon/engine/metrics/CustomMetricsManager.java:27-70).

The delta contract
------------------

The engine sink **sums** every COUNTER value it receives per response
(``engine_metrics.record_custom``). A component that keeps cumulative
totals (the continuous batcher's scheduler counters) must therefore ship
the *increment since its last export*, never the running total — a total
re-shipped on every scrape would grow the engine series quadratically.
:class:`CounterDeltas` is the one sanctioned way to do that conversion:
one instance per component, ``delta = deltas.counter(key, running_total)``
per export. Rules:

* COUNTER = a delta produced by ``CounterDeltas.counter`` (monotonic
  source total; the first export ships the whole total as its delta);
* GAUGE = a level (cache bytes, occupancy, acceptance rate) — ship the
  current value, the sink overwrites;
* TIMER = one duration sample in **milliseconds** — the sink divides by
  1000 into a seconds histogram (one sample per event, e.g. the generate
  server's per-completion TTFT/TPOT/queue-wait triple).

The generate server's ``metrics()`` hook is the reference implementation
of all three.
"""

from __future__ import annotations

from typing import Dict, List

COUNTER = "COUNTER"
GAUGE = "GAUGE"
TIMER = "TIMER"

_TYPES = (COUNTER, GAUGE, TIMER)


def create_counter(key: str, value: float, tags: Dict[str, str] | None = None) -> Dict:
    m = {"key": key, "type": COUNTER, "value": value}
    if tags:
        m["tags"] = tags
    return m


def create_gauge(key: str, value: float, tags: Dict[str, str] | None = None) -> Dict:
    m = {"key": key, "type": GAUGE, "value": value}
    if tags:
        m["tags"] = tags
    return m


def create_timer(key: str, value: float, tags: Dict[str, str] | None = None) -> Dict:
    m = {"key": key, "type": TIMER, "value": value}
    if tags:
        m["tags"] = tags
    return m


class CounterDeltas:
    """Turn monotonically growing totals into ``Meta.metrics`` COUNTER
    deltas. The engine sink SUMS counter values per response
    (engine_metrics.record_custom), so a component holding cumulative
    stats (e.g. the continuous batcher's scheduler counters) must ship
    the increment since its last export, not the running total — this
    keeps that bookkeeping in one place. Locked: ``metrics()`` hooks run
    per-response from the serving thread pool, and an unlocked
    read-modify-write would double-report (or drop) deltas under
    concurrent exports."""

    def __init__(self):
        import threading

        self._last: Dict[str, float] = {}
        self._lock = threading.Lock()

    def counter(self, key: str, total: float, tags: Dict[str, str] | None = None) -> Dict:
        # the delta ledger is keyed by (key, tags): per-tenant counters
        # share a key and differ only in tags, and folding the tags in
        # keeps each series' running total independent — without this a
        # two-tenant export would see the other tenant's total and
        # clamp every other delta to zero
        ledger_key = key if not tags else key + "|" + ",".join(
            f"{k}={v}" for k, v in sorted(tags.items())
        )
        with self._lock:
            last = self._last.get(ledger_key, 0.0)
            self._last[ledger_key] = float(total)
        return create_counter(key, max(0.0, float(total) - last), tags)


class Ewma:
    """Exponentially weighted moving average of a scalar, thread-safe.

    ``value`` stays 0.0 until the first update; callers treat 0 as "no
    estimate yet". The engine's admission gate feeds it successful
    request durations — the observed-service-time estimate that drives
    deadline-aware load shedding (shed-before-work: reject when the
    expected completion time already exceeds the request's remaining
    budget). The continuous batcher's admit queue sheds on a different
    estimator suited to its shape — a completion-rate window over recent
    finishes (serving/continuous.py observed_rate)."""

    def __init__(self, alpha: float = 0.1):
        import threading

        self.alpha = float(alpha)
        self.value = 0.0
        self._seen = False
        self._lock = threading.Lock()

    def update(self, x: float) -> float:
        with self._lock:
            if not self._seen:
                self.value = float(x)
                self._seen = True
            else:
                self.value += self.alpha * (float(x) - self.value)
            return self.value


def validate_metrics(metrics: List[Dict]) -> bool:
    if not isinstance(metrics, (list, tuple)):
        return False
    for m in metrics:
        if not isinstance(m, dict):
            return False
        if "key" not in m or "value" not in m:
            return False
        if m.get("type", COUNTER) not in _TYPES:
            return False
        try:
            float(m["value"])
        except (TypeError, ValueError):
            return False
    return True
