"""seldon-lint core: findings, suppressions, baseline, and the runner.

Design constraints, in priority order:

* **Stdlib only.** Everything rides on ``ast`` + ``re`` so the gate runs
  in any environment that can import the repo.
* **Regression gate, not a style cop.** A checked-in baseline file holds
  accepted pre-existing findings; CI fails only on findings NOT covered
  by the baseline, so landing the analyzer never blocks on boiling the
  ocean — while any *new* violation of an encoded invariant fails the
  build the day it is written.
* **Suppressible with provenance.** ``# seldon-lint: disable=<rule>``
  on the flagged line (or alone on the line above) silences exactly that
  rule there; reviewers see the justification comment next to it.

Baseline matching is by ``(rule, path, stripped line text)`` with
counts, not line numbers — unrelated edits that shift a file must not
resurrect accepted findings, while editing the flagged line itself
re-opens the question.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "LintContext",
    "LintResult",
    "SourceFile",
    "collect_files",
    "load_baseline",
    "run_lint",
    "write_baseline",
]

_DIRECTIVE = re.compile(
    r"#\s*seldon-lint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\- ]+)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored at ``path:line:col``."""

    rule: str
    path: str  # repo-relative, POSIX separators
    line: int
    col: int
    message: str
    line_text: str = ""  # stripped source line: the baseline key

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.line_text)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


class SourceFile:
    """A parsed python file plus its suppression directives."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:  # surfaced as a parse-error finding
            self.parse_error = e
        # line -> set of disabled rules ({"all"} disables everything)
        self._disabled: Dict[int, set] = {}
        self._file_disabled: set = set()
        for i, line in enumerate(self.lines, start=1):
            m = _DIRECTIVE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
            if m.group(1) == "disable-file":
                self._file_disabled |= rules
            else:
                self._disabled.setdefault(i, set()).update(rules)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        if {"all", rule} & self._file_disabled:
            return True
        for at in (lineno, lineno - 1):
            rules = self._disabled.get(at)
            if rules and ({"all", rule} & rules):
                # a directive on the preceding line only counts when that
                # line is a standalone comment (a trailing directive
                # belongs to ITS line's findings)
                if at == lineno or self.line_text(at).startswith("#"):
                    return True
        return False

    def finding(self, rule: str, node, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule, self.rel, line, col, message, self.line_text(line))


@dataclasses.dataclass
class LintContext:
    """Project-level inputs shared by the rules."""

    root: str
    docs_files: List[str] = dataclasses.field(default_factory=list)

    def doc_text(self, path: str) -> str:
        with open(path, "r", encoding="utf-8") as f:
            return f.read()


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]  # actionable: neither suppressed nor baselined
    baselined: List[Finding]
    suppressed: List[Finding]
    files: int = 0

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


def collect_files(paths: Sequence[str], root: str) -> List[SourceFile]:
    out: List[SourceFile] = []
    seen = set()
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            cands = [ap]
        else:
            cands = []
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                cands.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames)
                    if f.endswith(".py")
                )
        for f in sorted(cands):
            if f in seen:
                continue
            seen.add(f)
            rel = os.path.relpath(f, root).replace(os.sep, "/")
            with open(f, "r", encoding="utf-8") as fh:
                out.append(SourceFile(f, rel, fh.read()))
    return out


def default_docs(root: str) -> List[str]:
    docs_dir = os.path.join(root, "docs")
    if not os.path.isdir(docs_dir):
        return []
    return sorted(
        os.path.join(docs_dir, f)
        for f in os.listdir(docs_dir)
        if f.endswith(".md")
    )


# -- baseline ---------------------------------------------------------------


def load_baseline(path: str) -> Counter:
    """``(rule, path, line_text) -> accepted count``; empty when absent."""
    if not path or not os.path.exists(path):
        return Counter()
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: Counter = Counter()
    for entry in data.get("findings", []):
        key = (entry["rule"], entry["path"], entry.get("line_text", ""))
        out[key] += int(entry.get("count", 1))
    return out


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    counts: Counter = Counter(f.key() for f in findings)
    payload = {
        "version": 1,
        "comment": (
            "Accepted pre-existing seldon-lint findings. CI fails only on "
            "findings NOT in this file. Refresh with: "
            "python tools/seldon_lint.py --write-baseline <paths>"
        ),
        "findings": [
            {"rule": rule, "path": path_, "line_text": text, "count": n}
            for (rule, path_, text), n in sorted(counts.items())
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")


# -- runner -----------------------------------------------------------------


def _all_rules():
    # local import: rule modules import this module for Finding/SourceFile
    from . import contracts, hotpath, locks, threads

    return {
        "thread-role": threads.check_thread_roles,
        "blocking-under-lock": locks.check_blocking_under_lock,
        "lock-order": locks.check_lock_order,
        "host-sync-hot-path": hotpath.check_host_sync,
        "retrace-hazard": hotpath.check_retrace,
        "metric-drift": contracts.check_metric_drift,
        "annotation-drift": contracts.check_annotation_drift,
        "wall-clock": contracts.check_wall_clock,
    }


def run_lint(
    paths: Sequence[str],
    root: Optional[str] = None,
    docs: Optional[Sequence[str]] = None,
    rules: Optional[Sequence[str]] = None,
    baseline: Optional[Counter] = None,
) -> LintResult:
    """Run the rule set over ``paths`` and partition the findings.

    ``rules`` restricts to a subset of rule ids; ``baseline`` consumes
    matching findings up to each accepted count.
    """
    root = os.path.abspath(root or os.getcwd())
    files = collect_files(paths, root)
    ctx = LintContext(
        root=root,
        docs_files=list(docs) if docs is not None else default_docs(root),
    )
    available = _all_rules()
    if rules:
        unknown = set(rules) - set(available)
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}")
        selected = {k: v for k, v in available.items() if k in set(rules)}
    else:
        selected = available

    raw: List[Finding] = []
    for sf in files:
        if sf.parse_error is not None:
            raw.append(Finding(
                "parse-error", sf.rel, sf.parse_error.lineno or 1, 0,
                f"syntax error: {sf.parse_error.msg}",
                sf.line_text(sf.parse_error.lineno or 1),
            ))
    for rule_fn in selected.values():
        raw.extend(rule_fn(files, ctx))

    by_file = {sf.rel: sf for sf in files}
    suppressed: List[Finding] = []
    remaining: List[Finding] = []
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.col, f.rule)):
        sf = by_file.get(f.path)
        if sf is not None and sf.suppressed(f.rule, f.line):
            suppressed.append(f)
        else:
            remaining.append(f)

    budget = Counter(baseline or ())
    actionable: List[Finding] = []
    baselined: List[Finding] = []
    for f in remaining:
        if budget[f.key()] > 0:
            budget[f.key()] -= 1
            baselined.append(f)
        else:
            actionable.append(f)
    return LintResult(
        findings=actionable,
        baselined=baselined,
        suppressed=suppressed,
        files=len(files),
    )
