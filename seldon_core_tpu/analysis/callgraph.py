"""Lightweight per-module AST index: classes, methods, ``self.`` edges.

The serving stack's threading and locking invariants are all *intra-
class* properties (the batcher's scheduler contract, per-object lock
ordering), so the call graph deliberately resolves only what it can
resolve soundly:

* ``self.method(...)`` inside a class body → an edge to that class's
  method (if defined). This follows the admit path, the poll loop, the
  swap machinery — everything the thread-role and lock rules need.
* ``self._some_fn(...)`` where ``_some_fn`` was assigned from
  ``jax.jit(...)`` in the same class → recorded as a *jitted call site*
  with the jit's ``static_argnums`` (the hot-path rules consume these).
* Anything else (cross-object calls, dynamic dispatch) is NOT an edge.
  Under-approximating keeps the rules quiet where they cannot be sure;
  the runtime role assertions (``SELDON_DEBUG_THREADS=1``) cover the
  dynamic remainder.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

__all__ = ["ClassIndex", "MethodInfo", "index_classes", "decorator_names", "reach_path"]


def decorator_names(node) -> Set[str]:
    """Trailing identifiers of each decorator (``@roles.scheduler_only``
    and ``@scheduler_only`` both yield ``scheduler_only``)."""
    out: Set[str] = set()
    for dec in getattr(node, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Attribute):
            out.add(target.attr)
        elif isinstance(target, ast.Name):
            out.add(target.id)
    return out


@dataclasses.dataclass
class MethodInfo:
    name: str
    node: ast.AST
    decorators: Set[str]
    # callee method name -> first call-site line number
    self_calls: Dict[str, int]
    role: Optional[str] = None  # "scheduler" | "caller" | None


@dataclasses.dataclass
class ClassIndex:
    name: str
    node: ast.ClassDef
    methods: Dict[str, MethodInfo]
    # attr name -> static_argnums for self.<attr> = jax.jit(fn, ...)
    jit_attrs: Dict[str, Tuple[int, ...]]


def _is_jit_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "jit":
        return True
    return isinstance(f, ast.Name) and f.id == "jit"


def _static_argnums(call: ast.Call) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "static_argnums" and isinstance(
            kw.value, (ast.Tuple, ast.List)
        ):
            out = []
            for elt in kw.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    out.append(elt.value)
            return tuple(out)
        if kw.arg == "static_argnums" and isinstance(kw.value, ast.Constant):
            if isinstance(kw.value.value, int):
                return (kw.value.value,)
    return ()


def _index_method(fn: ast.AST) -> MethodInfo:
    decs = decorator_names(fn)
    calls: Dict[str, int] = {}
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            recv = sub.func.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                calls.setdefault(sub.func.attr, sub.lineno)
    role = None
    if "scheduler_only" in decs:
        role = "scheduler"
    elif "caller_thread" in decs:
        role = "caller"
    return MethodInfo(
        name=fn.name, node=fn, decorators=decs, self_calls=calls, role=role
    )


def index_classes(tree: ast.AST) -> List[ClassIndex]:
    out: List[ClassIndex] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        methods: Dict[str, MethodInfo] = {}
        jit_attrs: Dict[str, Tuple[int, ...]] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[item.name] = _index_method(item)
        # self.<attr> = jax.jit(...) anywhere in the class (usually __init__,
        # including nested branches — speculation assigns conditionally)
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign) or not isinstance(
                sub.value, ast.Call
            ):
                continue
            if not _is_jit_call(sub.value):
                continue
            for tgt in sub.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    jit_attrs[tgt.attr] = _static_argnums(sub.value)
        out.append(ClassIndex(node.name, node, methods, jit_attrs))
    return out


def reach_path(
    cls: ClassIndex,
    start: str,
    hits: Set[str],
    through: Optional[Set[str]] = None,
) -> Optional[List[Tuple[str, int]]]:
    """Shortest-ish self-call path from ``start`` to any method in
    ``hits``, traversing only methods in ``through`` (None = any method
    not itself in ``hits``). Returns ``[(callee, call lineno), ...]``
    edges, or None when unreachable. BFS so reports stay minimal."""
    from collections import deque

    q = deque([(start, [])])
    seen = {start}
    while q:
        cur, path = q.popleft()
        info = cls.methods.get(cur)
        if info is None:
            continue
        for callee, lineno in sorted(info.self_calls.items()):
            edge = path + [(callee, lineno)]
            if callee in hits:
                return edge
            if callee in seen or callee not in cls.methods:
                continue
            if through is not None and callee not in through:
                continue
            seen.add(callee)
            q.append((callee, edge))
    return None


def reachable_set(cls: ClassIndex, roots: Sequence[str]) -> Set[str]:
    """Every method reachable from ``roots`` via self-calls (inclusive)."""
    out: Set[str] = set()
    stack = [r for r in roots if r in cls.methods]
    while stack:
        cur = stack.pop()
        if cur in out:
            continue
        out.add(cur)
        stack.extend(
            c for c in cls.methods[cur].self_calls if c in cls.methods
        )
    return out


def iter_functions(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
