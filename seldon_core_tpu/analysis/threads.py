"""``thread-role``: caller-thread entry points must not reach
scheduler-only device mutations through the call graph.

The legal handoff between the two roles is the admit queue
(``self._queue.put`` on the caller side, ``get_nowait`` in the poll
loop) — a data-flow edge the call graph deliberately does not follow.
Any *call-graph* path from a ``@caller_thread`` method to a
``@scheduler_only`` method therefore means caller code can execute a
device mutation on the wrong thread, racing the scheduler over donated
buffers.

The reverse direction is checked too: a ``@scheduler_only`` method
calling a ``@caller_thread`` entry point would have the poll loop block
on its own progress (``start()`` waits on ``_started``, ``generate()``
waits on a future the loop must resolve) — a deadlock, not a race.

Classes with no role declarations are skipped entirely: the rule rides
on declared intent, it does not guess.
"""

from __future__ import annotations

from typing import Iterable, List

from .callgraph import index_classes, reach_path
from .core import Finding, LintContext, SourceFile

__all__ = ["check_thread_roles"]


def _fmt_path(start: str, edges) -> str:
    return " -> ".join([start] + [callee for callee, _ in edges])


def check_thread_roles(
    files: List[SourceFile], ctx: LintContext
) -> Iterable[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        for cls in index_classes(sf.tree):
            sched = {n for n, m in cls.methods.items() if m.role == "scheduler"}
            callers = {n for n, m in cls.methods.items() if m.role == "caller"}
            if not sched or not callers:
                continue
            # undecorated methods and same-role methods are legal
            # intermediaries; only reaching the OPPOSITE role violates
            for entry in sorted(callers):
                through = set(cls.methods) - sched
                edges = reach_path(cls, entry, sched, through=through)
                if edges is not None:
                    callee, lineno = edges[-1]
                    findings.append(Finding(
                        "thread-role", sf.rel, lineno, 0,
                        f"caller-thread entry point '{entry}' reaches "
                        f"scheduler-only '{cls.name}.{callee}' via "
                        f"{_fmt_path(entry, edges)}; device state may only "
                        "be touched by the scheduler thread — hand off "
                        "through the admit queue",
                        sf.line_text(lineno),
                    ))
            for entry in sorted(sched):
                edges = reach_path(
                    cls, entry, callers,
                    through=set(cls.methods) - callers,
                )
                if edges is not None:
                    callee, lineno = edges[-1]
                    findings.append(Finding(
                        "thread-role", sf.rel, lineno, 0,
                        f"scheduler-only '{entry}' reaches caller-thread "
                        f"entry point '{cls.name}.{callee}' via "
                        f"{_fmt_path(entry, edges)}; caller entry points "
                        "block on scheduler progress — calling one from "
                        "the poll loop deadlocks it",
                        sf.line_text(lineno),
                    ))
    return findings
