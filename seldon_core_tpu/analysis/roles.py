"""Thread-role contracts: which thread may run which method.

The continuous batcher's correctness rests on a single ownership rule:
**device decode state (KV cache, lane registers, PRNG streams) is
mutated on the scheduler thread only**; request-worker ("caller")
threads hand work over exclusively through the admit queue (plus the
caller-side H2D upload in ``admit_remote``, which touches no lane
state). Before this module that rule lived in comments. Now it is
declared:

* ``@scheduler_only`` — the method mutates scheduler-owned state and
  must run on the batcher's scheduler thread (``self._thread``).
* ``@caller_thread`` — the method is a caller-facing entry point and
  must NEVER run on the scheduler thread (it blocks on scheduler
  progress — running it there deadlocks the loop).

Two enforcement layers consume the declarations:

* The ``thread-role`` static rule (:mod:`.threads`) verifies by
  call-graph reachability that no ``@caller_thread`` entry point reaches
  a ``@scheduler_only`` method — the admit-queue handoff is invisible to
  the call graph, so it is the only legal path.
* With ``SELDON_DEBUG_THREADS=1`` in the environment (read once at
  import — the tier-1 test run and the chaos/disagg smokes set it), the
  decorators wrap each method with an executing-thread assertion, so a
  role violation fails loudly in tests instead of corrupting device
  state. Without the env var the decorators only tag the function
  (``__seldon_role__``) and return it unchanged — zero runtime cost on
  the hot path.

The scheduler thread is discovered per instance: ``self._thread`` (the
batcher), falling back to ``self.batcher._thread`` (the generate
server). A method whose object has no live scheduler thread yet — e.g.
``_alloc_device_state`` from the constructor, before ``start()`` — is
exempt: roles constrain *which* thread, not *whether* one exists.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Callable, Optional, TypeVar

F = TypeVar("F", bound=Callable)

__all__ = [
    "ThreadRoleViolation",
    "caller_thread",
    "debug_threads_enabled",
    "scheduler_only",
]


class ThreadRoleViolation(AssertionError):
    """A method executed on a thread its declared role forbids.

    An ``AssertionError`` subclass on purpose: a violation is a
    programming error in the serving stack, never an operational
    condition to retry — tests must fail, not recover.
    """


def _env_enabled() -> bool:
    return os.environ.get("SELDON_DEBUG_THREADS", "").strip().lower() in (
        "1", "true", "yes", "on",
    )


_DEBUG = _env_enabled()


def debug_threads_enabled() -> bool:
    """Whether runtime role assertions are active (decided at import)."""
    return _DEBUG


def _scheduler_thread(obj) -> Optional[threading.Thread]:
    """The scheduler thread governing ``obj``, if one is running."""
    t = getattr(obj, "_thread", None)
    if isinstance(t, threading.Thread):
        return t
    batcher = getattr(obj, "batcher", None)
    if batcher is not None:
        t = getattr(batcher, "_thread", None)
        if isinstance(t, threading.Thread):
            return t
    return None


def _check(obj, role: str, qualname: str) -> None:
    sched = _scheduler_thread(obj)
    if sched is None or not sched.is_alive():
        return  # no scheduler running: init-time / test-harness calls
    cur = threading.current_thread()
    if role == "scheduler" and cur is not sched:
        raise ThreadRoleViolation(
            f"{qualname} is @scheduler_only but ran on {cur.name!r} "
            f"while the scheduler thread {sched.name!r} is alive — "
            "device state may only be mutated by the scheduler; hand "
            "work over through the admit queue"
        )
    if role == "caller" and cur is sched:
        raise ThreadRoleViolation(
            f"{qualname} is @caller_thread but ran on the scheduler "
            f"thread {sched.name!r} — caller entry points block on "
            "scheduler progress and would deadlock the poll loop"
        )


def _role_decorator(role: str) -> Callable[[F], F]:
    def decorate(fn: F) -> F:
        fn.__seldon_role__ = role
        if not _DEBUG:
            return fn

        @functools.wraps(fn)
        def guarded(self, *args, **kwargs):
            _check(self, role, fn.__qualname__)
            return fn(self, *args, **kwargs)

        guarded.__seldon_role__ = role
        return guarded  # type: ignore[return-value]

    return decorate


#: The method mutates scheduler-owned device/lane state: it must run on
#: the batcher's scheduler thread (or before any scheduler exists).
scheduler_only = _role_decorator("scheduler")

#: The method is a caller-facing entry point: it must never run on the
#: scheduler thread.
caller_thread = _role_decorator("caller")
