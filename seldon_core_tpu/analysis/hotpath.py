"""JAX hot-path hygiene: ``host-sync-hot-path`` and ``retrace-hazard``.

Scope: methods reachable (via same-class calls) from a scheduler hot
root — any ``@scheduler_only`` method, or a method named ``_loop`` /
``_run``. That is the code executing at poll cadence between device
dispatches, where a stray host sync serializes the software pipeline
and a retrace stalls every lane for seconds.

**host-sync-hot-path.** Implicit host syncs block the scheduler until
the device catches up:

* ``.item()`` / ``.block_until_ready()`` / ``jax.device_get`` anywhere
  in hot-path code — these are syncs by definition. The *designed* sync
  points (reading a finished burst's tokens) carry suppressions with
  justification, which is exactly the visibility we want.
* ``bool()`` / ``int()`` / ``float()`` / ``np.asarray()`` /
  ``np.array()`` applied to a value produced by a jitted callable in
  the same function (``self._burst_fn``-style attributes assigned from
  ``jax.jit`` in the class body). Tracking is intra-function
  assignment-based on purpose: a parameter or attribute could be
  anything, and guessing would bury real findings in noise.

**retrace-hazard.** Calls to those same jitted callables are checked at
their ``static_argnums`` positions: a static argument drawn from an
unbounded or unhashable domain re-specializes the executable per
distinct value —

* ``len(...)`` at a static position (unbounded integers; pass a pow2 /
  bucketized size instead, as ``_group_size_bucket`` does),
* float constants or ``float()`` casts (continuous domain — e.g. a
  temperature must be a traced operand, not a static),
* dict/list/set literals (unhashable: ``jit`` rejects them at runtime,
  and hashable wrappers retrace per content).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import ClassIndex, index_classes, reachable_set
from .core import Finding, LintContext, SourceFile

__all__ = ["check_host_sync", "check_retrace"]

_CAST_FNS = {"bool", "int", "float"}
_NP_SYNC = {"asarray", "array"}


def _hot_roots(cls: ClassIndex) -> List[str]:
    roots = [n for n, m in cls.methods.items() if m.role == "scheduler"]
    for name in ("_loop", "_run"):
        if name in cls.methods and name not in roots:
            roots.append(name)
    return roots


def _jit_result_names(fn: ast.AST, jit_attrs: Dict[str, Tuple[int, ...]]) -> Set[str]:
    """Names bound (directly or via tuple unpack) from a jitted call."""
    names: Set[str] = set()
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Assign) or not _is_jit_call_expr(
            sub.value, jit_attrs
        ):
            continue
        for tgt in sub.targets:
            for leaf in ast.walk(tgt):
                if isinstance(leaf, ast.Name):
                    names.add(leaf.id)
    return names


def _is_jit_call_expr(expr, jit_attrs) -> bool:
    return (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and isinstance(expr.func.value, ast.Name)
        and expr.func.value.id == "self"
        and expr.func.attr in jit_attrs
    )


def check_host_sync(
    files: List[SourceFile], ctx: LintContext
) -> Iterable[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        for cls in index_classes(sf.tree):
            roots = _hot_roots(cls)
            if not roots:
                continue
            hot = reachable_set(cls, roots)
            for name in sorted(hot):
                fn = cls.methods[name].node
                traced = _jit_result_names(fn, cls.jit_attrs)
                for sub in ast.walk(fn):
                    if not isinstance(sub, ast.Call):
                        continue
                    f = sub.func
                    if isinstance(f, ast.Attribute) and f.attr in (
                        "item", "block_until_ready",
                    ):
                        findings.append(sf.finding(
                            "host-sync-hot-path", sub,
                            f".{f.attr}() in '{cls.name}.{name}' "
                            "(poll-loop-reachable) blocks the scheduler "
                            "on the device; move the read behind the "
                            "pipelined burst boundary",
                        ))
                        continue
                    if isinstance(f, ast.Attribute) and f.attr == "device_get":
                        findings.append(sf.finding(
                            "host-sync-hot-path", sub,
                            f"device_get in '{cls.name}.{name}' "
                            "(poll-loop-reachable) is a host sync",
                        ))
                        continue
                    # casts / np conversions applied to jitted results
                    target: Optional[ast.expr] = None
                    what = None
                    if (
                        isinstance(f, ast.Name)
                        and f.id in _CAST_FNS
                        and sub.args
                    ):
                        target, what = sub.args[0], f"{f.id}()"
                    elif (
                        isinstance(f, ast.Attribute)
                        and f.attr in _NP_SYNC
                        and isinstance(f.value, ast.Name)
                        and f.value.id in ("np", "numpy")
                        and sub.args
                    ):
                        target, what = sub.args[0], f"np.{f.attr}()"
                    if target is None:
                        continue
                    # metadata reads (.nbytes/.shape/.dtype/...) off a
                    # device array never touch the device: exempt names
                    # that only appear under such attributes. ``.sharding``
                    # joins the set for mesh-sharded serving — a layout
                    # read (shard_shape, is_fully_replicated) is pure
                    # metadata, same as .shape
                    meta_names = set()
                    for wrap in ast.walk(target):
                        if isinstance(wrap, ast.Attribute) and wrap.attr in (
                            "nbytes", "shape", "ndim", "size", "dtype",
                            "sharding",
                        ):
                            meta_names.update(
                                id(leaf) for leaf in ast.walk(wrap.value)
                                if isinstance(leaf, ast.Name)
                            )
                    hit = any(
                        isinstance(leaf, ast.Name)
                        and leaf.id in traced
                        and id(leaf) not in meta_names
                        for leaf in ast.walk(target)
                    )
                    if hit:
                        findings.append(sf.finding(
                            "host-sync-hot-path", sub,
                            f"{what} on a jitted-call result in "
                            f"'{cls.name}.{name}' (poll-loop-reachable) "
                            "forces an implicit device->host sync",
                        ))
    return findings


def _static_positions(call: ast.Call, statics: Tuple[int, ...]):
    for pos in statics:
        if pos < len(call.args):
            yield pos, call.args[pos]


def check_retrace(
    files: List[SourceFile], ctx: LintContext
) -> Iterable[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        for cls in index_classes(sf.tree):
            roots = _hot_roots(cls)
            if not roots or not cls.jit_attrs:
                continue
            hot = reachable_set(cls, roots)
            for name in sorted(hot):
                fn = cls.methods[name].node
                for sub in ast.walk(fn):
                    if not isinstance(sub, ast.Call):
                        continue
                    if not _is_jit_call_expr(sub, cls.jit_attrs):
                        continue
                    attr = sub.func.attr
                    statics = cls.jit_attrs[attr]
                    for pos, arg in _static_positions(sub, statics):
                        bad = None
                        if (
                            isinstance(arg, ast.Call)
                            and isinstance(arg.func, ast.Name)
                            and arg.func.id == "len"
                        ):
                            bad = (
                                "len(...) at a static position retraces "
                                "per distinct size; pass a bucketized "
                                "value (pow2 group size, attn bucket)"
                            )
                        elif isinstance(arg, ast.Constant) and isinstance(
                            arg.value, float
                        ):
                            bad = (
                                "float constant at a static position: "
                                "continuous-domain statics re-specialize "
                                "the executable; make it a traced operand"
                            )
                        elif (
                            isinstance(arg, ast.Call)
                            and isinstance(arg.func, ast.Name)
                            and arg.func.id == "float"
                        ):
                            bad = (
                                "float(...) at a static position: "
                                "continuous-domain statics re-specialize "
                                "the executable; make it a traced operand"
                            )
                        elif isinstance(arg, (ast.Dict, ast.List, ast.Set)):
                            bad = (
                                "unhashable container literal at a static "
                                "position of a jitted callable"
                            )
                        if bad:
                            findings.append(sf.finding(
                                "retrace-hazard", arg,
                                f"self.{attr}(...) arg {pos} in "
                                f"'{cls.name}.{name}': {bad}",
                            ))
    return findings
