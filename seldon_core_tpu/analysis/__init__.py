"""seldon-lint: invariant-aware static analysis for the serving stack.

Seven PRs of multi-threaded scheduler growth left the repo's correctness
invariants living in comments and reviewers' heads: device state is
touched on the scheduler thread only, nothing blocks under a lock in the
hot loop, deadline math uses monotonic clocks, and the
``seldon_engine_*`` metric / ``seldon.io/*`` annotation vocabularies
must agree across the code that emits them, the registry that maps them,
and the docs that operators read. This package turns those conventions
into machine-checked contracts (InferLine / DeepServe both argue serving
planes need exactly this before fleet operation).

Stdlib ``ast`` only — no new dependencies. Entry points:

* ``tools/seldon_lint.py`` — the CLI and CI gate.
* :mod:`.roles` — ``@scheduler_only`` / ``@caller_thread`` thread-role
  decorators, statically verified by the ``thread-role`` rule and
  runtime-asserted under ``SELDON_DEBUG_THREADS=1``.
* :func:`.core.run_lint` — programmatic runner (used by the tests).

Rule catalog (ids are what ``# seldon-lint: disable=<rule>`` takes):

==================== ====================================================
``thread-role``      caller-thread entry points must not reach
                     scheduler-only device mutations through the call
                     graph (the admit queue is the only legal handoff)
``blocking-under-lock`` no sleeps, socket/queue waits, future results or
                     device syncs inside a ``with <lock>:`` body
``lock-order``       the cross-module lock acquisition graph must be
                     acyclic
``host-sync-hot-path`` no implicit host syncs (``.item()``,
                     ``np.asarray`` / ``int()`` on jitted results,
                     ``block_until_ready``) in poll-loop-reachable code
``retrace-hazard``   no unbounded/unhashable Python values at static
                     positions of jitted callables
``metric-drift``     ``seldon_engine_*`` series must agree across
                     engine_metrics maps, server emitters, tools and docs
``annotation-drift`` ``seldon.io/*`` annotations parsed by the control
                     plane must match the documented tables
``wall-clock``       ``time.time()`` is reserved for named wall anchors;
                     interval/deadline/ordering math uses monotonic time
``parse-error``      a scanned file failed to parse
==================== ====================================================
"""

from .core import Finding, LintResult, load_baseline, run_lint, write_baseline
from .roles import (
    ThreadRoleViolation,
    caller_thread,
    debug_threads_enabled,
    scheduler_only,
)

__all__ = [
    "Finding",
    "LintResult",
    "ThreadRoleViolation",
    "caller_thread",
    "debug_threads_enabled",
    "load_baseline",
    "run_lint",
    "scheduler_only",
    "write_baseline",
]
