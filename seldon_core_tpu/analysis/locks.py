"""Lock discipline: ``blocking-under-lock`` and ``lock-order``.

**blocking-under-lock.** The batcher's poll loop and every request
worker share a handful of mutexes (``_swap_lock``, ``_export_lock``,
``_thread_lock``, the prefix index's ``_lock``, the metrics registry's
``_lock``...). The standing convention is that lock bodies are
pointer/bookkeeping work only — the moment a ``time.sleep``, socket
recv, queue wait, ``Future.result`` or device sync
(``block_until_ready`` / ``device_get``) runs under one, every thread
needing that lock stalls behind I/O, and at production rates that reads
as a tail-latency cliff (or a deadlock when the blocked-on progress
needs the same lock). The rule flags blocking calls lexically inside
``with <lock>:`` bodies, including one level of indirection through a
same-class helper (``with self._lock: self._helper()`` where the helper
blocks).

**lock-order.** Nested acquisitions define a lock-ordering graph: an
edge ``A -> B`` whenever ``B`` is taken while ``A`` is held (directly
nested ``with``, or a self-call made under ``A`` into a method that
takes ``B``, transitively). A cycle in that graph is a latent deadlock —
two threads entering the cycle from different corners stop forever.
Lock identity is scoped per class/module (``ContinuousBatcher:
self._swap_lock``), which matches how every lock in this repo is owned;
cross-object aliasing is out of scope and documented as such.

A ``with`` context counts as a lock when its expression's trailing name
ends in ``lock`` (``self._lock``, ``self._swap_lock``, ``run_lock``) —
the repo's universal naming convention, checked by fixture tests.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import index_classes, iter_functions
from .core import Finding, LintContext, SourceFile

__all__ = ["check_blocking_under_lock", "check_lock_order"]

# attribute names whose call blocks the calling thread
_BLOCK_ATTRS = {
    "recv", "recv_into", "recvfrom", "accept", "connect", "sendall",
    "result", "join", "wait", "acquire", "block_until_ready", "device_get",
}
_QUEUE_ATTRS = {"get", "put"}


def _dotted(node) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _lock_ids(with_node: ast.With, scope: str) -> List[Tuple[str, str]]:
    """``(lock id, display text)`` for each lock-like context item."""
    out = []
    for item in with_node.items:
        expr = item.context_expr
        # `with self._lock.acquire_timeout(...)`-style helpers: look at
        # the called object
        if isinstance(expr, ast.Call):
            expr = expr.func
        text = _dotted(expr)
        if not text:
            continue
        leaf = text.rsplit(".", 1)[-1]
        if leaf.lower().endswith("lock"):
            out.append((f"{scope}:{text}", text))
    return out


def _blocking_reason(call: ast.Call) -> Optional[str]:
    """Why this call blocks, or None."""
    func = call.func
    text = _dotted(func)
    if text == "time.sleep" or text == "sleep":
        return "time.sleep"
    if isinstance(func, ast.Attribute):
        attr = func.attr
        if attr == "join":
            if isinstance(func.value, (ast.Constant, ast.JoinedStr)):
                return None  # str.join
            recv = (_dotted(func.value) or "").lower()
            if recv.rsplit(".", 1)[-1] in ("path", "posixpath", "ntpath"):
                return None  # os.path.join
        if attr in _BLOCK_ATTRS:
            return f".{attr}()"
        if attr in _QUEUE_ATTRS:
            recv = _dotted(func.value) or ""
            leaf = recv.rsplit(".", 1)[-1].lower()
            if "queue" in leaf or leaf in ("q", "_q"):
                return f"queue .{attr}()"
    return None


def _direct_blockers(fn: ast.AST) -> List[Tuple[str, int]]:
    out = []
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            why = _blocking_reason(sub)
            if why:
                out.append((why, sub.lineno))
    return out


def _held_events(fn: ast.AST, scope: str):
    """Yield ``(held locks, statement)`` for statements under >=1 lock, and
    ``(held locks, with_node, new locks)`` acquisition events."""
    acquisitions: List[Tuple[Tuple[Tuple[str, str], ...], ast.With, List[Tuple[str, str]]]] = []
    under: List[Tuple[Tuple[Tuple[str, str], ...], ast.stmt]] = []

    def walk(stmts, held):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # deferred execution: not under this lock
            if isinstance(stmt, ast.With):
                locks = _lock_ids(stmt, scope)
                acquisitions.append((tuple(held), stmt, locks))
                walk(stmt.body, held + list(locks))
                continue
            compound = False
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list):
                    compound = True
                    walk(sub, held)
            for handler in getattr(stmt, "handlers", []) or []:
                compound = True
                walk(handler.body, held)
            # only SIMPLE statements become events: compound bodies were
            # recursed above, and a nested def under a lock runs later —
            # its body is not "under the lock"
            if held and not compound:
                under.append((tuple(held), stmt))
            # if/while TESTS and for ITERS evaluate under the lock even
            # though the statement is compound
            if held and compound:
                for field in ("test", "iter"):
                    expr = getattr(stmt, field, None)
                    if expr is not None:
                        under.append((tuple(held), expr))

    walk(getattr(fn, "body", []), [])
    return acquisitions, under


def check_blocking_under_lock(
    files: List[SourceFile], ctx: LintContext
) -> Iterable[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        # same-class helpers that block directly (one indirection level)
        class_blockers: Dict[str, Dict[str, Tuple[str, int]]] = {}
        for cls in index_classes(sf.tree):
            blockers: Dict[str, Tuple[str, int]] = {}
            for name, info in cls.methods.items():
                direct = _direct_blockers(info.node)
                if direct:
                    blockers[name] = direct[0]
            class_blockers[cls.name] = blockers

        # one pass: which class owns each directly-enclosed function
        owner: Dict[int, str] = {
            id(item): cls.name
            for cls in index_classes(sf.tree)
            for item in cls.node.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        seen: Set[int] = set()
        for fn in iter_functions(sf.tree):
            scope = owner.get(id(fn), sf.rel)
            _, under = _held_events(fn, scope)
            blockers = class_blockers.get(scope, {})
            for held, stmt in under:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call) or id(sub) in seen:
                        continue
                    lock_text = held[-1][1]
                    why = _blocking_reason(sub)
                    if why:
                        seen.add(id(sub))
                        findings.append(sf.finding(
                            "blocking-under-lock", sub,
                            f"{why} inside `with {lock_text}:` — lock "
                            "bodies must be pointer/bookkeeping work; "
                            "every thread needing the lock stalls behind "
                            "this call",
                        ))
                        continue
                    # one level of self-call indirection
                    f = sub.func
                    if (
                        isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                        and f.attr in blockers
                        and f.attr != getattr(fn, "name", None)
                    ):
                        inner_why, inner_line = blockers[f.attr]
                        seen.add(id(sub))
                        findings.append(sf.finding(
                            "blocking-under-lock", sub,
                            f"self.{f.attr}() inside `with {lock_text}:` "
                            f"blocks ({inner_why} at line {inner_line})",
                        ))
    return findings


def check_lock_order(
    files: List[SourceFile], ctx: LintContext
) -> Iterable[Finding]:
    # edge: (from_lock, to_lock) -> (file, with node, description)
    edges: Dict[Tuple[str, str], Tuple[SourceFile, ast.AST, str]] = {}

    for sf in files:
        if sf.tree is None:
            continue
        module_scope = sf.rel
        classes = index_classes(sf.tree)
        # per class: method -> set of locks it (transitively) acquires
        for cls in classes:
            direct: Dict[str, List[Tuple[str, str]]] = {}
            events_by_method = {}
            for name, info in cls.methods.items():
                events_by_method[name] = _held_events(info.node, cls.name)
                direct[name] = [
                    lock
                    for _, _, locks in events_by_method[name][0]
                    for lock in locks
                ]
            # fixpoint: locks acquired transitively through self-calls
            trans: Dict[str, Set[str]] = {
                n: {lid for lid, _ in direct[n]} for n in cls.methods
            }
            changed = True
            while changed:
                changed = False
                for name, info in cls.methods.items():
                    for callee in info.self_calls:
                        if callee in trans and not trans[callee] <= trans[name]:
                            trans[name] |= trans[callee]
                            changed = True
            for name, info in cls.methods.items():
                acqs, under = events_by_method[name]
                for held, node, locks in acqs:
                    for h_id, h_text in held:
                        for l_id, l_text in locks:
                            edges.setdefault((h_id, l_id), (
                                sf, node,
                                f"{cls.name}.{name} takes {l_text} while "
                                f"holding {h_text}",
                            ))
                # calls made while holding a lock pull in the callee's
                # transitive acquisitions — INCLUDING re-acquisition of
                # the held lock itself (h_id == l_id lands on the a == b
                # branch below: threading.Lock is not re-entrant, and
                # unlike lexical with-nesting the deadlock hides behind
                # the call)
                for held, stmt in under:
                    for sub in ast.walk(stmt):
                        if not (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and isinstance(sub.func.value, ast.Name)
                            and sub.func.value.id == "self"
                        ):
                            continue
                        callee = sub.func.attr
                        for l_id in trans.get(callee, ()):
                            for h_id, h_text in held:
                                edges.setdefault((h_id, l_id), (
                                    sf, sub,
                                    f"{cls.name}.{name} calls "
                                    f"self.{callee}() (which takes "
                                    f"{l_id.split(':', 1)[1]}) while "
                                    f"holding {h_text}",
                                ))
        # module-level functions (rare; scoped by file)
        class_fns = {
            id(item)
            for cls in classes
            for item in cls.node.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for fn in iter_functions(sf.tree):
            if id(fn) in class_fns:
                continue
            acqs, _ = _held_events(fn, module_scope)
            for held, node, locks in acqs:
                for h_id, h_text in held:
                    for l_id, l_text in locks:
                        edges.setdefault((h_id, l_id), (
                            sf, node,
                            f"{getattr(fn, 'name', '?')} takes {l_text} "
                            f"while holding {h_text}",
                        ))

    # immediate self-deadlock: with L: ... with L: (non-reentrant Lock)
    findings: List[Finding] = []
    graph: Dict[str, Set[str]] = {}
    for (a, b), (sf, node, desc) in sorted(edges.items()):
        if a == b:
            findings.append(sf.finding(
                "lock-order", node,
                f"re-acquisition of {a.split(':', 1)[1]} while already "
                f"held ({desc}) — threading.Lock is not re-entrant",
            ))
            continue
        graph.setdefault(a, set()).add(b)

    # cycle detection: DFS with coloring; report each cycle once
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[str, int] = {n: WHITE for n in graph}
    reported: Set[frozenset] = set()

    def dfs(node: str, stack: List[str]):
        color[node] = GREY
        stack.append(node)
        for nxt in sorted(graph.get(node, ())):
            if color.get(nxt, WHITE) == GREY:
                cycle = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cycle)
                if key not in reported:
                    reported.add(key)
                    sf, anchor, desc = edges[(node, nxt)]
                    pretty = " -> ".join(c.split(":", 1)[1] for c in cycle)
                    findings.append(sf.finding(
                        "lock-order", anchor,
                        f"lock acquisition cycle {pretty} ({desc}); two "
                        "threads entering from different corners deadlock",
                    ))
            elif color.get(nxt, WHITE) == WHITE:
                dfs(nxt, stack)
        stack.pop()
        color[node] = BLACK

    for n in sorted(graph):
        if color.get(n, WHITE) == WHITE:
            dfs(n, [])
    return findings
