"""Contract drift: ``metric-drift``, ``annotation-drift``, ``wall-clock``.

**metric-drift.** The ``seldon_engine_*`` vocabulary lives in four
places that historically drifted independently: the registry's mapping
tables in ``graph/engine_metrics.py`` (``_STEP_PHASES``,
``_KV_TRANSFER``, ``_RECOVERY``, ``_RECOVERY_GAUGES``, ``_SLO_TIMERS``,
``_FUSED``, ``_DEVICE``, ``_DEVICE_GAUGES``, ``_SLO_BURN``),
the servers that emit the ``gen_*`` keys those tables consume, the
tools that parse the published series (``flight_report``,
``gen_arch_numbers``), and the operator docs. The rule re-derives the
table from source and cross-checks all four:

* every mapped ``gen_*`` input key is actually emitted somewhere,
* every first-class ``seldon_engine_*`` series named in package code is
  documented in ``docs/*.md`` (its *full* name — shorthand like
  ``_bytes`` does not count, because operators copy metric names into
  PromQL),
* every ``seldon_engine_*`` name the docs mention exists in code (a
  rename must update the docs in the same PR),
* a ``seldon_engine_*`` literal in ``tools/`` must exist in the package
  (drift there makes the published numbers lie).

**annotation-drift.** Same pact for ``seldon.io/*`` annotations between
the controlplane/graph parsers and the docs tables, both directions.
Keys ending in ``-`` (e.g. the ``seldon.io/engine-env-`` prefix) match
on the prefix base.

**wall-clock.** ``time.time()`` is reserved for *named wall anchors* —
an assignment whose target contains ``wall`` (``submit_wall_us``,
``_WALL_ANCHOR_US``). Everything else must use ``time.monotonic()``
(intervals, deadlines, backoff, ordering) or the monotonic-anchored
:func:`seldon_core_tpu.tracing.wall_us` (event timestamps): the wall
clock steps under NTP corrections, and at production rates a one-second
step silently corrupts every deadline and every recorded interval in
flight. Genuine wall-time sites (persisted checkpoint stamps,
human-facing event trails) carry inline suppressions with their
justification.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, LintContext, SourceFile

__all__ = [
    "check_annotation_drift",
    "check_metric_drift",
    "check_wall_clock",
]

_MAP_NAMES = {
    "_STEP_PHASES", "_KV_TRANSFER", "_RECOVERY", "_RECOVERY_GAUGES",
    "_SLO_TIMERS", "_FUSED", "_DEVICE", "_DEVICE_GAUGES", "_SLO_BURN",
}
# built by concatenation so these source files never match their own
# scanning patterns
_METRIC_RE = re.compile("seldon_engine" + "_[a-z0-9_]+")
_GEN_KEY_RE = re.compile("gen" + "_[a-z0-9_]+")
_ANNOT_RE = re.compile(r"(?<![a-z0-9.])seldon\.io/[a-z0-9-]+")


def _str_constants(tree: ast.AST) -> Iterable[Tuple[str, int]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node.lineno


def _docs_tokens(ctx: LintContext, pattern: re.Pattern):
    """``name -> (docfile rel-ish path, lineno, line text)`` first sighting."""
    out: Dict[str, Tuple[str, int, str]] = {}
    for path in ctx.docs_files:
        try:
            text = ctx.doc_text(path)
        except OSError:
            continue
        rel = _rel(ctx, path)
        for i, line in enumerate(text.splitlines(), start=1):
            for m in pattern.finditer(line):
                tok = m.group(0)
                # `seldon_engine_kv_transfer_*` / `..._{slabs,bytes}`-style
                # family shorthand is not a name: skip in both directions.
                # (A name followed by a label set — `..._retries{unit=...}`
                # — does NOT end with `_` and still counts.)
                if tok.endswith("_") and line[m.end():m.end() + 1] in ("*", "{"):
                    continue
                out.setdefault(tok.rstrip("_"), (rel, i, line.strip()))
    return out


def _rel(ctx: LintContext, path: str) -> str:
    import os

    try:
        return os.path.relpath(path, ctx.root).replace(os.sep, "/")
    except ValueError:
        return path


def _is_tools_file(sf: SourceFile) -> bool:
    return sf.rel.startswith("tools/") or "/tools/" in sf.rel


def check_metric_drift(
    files: List[SourceFile], ctx: LintContext
) -> Iterable[Finding]:
    findings: List[Finding] = []
    # map entries: (gen key, output name, file, lineno)
    entries: List[Tuple[str, Optional[str], SourceFile, int]] = []
    map_files: Set[str] = set()
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Dict
            ):
                continue
            names = {
                t.id for t in node.targets if isinstance(t, ast.Name)
            }
            if not names & _MAP_NAMES:
                continue
            map_files.add(sf.rel)
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                    continue
                out_name = None
                cands = [v] + (list(v.elts) if isinstance(v, ast.Tuple) else [])
                for cand in cands:
                    if (
                        isinstance(cand, ast.Constant)
                        and isinstance(cand.value, str)
                        and _METRIC_RE.fullmatch(cand.value)
                    ):
                        out_name = cand.value
                        break
                entries.append((k.value, out_name, sf, k.lineno))

    # gen_* keys emitted anywhere OUTSIDE the mapping file(s)
    emitted: Set[str] = set()
    # package-defined and tools-referenced seldon_engine_* literals
    defined: Dict[str, Tuple[SourceFile, int]] = {}
    tool_refs: List[Tuple[str, SourceFile, int]] = []
    any_package = False
    for sf in files:
        if sf.tree is None:
            continue
        is_tool = _is_tools_file(sf)
        if not is_tool:
            any_package = True
        for value, lineno in _str_constants(sf.tree):
            if _GEN_KEY_RE.fullmatch(value) and sf.rel not in map_files:
                emitted.add(value)
            if _METRIC_RE.fullmatch(value):
                if is_tool:
                    tool_refs.append((value, sf, lineno))
                else:
                    defined.setdefault(value, (sf, lineno))

    doc_metrics = _docs_tokens(ctx, _METRIC_RE)

    for gen_key, out_name, sf, lineno in entries:
        if gen_key not in emitted:
            findings.append(Finding(
                "metric-drift", sf.rel, lineno, 0,
                f"mapped metric key '{gen_key}' is emitted by no server — "
                "the first-class series it feeds will stay empty "
                "(renamed emitter?)",
                sf.line_text(lineno),
            ))
        if out_name is None:
            findings.append(Finding(
                "metric-drift", sf.rel, lineno, 0,
                f"mapping for '{gen_key}' carries no seldon_engine_* "
                "output name",
                sf.line_text(lineno),
            ))
    if ctx.docs_files:
        for name, (sf, lineno) in sorted(defined.items()):
            if name not in doc_metrics:
                findings.append(Finding(
                    "metric-drift", sf.rel, lineno, 0,
                    f"metric '{name}' is not documented in docs/*.md by "
                    "its full name — operators copy metric names into "
                    "PromQL; shorthand does not scrape",
                    sf.line_text(lineno),
                ))
        if any_package:
            for name, (doc, lineno, text) in sorted(doc_metrics.items()):
                if name not in defined:
                    findings.append(Finding(
                        "metric-drift", doc, lineno, 0,
                        f"docs document metric '{name}' but no package "
                        "code defines it (renamed series?)",
                        text,
                    ))
    if any_package:
        for name, sf, lineno in tool_refs:
            if name not in defined:
                findings.append(Finding(
                    "metric-drift", sf.rel, lineno, 0,
                    f"tool references metric '{name}' that no package "
                    "code defines — published numbers would lie",
                    sf.line_text(lineno),
                ))
    return findings


def _annot_base(key: str) -> str:
    return key.rstrip("-")


def check_annotation_drift(
    files: List[SourceFile], ctx: LintContext
) -> Iterable[Finding]:
    findings: List[Finding] = []
    if not ctx.docs_files:
        return findings
    code_keys: Dict[str, Tuple[SourceFile, int]] = {}
    any_package = False
    for sf in files:
        if sf.tree is None or _is_tools_file(sf):
            continue
        any_package = True
        for value, lineno in _str_constants(sf.tree):
            if _ANNOT_RE.fullmatch(value):
                # trailing-dash keys are prefix families
                # (seldon.io/engine-env-): compare on the dash-stripped base
                code_keys.setdefault(_annot_base(value), (sf, lineno))

    doc_keys = _docs_tokens(ctx, _ANNOT_RE)
    doc_bases = {_annot_base(k) for k in doc_keys}
    # docs may document a prefix family as `seldon.io/engine-env-<NAME>`;
    # count any documented key that starts with a code prefix base
    for base, (sf, lineno) in sorted(code_keys.items()):
        documented = base in doc_bases or any(
            d.startswith(base + "-") or d == base for d in doc_bases
        )
        if not documented:
            findings.append(Finding(
                "annotation-drift", sf.rel, lineno, 0,
                f"annotation '{base}' is parsed by the code but appears "
                "in no docs/*.md table",
                sf.line_text(lineno),
            ))
    if any_package:
        for key, (doc, lineno, text) in sorted(doc_keys.items()):
            base = _annot_base(key)
            known = base in code_keys or any(
                base.startswith(c + "-") for c in code_keys
            )
            if not known:
                findings.append(Finding(
                    "annotation-drift", doc, lineno, 0,
                    f"docs document annotation '{base}' that no code "
                    "parses (renamed?)",
                    text,
                ))
    return findings


def _is_time_time(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "time":
        return isinstance(f.value, ast.Name) and f.value.id == "time"
    return isinstance(f, ast.Name) and f.id == "time"


def check_wall_clock(
    files: List[SourceFile], ctx: LintContext
) -> Iterable[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        allowed: Set[int] = set()
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            anchors = False
            for tgt in node.targets:
                text = ""
                t = tgt
                while isinstance(t, ast.Attribute):
                    text = t.attr
                    break
                if isinstance(t, ast.Name):
                    text = t.id
                if "wall" in text.lower():
                    anchors = True
            if anchors:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call) and _is_time_time(sub):
                        allowed.add(id(sub))
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and _is_time_time(node)
                and id(node) not in allowed
            ):
                findings.append(sf.finding(
                    "wall-clock", node,
                    "time.time() outside a *wall* anchor assignment: "
                    "interval/deadline/ordering math must use "
                    "time.monotonic(); event timestamps should go "
                    "through the monotonic-anchored tracing.wall_us()",
                ))
    return findings
