"""OpenAPI 3.0 documents for the external (engine) and internal
(microservice wrapper) HTTP APIs.

The reference ships hand-written specs (reference: openapi/
engine.oas3.json, openapi/wrapper.oas3.json, openapi/apife.oas3.json);
here the documents are generated from one schema table and served live at
``GET /openapi.json`` on both servers, RECONCILED against the server's
registered routes (undocumented routes appear with a generic entry,
unserved documented paths are dropped) — so the published document cannot
drift from the routes that actually exist.
"""

from __future__ import annotations

from typing import Any, Dict

SELDON_MESSAGE_SCHEMA: Dict[str, Any] = {
    "type": "object",
    "description": "SeldonMessage (protos/prediction.proto): Status + Meta "
    "+ one payload of data/binData/strData/jsonData. The `raw` encoding "
    "(dtype, shape, little-endian bytes) also crosses REST as a binary "
    "protobuf body with Content-Type application/x-protobuf.",
    "properties": {
        "status": {
            "type": "object",
            "properties": {
                "code": {"type": "integer"},
                "info": {"type": "string"},
                "reason": {"type": "string"},
                "status": {"type": "string"},
            },
        },
        "meta": {
            "type": "object",
            "properties": {
                "puid": {"type": "string"},
                "tags": {"type": "object"},
                "routing": {"type": "object", "additionalProperties": {"type": "integer"}},
                "requestPath": {"type": "object", "additionalProperties": {"type": "string"}},
                "metrics": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "properties": {
                            "key": {"type": "string"},
                            "type": {"type": "string", "enum": ["COUNTER", "GAUGE", "TIMER"]},
                            "value": {"type": "number"},
                        },
                    },
                },
            },
        },
        "data": {
            "type": "object",
            "properties": {
                "names": {"type": "array", "items": {"type": "string"}},
                "tensor": {
                    "type": "object",
                    "properties": {
                        "shape": {"type": "array", "items": {"type": "integer"}},
                        "values": {"type": "array", "items": {"type": "number"}},
                    },
                },
                "ndarray": {"type": "array", "items": {}},
                "raw": {
                    "type": "object",
                    "properties": {
                        "dtype": {"type": "string"},
                        "shape": {"type": "array", "items": {"type": "integer"}},
                        "data": {"type": "string", "format": "byte"},
                    },
                },
            },
        },
        "binData": {"type": "string", "format": "byte"},
        "strData": {"type": "string"},
        "jsonData": {},
    },
}

FEEDBACK_SCHEMA: Dict[str, Any] = {
    "type": "object",
    "properties": {
        "request": {"$ref": "#/components/schemas/SeldonMessage"},
        "response": {"$ref": "#/components/schemas/SeldonMessage"},
        "reward": {"type": "number"},
        "truth": {"$ref": "#/components/schemas/SeldonMessage"},
    },
}

MESSAGE_LIST_SCHEMA: Dict[str, Any] = {
    "type": "object",
    "description": "SeldonMessageList: combiner input (one message per child branch).",
    "properties": {
        "seldonMessages": {
            "type": "array",
            "items": {"$ref": "#/components/schemas/SeldonMessage"},
        }
    },
}


def _message_op(
    summary: str, tag: str, request_schema: str = "SeldonMessage"
) -> Dict[str, Any]:
    body = {
        "required": True,
        "content": {
            "application/json": {
                "schema": {"$ref": f"#/components/schemas/{request_schema}"}
            },
            "application/x-protobuf": {
                "schema": {"type": "string", "format": "binary"}
            },
        },
    }
    return {
        "summary": summary,
        "tags": [tag],
        "requestBody": body,
        "responses": {
            "200": {
                "description": "SeldonMessage response",
                "content": {
                    "application/json": {
                        "schema": {"$ref": "#/components/schemas/SeldonMessage"}
                    },
                    "application/x-protobuf": {
                        "schema": {"type": "string", "format": "binary"}
                    },
                },
            },
            "400": {"description": "malformed payload"},
            "503": {"description": "paused or graph not ready"},
        },
    }


def _probe_op(summary: str, tag: str) -> Dict[str, Any]:
    return {
        "summary": summary,
        "tags": [tag],
        "responses": {"200": {"description": "ok"}},
    }


def _base(title: str, description: str) -> Dict[str, Any]:
    return {
        "openapi": "3.0.3",
        "info": {"title": title, "version": "1.0.0", "description": description},
        "components": {
            "schemas": {
                "SeldonMessage": SELDON_MESSAGE_SCHEMA,
                "Feedback": FEEDBACK_SCHEMA,
                "SeldonMessageList": MESSAGE_LIST_SCHEMA,
            }
        },
    }


def _reconcile(doc: Dict[str, Any], served_paths) -> Dict[str, Any]:
    """Make the document match the routes a server REALLY registered:
    drop documented paths the server doesn't serve, add generic entries
    for served paths the table doesn't know — so a new add_route can
    never silently drift out of the published spec."""
    if served_paths is None:
        return doc
    served = set(served_paths)
    doc["paths"] = {p: op for p, op in doc["paths"].items() if p in served}
    for p in sorted(served - set(doc["paths"])):
        # method/shape unknown: advertise both verbs with no required body
        # rather than inventing a POST-only SeldonMessage contract
        unknown = {
            "summary": f"(undocumented route {p})",
            "tags": ["extra"],
            "responses": {"200": {"description": "see server source"}},
        }
        doc["paths"][p] = {"get": dict(unknown), "post": dict(unknown)}
    return doc


def engine_spec(served_paths=None) -> Dict[str, Any]:
    """External API of the graph engine (reference: openapi/engine.oas3.json).

    ``served_paths``: the serving app's registered routes; when given,
    the document is reconciled against them (see _reconcile). The native
    C++ engine serves the predictions/probes/lifecycle/metrics subset of
    these routes — not feedback, /traces, or /openapi.json.
    """
    doc = _base(
        "seldon-core-tpu engine API",
        "External data-plane API of the inference-graph engine "
        "(graph/service.py; native/engine.cpp serves the predictions + "
        "probe + lifecycle + metrics subset).",
    )
    feedback_op = _message_op("Send reward feedback through the graph", "engine")
    feedback_op["requestBody"]["content"]["application/json"]["schema"] = {
        "$ref": "#/components/schemas/Feedback"
    }
    predict_op = _message_op("Run the inference graph", "engine")
    doc["paths"] = {
        "/api/v0.1/predictions": {"post": predict_op},
        "/api/v1.0/predictions": {"post": predict_op},
        "/predict": {"post": predict_op},
        "/api/v0.1/feedback": {"post": feedback_op},
        "/api/v1.0/feedback": {"post": feedback_op},
        **{
            p: {
                "post": {
                    "summary": "SSE token streaming (GENERATE_SERVER graphs)",
                    "tags": ["engine"],
                    "requestBody": {
                        "required": True,
                        "content": {"application/json": {"schema": {}}},
                    },
                    "responses": {
                        "200": {
                            "description": "text/event-stream of "
                            '`data: {"tokens": [...]}` events, ending with '
                            '`data: {"done": true, "tokens": [...]}`',
                        },
                        "501": {
                            "description": "graph is not a single generate server"
                        },
                    },
                }
            }
            for p in ("/api/v0.1/generate", "/api/v1.0/generate")
        },
        "/ready": {"get": _probe_op("Readiness (graph-gated)", "probes")},
        "/live": {"get": _probe_op("Liveness", "probes")},
        "/ping": {"get": _probe_op("Ping", "probes")},
        "/pause": {"get": _probe_op("Reject new work (drain step 1)", "lifecycle")},
        "/unpause": {"get": _probe_op("Accept work again", "lifecycle")},
        "/inflight": {"get": _probe_op("Live-request gauge (drain step 2)", "lifecycle")},
        "/prometheus": {"get": _probe_op("Prometheus metrics", "observability")},
        "/metrics": {"get": _probe_op("Prometheus metrics", "observability")},
        "/traces": {"get": _probe_op(
            "Jaeger-JSON trace export (?operation=&limit=&since_us=)",
            "observability")},
        "/flightrecorder": {"get": _probe_op(
            "Scheduler flight-recorder dump (generate graphs; ?limit=)",
            "observability")},
        "/openapi.json": {"get": _probe_op("This document", "meta")},
    }
    return _reconcile(doc, served_paths)


def wrapper_spec(served_paths=None) -> Dict[str, Any]:
    """Internal API of a model microservice (reference: openapi/wrapper.oas3.json)."""
    doc = _base(
        "seldon-core-tpu microservice API",
        "Internal per-component API the engine calls (wrapper.py routes; "
        "the gRPC services mirror these one-to-one).",
    )
    doc["paths"] = {
        path: {"post": _message_op(summary, "component", request_schema=schema)}
        for path, summary, schema in [
            ("/predict", "Model predict", "SeldonMessage"),
            ("/api/v0.1/predictions", "Model predict", "SeldonMessage"),
            ("/api/v1.0/predictions", "Model predict", "SeldonMessage"),
            ("/transform-input", "Input transformer", "SeldonMessage"),
            ("/transform-output", "Output transformer", "SeldonMessage"),
            ("/route", "Router: pick a child branch", "SeldonMessage"),
            ("/aggregate", "Combiner: merge child outputs", "SeldonMessageList"),
            ("/send-feedback", "Reward feedback", "Feedback"),
            ("/explain", "Explanation (integrated gradients)", "SeldonMessage"),
            ("/api/v1.0/explain", "Explanation (integrated gradients)", "SeldonMessage"),
        ]
    }
    doc["paths"]["/health/status"] = {
        "get": _probe_op("Model health (calls the component's health hook)", "probes")
    }
    for path, summary in [
        ("/live", "Liveness"),
        ("/ready", "Readiness (503 while paused)"),
        ("/pause", "Reject new work"),
        ("/unpause", "Accept work again"),
    ]:
        doc["paths"][path] = {"get": _probe_op(summary, "probes")}
    doc["paths"]["/openapi.json"] = {"get": _probe_op("This document", "meta")}
    return _reconcile(doc, served_paths)
