"""Streaming batch scorer: pipe a dataset through a deployment.

Counterpart of the reference's Kafka streaming path (reference:
kafka/kafka.json + the stream-processing deployment pattern its docs
describe): instead of running a broker, the TPU-native design treats
batch scoring as a bounded-concurrency PIPELINE — read records from a
JSONL/CSV stream (file or stdin), keep N requests in flight against the
engine/gateway so the device-side micro-batcher always has work, and
write one JSONL result per record in INPUT ORDER. Failures are recorded
per-record, never dropped.

CLI::

    seldon-tpu-batch http://HOST:8000 --input data.jsonl --output out.jsonl \
        [--format jsonl|csv] [--concurrency 16] [--batch-rows 8]
        [--path /api/v0.1/predictions] [--binary]

Input records: JSONL — either a full SeldonMessage dict or a bare list
(one data row); CSV — one row per line. ``--batch-rows`` fuses that many
input rows per request (client-side batching on top of the engine's
micro-batching).
"""

from __future__ import annotations

import argparse
import asyncio
import csv
import io
import json
import logging
import sys
from typing import Any, Dict, Iterable, Iterator, List, Optional, TextIO

logger = logging.getLogger(__name__)


PARSE_ERROR = "_parse_error"


def read_records(stream: TextIO, fmt: str) -> Iterator[Dict[str, Any]]:
    """Yield SeldonMessage-shaped dicts from a JSONL or CSV stream. A
    malformed line yields a {PARSE_ERROR: ...} marker instead of aborting
    the whole run — per-record failure is the module's contract."""
    if fmt == "csv":
        for row in csv.reader(stream):
            if not row:
                continue
            try:
                yield {"data": {"ndarray": [[float(x) for x in row]]}}
            except ValueError as e:
                yield {PARSE_ERROR: f"bad csv row {row!r}: {e}"}
        return
    for line in stream:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            yield {PARSE_ERROR: f"bad json line: {e}"}
            continue
        if isinstance(rec, list):
            rec = {"data": {"ndarray": [rec]}}
        yield rec


def fuse_rows(records: Iterable[Dict[str, Any]], batch_rows: int) -> Iterator[Dict[str, Any]]:
    """Fuse consecutive bare-ndarray records into one request of up to
    ``batch_rows`` rows. Records carrying meta/strData/jsonData — or a
    different ``names`` list than the pending batch — pass through / start
    a new batch, so nothing is silently dropped. Yields
    {"message", "count"} where count is the number of INPUT RECORDS fused.
    """
    pending: List[List[Any]] = []
    pending_names: Optional[List[str]] = None

    def flush():
        nonlocal pending, pending_names
        if pending:
            data: Dict[str, Any] = {"ndarray": pending}
            if pending_names:
                data["names"] = pending_names
            out = {"message": {"data": data}, "count": len(pending)}
            pending, pending_names = [], None
            return out
        return None

    for rec in records:
        data = rec.get("data") or {}
        names = data.get("names") or None
        fusable = (
            PARSE_ERROR not in rec
            and set(rec.keys()) <= {"data"}
            and set(data.keys()) <= {"ndarray", "names"}
            and isinstance(data.get("ndarray"), list)
            and len(data["ndarray"]) == 1
        )
        if fusable and batch_rows > 1:
            if pending and names != pending_names:
                yield flush()
            pending.append(data["ndarray"][0])
            pending_names = names
            if len(pending) >= batch_rows:
                yield flush()
        else:
            out = flush()
            if out:
                yield out
            yield {"message": rec, "count": 1}
    out = flush()
    if out:
        yield out


class BatchScorer:
    def __init__(
        self,
        target: str,
        path: str = "/api/v0.1/predictions",
        concurrency: int = 16,
        binary: bool = False,
        timeout_s: float = 60.0,
    ):
        import threading
        from concurrent.futures import ThreadPoolExecutor
        from urllib.parse import urlparse

        self.target = target.rstrip("/")
        self.path = path
        self.concurrency = max(1, int(concurrency))
        self.binary = binary
        self.timeout_s = timeout_s
        self.stats = {"requests": 0, "rows": 0, "failures": 0}
        parsed = urlparse(self.target if "//" in self.target else f"http://{self.target}")
        self._host = parsed.hostname
        self._port = parsed.port or (443 if parsed.scheme == "https" else 80)
        self._tls = parsed.scheme == "https"
        # own pool sized to the requested concurrency (the loop's default
        # executor is cpu+4 threads — it would silently cap parallelism),
        # with one KEEP-ALIVE http connection per worker thread
        self._pool = ThreadPoolExecutor(
            max_workers=self.concurrency, thread_name_prefix="batch-score"
        )
        self._local = threading.local()

    def _connection(self):
        import http.client

        conn = getattr(self._local, "conn", None)
        if conn is None:
            cls = http.client.HTTPSConnection if self._tls else http.client.HTTPConnection
            conn = cls(self._host, self._port, timeout=self.timeout_s)
            self._local.conn = conn
        return conn

    async def _post(self, message: Dict[str, Any]) -> Dict[str, Any]:
        from .payload import json_to_proto, jsonable, proto_to_json
        from .proto import prediction_pb2 as pb

        if self.binary:
            body = json_to_proto(message).SerializeToString()
            headers = {"Content-Type": "application/x-protobuf"}
        else:
            body = json.dumps(jsonable(message)).encode()
            headers = {"Content-Type": "application/json"}

        def send():
            conn = self._connection()
            try:
                conn.request("POST", self.path, body, headers)
                resp = conn.getresponse()
                payload = resp.read()
            except Exception:
                # a broken keep-alive connection must not poison the thread
                conn.close()
                self._local.conn = None
                raise
            if resp.status >= 400:
                raise RuntimeError(f"HTTP {resp.status}: {payload[:200]!r}")
            if (resp.headers.get("Content-Type") or "").startswith("application/x-protobuf"):
                return jsonable(proto_to_json(pb.SeldonMessage.FromString(payload)))
            return json.loads(payload)

        return await asyncio.get_running_loop().run_in_executor(self._pool, send)

    @staticmethod
    def _split_records(first_record: int, count: int, out: Dict[str, Any]) -> List[Dict]:
        """One output line per INPUT RECORD: split a fused response's data
        rows back to the records they came from."""
        if count == 1:
            return [{"index": first_record, "response": out}]
        data = out.get("data") or {}
        rows = data.get("ndarray")
        if isinstance(rows, list) and len(rows) == count:
            records = []
            for i, row in enumerate(rows):
                rec_out = dict(out)
                rec_out["data"] = {**data, "ndarray": [row]}
                records.append({"index": first_record + i, "response": rec_out})
            return records
        # unsplittable response shape: attribute the whole response to
        # every record rather than silently misaligning the output
        return [
            {"index": first_record + i, "response": out, "fused_rows": count}
            for i in range(count)
        ]

    async def run(self, requests: Iterable[Dict[str, Any]], out_stream: TextIO) -> Dict[str, Any]:
        """Bounded-concurrency pipeline; output is ONE JSONL line per input
        record, in input-record order."""
        sem = asyncio.Semaphore(self.concurrency)
        results: Dict[int, List[Dict[str, Any]]] = {}
        next_write = 0
        write_lock = asyncio.Lock()

        async def score(req_idx: int, first_record: int, item: Dict[str, Any]):
            nonlocal next_write
            count = item["count"]
            parse_err = item["message"].get(PARSE_ERROR)
            async with sem:
                if parse_err is not None:
                    records = [{"index": first_record, "error": parse_err}]
                    self.stats["failures"] += 1
                else:
                    try:
                        out = await self._post(item["message"])
                        records = self._split_records(first_record, count, out)
                        self.stats["rows"] += count
                    except Exception as e:  # noqa: BLE001 - record, don't die
                        records = [
                            {"index": first_record + i, "error": f"{type(e).__name__}: {e}"}
                            for i in range(count)
                        ]
                        self.stats["failures"] += 1
                    self.stats["requests"] += 1
            async with write_lock:
                results[req_idx] = records
                while next_write in results:
                    for rec in results.pop(next_write):
                        out_stream.write(json.dumps(rec) + "\n")
                    next_write += 1

        # pull the (possibly blocking: stdin, slow producers) iterator on a
        # reader thread so in-flight requests proceed WHILE records stream in
        loop = asyncio.get_running_loop()
        it = iter(requests)
        _END = object()

        def pull():
            try:
                return next(it)
            except StopIteration:
                return _END

        tasks = []
        record_base = 0
        req_idx = 0
        while True:
            item = await loop.run_in_executor(None, pull)
            if item is _END:
                break
            # backpressure: do not materialise the whole dataset as tasks
            while len(tasks) >= self.concurrency * 4:
                done, pending = await asyncio.wait(
                    tasks, return_when=asyncio.FIRST_COMPLETED
                )
                tasks = list(pending)
            tasks.append(
                asyncio.ensure_future(score(req_idx, record_base, item))
            )
            record_base += item["count"]
            req_idx += 1
        if tasks:
            await asyncio.gather(*tasks)
        out_stream.flush()
        return dict(self.stats)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser("seldon-tpu-batch")
    parser.add_argument("target", help="http://host:port of an engine/gateway")
    parser.add_argument("--input", default="-", help="JSONL/CSV file ('-' = stdin)")
    parser.add_argument("--output", default="-", help="JSONL output ('-' = stdout)")
    parser.add_argument("--format", choices=("jsonl", "csv"), default="jsonl")
    parser.add_argument("--path", default="/api/v0.1/predictions")
    parser.add_argument("--concurrency", type=int, default=16)
    parser.add_argument("--batch-rows", type=int, default=1)
    parser.add_argument("--binary", action="store_true",
                        help="binary protobuf bodies (raw tensors, no b64)")
    parser.add_argument("--timeout", type=float, default=60.0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    in_stream = sys.stdin if args.input == "-" else open(args.input)
    out_stream = sys.stdout if args.output == "-" else open(args.output, "w")
    scorer = BatchScorer(
        args.target, path=args.path, concurrency=args.concurrency,
        binary=args.binary, timeout_s=args.timeout,
    )
    try:
        stats = asyncio.run(
            scorer.run(
                fuse_rows(read_records(in_stream, args.format), args.batch_rows),
                out_stream,
            )
        )
    finally:
        if in_stream is not sys.stdin:
            in_stream.close()
        if out_stream is not sys.stdout:
            out_stream.close()
    print(json.dumps(stats), file=sys.stderr)


if __name__ == "__main__":
    main()
