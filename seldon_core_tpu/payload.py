"""Marshaling between wire payloads and device arrays.

TPU-first replacement for the reference's conversion layer
(reference: python/seldon_core/utils.py:17-566). The reference round-trips
every request through ``repeated double`` protos or JSON lists into numpy
(reference: python/seldon_core/utils.py:147-183) — the #1 serving overhead.
Here the preferred encoding is ``RawTensor`` (dtype + shape + LE bytes):
decode is a single ``np.frombuffer`` view (zero copy on the host) and one
``jax.device_put`` to land in HBM; encode from a ``jax.Array`` is one
device-to-host DMA into a bytes object.

Three wire encodings are kept for reference compatibility:
  * ``tensor``  — shape + double values (reference: proto/prediction.proto:30-33)
  * ``ndarray`` — nested JSON lists  (reference: proto/prediction.proto:36)
  * ``raw``     — the TPU-native zero-copy path (new)
plus the non-tensor payloads ``binData`` / ``strData`` / ``jsonData``.

JSON wire format is the canonical protobuf JSON mapping of ``SeldonMessage``
(camelCase keys, e.g. ``binData``), so REST and gRPC bodies transcode 1:1 —
the same property the reference relied on its vendored JsonFormat for
(reference: engine/src/main/java/io/seldon/engine/pb/JsonFormat.java).
"""

from __future__ import annotations

import base64
import json
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

try:  # ml_dtypes ships with jax; gives numpy bfloat16/fp8 dtypes.
    import ml_dtypes

    _EXTENDED_DTYPES = {
        "bfloat16": np.dtype(ml_dtypes.bfloat16),
        "float8_e4m3fn": np.dtype(ml_dtypes.float8_e4m3fn),
        "float8_e5m2": np.dtype(ml_dtypes.float8_e5m2),
    }
except ImportError:  # pragma: no cover - ml_dtypes is a jax dependency
    _EXTENDED_DTYPES = {}

from .proto import prediction_pb2 as pb

JsonDict = Dict[str, Any]
ArrayLike = Any  # np.ndarray | jax.Array


class PayloadError(ValueError):
    """Malformed wire payload (maps to HTTP 400 / gRPC INVALID_ARGUMENT)."""


DEFAULT_MAX_DECODED_BYTES = 512 * 1024 * 1024


def max_decoded_bytes(default: int = DEFAULT_MAX_DECODED_BYTES) -> int:
    """Server-side ceiling on the *decoded* size of compressed tensor
    encodings (``zlib``, ``jpeg-rows``). The REST/gRPC body caps bound the
    wire bytes, but the decoded size is declared by the client in
    ``RawTensor.shape`` — a <=64MB zlib body can legally inflate ~1000:1,
    so the shape-declared size must be checked against a server-side limit
    *before* any decompression happens. ``SELDON_MAX_DECODED_BYTES`` env
    overrides the 512MiB default."""
    import os

    try:
        v = int(os.environ["SELDON_MAX_DECODED_BYTES"])
        if v > 0:
            return v
    except (KeyError, ValueError):
        pass
    return default


def _declared_nbytes(shape, dtype: np.dtype) -> int:
    """Byte size a client-declared shape claims, in exact Python ints —
    np.prod wraps at int64, which would let a huge shape slip past the
    cap below and surface as an uncaught OverflowError downstream."""
    import math

    dims = [int(s) for s in shape]
    if any(s < 0 for s in dims):
        raise PayloadError(f"negative dimension in shape {tuple(shape)}")
    return math.prod(dims) * dtype.itemsize if dims else dtype.itemsize


def _check_decoded_size(expected: int, shape, dtype_str: str) -> None:
    cap = max_decoded_bytes()
    if expected > cap:
        raise PayloadError(
            f"decoded tensor shape {tuple(shape)} x {dtype_str} is "
            f"{expected} bytes, over the SELDON_MAX_DECODED_BYTES cap {cap}"
        )


# ---------------------------------------------------------------------------
# dtype helpers
# ---------------------------------------------------------------------------


def dtype_from_name(name: str) -> np.dtype:
    if name in _EXTENDED_DTYPES:
        return _EXTENDED_DTYPES[name]
    try:
        return np.dtype(name)
    except TypeError as e:
        raise PayloadError(f"unknown dtype {name!r}") from e


def is_extended_dtype(dtype: Any) -> bool:
    """True for the ml_dtypes types (bfloat16/fp8) that can't ride
    'tensor'/'ndarray' JSON without a silent upcast."""
    return np.dtype(dtype).name in _EXTENDED_DTYPES


def effective_encoding(arr: ArrayLike, requested: Optional[str]) -> str:
    """Wire encoding to actually use for ``arr``: honours ``requested``
    except that bfloat16/fp8 can't ride 'tensor'/'ndarray' JSON without a
    silent upcast — those are forced to 'raw'. The single place this rule
    lives; response builders and the micro-batch split all use it."""
    enc = requested or "ndarray"
    if np.dtype(_to_numpy(arr).dtype).name in _EXTENDED_DTYPES and enc != "raw":
        enc = "raw"
    return enc


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def _to_numpy(arr: ArrayLike) -> np.ndarray:
    """Materialise on host. jax.Array -> np.asarray triggers one D2H DMA."""
    if isinstance(arr, np.ndarray):
        return arr
    return np.asarray(arr)


# ---------------------------------------------------------------------------
# Tensor encodings -> numpy
# ---------------------------------------------------------------------------


def _decode_jpeg_rows(data: bytes, shape, dtype: np.dtype) -> np.ndarray:
    """Length-prefixed JPEG per leading-dim row -> stacked uint8 array.

    The wire-tier answer to a slow client->host pipe: a 224x224x3 raw row
    is ~150KB, its JPEG ~20-50KB — the H2D transport roofline moves ~5x
    (BASELINE.md documents the pipe). Decode is host-side, before
    ``to_device``."""
    if dtype != np.uint8:
        raise PayloadError(f"jpeg-rows requires uint8, got {dtype.name}")
    if len(shape) < 3:
        raise PayloadError(f"jpeg-rows needs [N, H, W(, C)] shape, got {shape}")
    if shape[0] <= 0:
        raise PayloadError(f"jpeg-rows needs at least one row, got shape {shape}")
    _check_decoded_size(_declared_nbytes(shape, dtype), shape, dtype.name)
    try:
        import io

        from PIL import Image
    except ImportError as e:  # pragma: no cover - PIL is in the image
        raise PayloadError("jpeg-rows encoding requires Pillow") from e
    blobs = []
    off, n = 0, shape[0]
    row_shape = tuple(shape[1:])
    for _ in range(n):
        if off + 4 > len(data):
            raise PayloadError("jpeg-rows: truncated length prefix")
        ln = int.from_bytes(data[off:off + 4], "little")
        off += 4
        if off + ln > len(data):
            raise PayloadError("jpeg-rows: truncated JPEG blob")
        blobs.append(data[off:off + ln])
        off += ln
    if off != len(data):
        raise PayloadError(f"jpeg-rows: {len(data) - off} trailing bytes")

    def decode(blob):
        img = np.asarray(Image.open(io.BytesIO(blob)))
        if img.shape != row_shape:
            raise PayloadError(
                f"jpeg-rows: decoded row shape {img.shape} != {row_shape}"
            )
        return img

    if len(blobs) > 4:
        # libjpeg releases the GIL: pooled decode keeps a 32-row batch from
        # serializing ~100ms of host CPU in front of the device step
        rows = list(decode_pool().map(decode, blobs))
    else:
        rows = [decode(b) for b in blobs]
    return np.stack(rows).astype(np.uint8, copy=False)


_DECODE_POOL = None


def decode_pool():
    """Shared host-side decode pool (JPEG rows, request unpacking). One
    persistent pool for the process: creating a ThreadPoolExecutor per
    request costs ~ms of thread spawn/teardown on the serving hot path."""
    global _DECODE_POOL
    if _DECODE_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        _DECODE_POOL = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="seldon-decode"
        )
    return _DECODE_POOL


def encode_jpeg_rows(arr: np.ndarray, quality: int = 90) -> bytes:
    """Inverse of ``_decode_jpeg_rows`` (client-side edge encoder)."""
    import io

    from PIL import Image

    if arr.dtype != np.uint8:
        raise PayloadError(f"jpeg-rows requires uint8, got {arr.dtype.name}")
    out = bytearray()
    for row in arr:
        buf = io.BytesIO()
        Image.fromarray(row).save(buf, format="JPEG", quality=quality)
        blob = buf.getvalue()
        out += len(blob).to_bytes(4, "little") + blob
    return bytes(out)


def raw_to_array(raw: pb.RawTensor) -> np.ndarray:
    dtype = dtype_from_name(raw.dtype)
    shape = tuple(raw.shape)
    encoding = getattr(raw, "encoding", "") or ""
    if encoding == "jpeg-rows":
        return _decode_jpeg_rows(raw.data, shape, dtype)
    expected = _declared_nbytes(shape, dtype)
    if encoding == "zlib":
        import zlib

        # Two-stage bomb defence: the shape-declared size itself is checked
        # against SELDON_MAX_DECODED_BYTES (shape is attacker-declared, so
        # capping at expected+1 alone would still allow a multi-GB inflate),
        # then decompression is bounded at that declared size.
        _check_decoded_size(expected, shape, raw.dtype)
        d = zlib.decompressobj()
        try:
            data = d.decompress(raw.data, expected + 1)
        except zlib.error as e:
            raise PayloadError(f"bad zlib raw tensor: {e}") from e
        if len(data) > expected or d.unconsumed_tail or not d.eof:
            raise PayloadError(
                f"zlib raw tensor inflates past shape {shape} x {raw.dtype}"
            )
    elif encoding == "":
        data = raw.data
    else:
        raise PayloadError(f"unknown raw encoding {encoding!r}")
    if len(data) != expected:
        raise PayloadError(
            f"raw tensor: {len(data)} bytes != shape {shape} x {raw.dtype}"
        )
    # frombuffer is zero-copy; the result is read-only which is fine because
    # the next hop is device_put (which copies to HBM) or pure-functional jax.
    return np.frombuffer(data, dtype=dtype).reshape(shape)


def tensor_to_array(tensor: pb.Tensor) -> np.ndarray:
    arr = np.asarray(tensor.values, dtype=np.float64)
    shape = tuple(tensor.shape)
    if shape:
        if int(np.prod(shape)) != arr.size:
            raise PayloadError(f"tensor: {arr.size} values != shape {shape}")
        arr = arr.reshape(shape)
    return arr


def ndarray_value_to_array(listvalue) -> np.ndarray:
    from google.protobuf import json_format

    nested = json_format.MessageToDict(listvalue)
    return np.asarray(nested)


def proto_data_to_array(data: pb.DefaultData) -> np.ndarray:
    which = data.WhichOneof("data_oneof")
    if which == "raw":
        return raw_to_array(data.raw)
    if which == "tensor":
        return tensor_to_array(data.tensor)
    if which == "ndarray":
        return ndarray_value_to_array(data.ndarray)
    raise PayloadError("DefaultData has no tensor/ndarray/raw payload")


# ---------------------------------------------------------------------------
# numpy -> tensor encodings
# ---------------------------------------------------------------------------


def array_to_raw(arr: ArrayLike, encoding: str = "",
                 jpeg_quality: int = 90) -> pb.RawTensor:
    np_arr = np.ascontiguousarray(_to_numpy(arr))
    if encoding == "jpeg-rows":
        data = encode_jpeg_rows(np_arr, quality=jpeg_quality)
    elif encoding == "zlib":
        import zlib

        data = zlib.compress(np_arr.tobytes(), level=1)
    elif encoding == "":
        data = np_arr.tobytes()
    else:
        raise PayloadError(f"unknown raw encoding {encoding!r}")
    return pb.RawTensor(
        dtype=dtype_name(np_arr.dtype),
        shape=list(np_arr.shape),
        data=data,
        encoding=encoding,
    )


def array_to_tensor(arr: ArrayLike) -> pb.Tensor:
    np_arr = _to_numpy(arr).astype(np.float64, copy=False)
    return pb.Tensor(shape=list(np_arr.shape), values=np_arr.ravel().tolist())


def array_to_proto_data(
    arr: ArrayLike, names: Optional[List[str]] = None, encoding: str = "raw"
) -> pb.DefaultData:
    data = pb.DefaultData(names=list(names) if names else [])
    if encoding == "raw":
        data.raw.CopyFrom(array_to_raw(arr))
    elif encoding == "tensor":
        data.tensor.CopyFrom(array_to_tensor(arr))
    elif encoding == "ndarray":
        from google.protobuf import json_format

        json_format.ParseDict(_to_numpy(arr).tolist(), data.ndarray)
    else:
        raise PayloadError(f"unknown tensor encoding {encoding!r}")
    return data


# ---------------------------------------------------------------------------
# JSON body <-> numpy (REST fast path: no proto objects constructed)
# ---------------------------------------------------------------------------


def json_data_to_array(data: JsonDict) -> np.ndarray:
    if "__jax__" in data:
        # device-resident interior fast path: the micro-batcher hands fused
        # HBM arrays straight to an in-process JAXComponent — no host copy,
        # no re-encode. Untrusted wire JSON can only put a list/str here
        # (it has no codec for array objects), so require a real array —
        # a client smuggling the key gets the 400 contract, not a 500.
        v = data["__jax__"]
        if not (hasattr(v, "shape") and hasattr(v, "dtype") and hasattr(v, "ndim")):
            raise PayloadError("__jax__ is an interior-only encoding")
        return v
    if "raw" in data:
        raw = data["raw"]
        if not isinstance(raw, dict):
            raise PayloadError(f"raw tensor must be an object, got {type(raw).__name__}")
        buf = raw.get("data")
        if isinstance(buf, (bytes, bytearray, memoryview)):
            # zero-copy interior path: proto_to_json keeps raw tensor bytes
            # as bytes, so in-process hops never pay the base64 tax
            buf = bytes(buf)
        else:
            try:
                buf = base64.b64decode(raw["data"])
            except (KeyError, TypeError, ValueError) as e:
                raise PayloadError(f"bad raw tensor in JSON: {e}") from e
        msg = pb.RawTensor(
            dtype=raw.get("dtype", "float32"),
            shape=[int(s) for s in raw.get("shape", [])],
            data=buf,
            encoding=raw.get("encoding", ""),
        )
        return raw_to_array(msg)
    if "tensor" in data:
        t = data["tensor"]
        arr = np.asarray(t.get("values", []), dtype=np.float64)
        shape = tuple(int(s) for s in t.get("shape", ()))
        if shape:
            if int(np.prod(shape)) != arr.size:
                raise PayloadError(f"tensor: {arr.size} values != shape {shape}")
            arr = arr.reshape(shape)
        return arr
    if "ndarray" in data:
        try:
            return np.asarray(data["ndarray"])
        except ValueError as e:
            raise PayloadError(f"ragged ndarray: {e}") from e
    raise PayloadError("JSON data has no tensor/ndarray/raw field")


def array_to_json_data(
    arr: ArrayLike, names: Optional[List[str]] = None, encoding: str = "ndarray"
) -> JsonDict:
    np_arr = _to_numpy(arr)
    out: JsonDict = {"names": list(names) if names else []}
    # "raw/zlib" and "raw/jpeg-rows" select a wire compression for the
    # bytes (client edge; decoded host-side by raw_to_array)
    raw_encoding = ""
    if encoding.startswith("raw/"):
        encoding, raw_encoding = "raw", encoding[4:]
    if encoding == "raw":
        # interior representation keeps BYTES (zero-copy all the way to the
        # proto edge); JSON edges base64 them via jsonable()/_json_default
        np_arr = np.ascontiguousarray(np_arr)
        r = array_to_raw(np_arr, encoding=raw_encoding)
        out["raw"] = {
            "dtype": r.dtype,
            "shape": list(r.shape),
            "data": r.data,
            **({"encoding": r.encoding} if r.encoding else {}),
        }
    elif encoding == "tensor":
        out["tensor"] = {
            "shape": list(np_arr.shape),
            "values": np_arr.astype(np.float64, copy=False).ravel().tolist(),
        }
    elif encoding == "ndarray":
        out["ndarray"] = np_arr.tolist()
    else:
        raise PayloadError(f"unknown tensor encoding {encoding!r}")
    return out


# ---------------------------------------------------------------------------
# Request part extraction / response construction
#
# The dispatch layer works on (payload, names, meta) triples in either
# representation. `Parts.datadef_type` remembers the requester's encoding so
# the response mirrors it (reference: python/seldon_core/utils.py:410-470).
# ---------------------------------------------------------------------------

TENSOR_KEYS = ("tensor", "ndarray", "raw")


class Parts:
    """Decoded request: exactly one of array/binary/string/jsondata is set."""

    __slots__ = ("array", "binary", "string", "jsondata", "names", "meta", "datadef_type")

    def __init__(
        self,
        array: Optional[np.ndarray] = None,
        binary: Optional[bytes] = None,
        string: Optional[str] = None,
        jsondata: Any = None,
        names: Optional[List[str]] = None,
        meta: Optional[JsonDict] = None,
        datadef_type: Optional[str] = None,
    ):
        self.array = array
        self.binary = binary
        self.string = string
        self.jsondata = jsondata
        self.names = names or []
        self.meta = meta or {}
        self.datadef_type = datadef_type

    @property
    def payload(self):
        if self.array is not None:
            return self.array
        if self.binary is not None:
            return self.binary
        if self.string is not None:
            return self.string
        return self.jsondata


def meta_from_proto(meta: pb.Meta) -> JsonDict:
    from google.protobuf import json_format

    return json_format.MessageToDict(meta)


def extract_parts_json(body: JsonDict) -> Parts:
    if not isinstance(body, dict):
        raise PayloadError("request body must be a JSON object")
    meta = body.get("meta") or {}
    if "data" in body:
        data = body["data"]
        # __jax__ (device-resident interior hop) responds raw: its results
        # are re-encoded per original caller by the micro-batch splitter,
        # and tolist()-ing a fused logits matrix would dwarf the forward
        datadef_type = (
            "raw" if "__jax__" in data
            else next((k for k in TENSOR_KEYS if k in data), "ndarray")
        )
        return Parts(
            array=json_data_to_array(data),
            names=list(data.get("names", [])),
            meta=meta,
            datadef_type=datadef_type,
        )
    if "binData" in body:
        try:
            raw = base64.b64decode(body["binData"])
        except (TypeError, ValueError) as e:
            raise PayloadError(f"bad binData: {e}") from e
        return Parts(binary=raw, meta=meta)
    if "strData" in body:
        return Parts(string=str(body["strData"]), meta=meta)
    if "jsonData" in body:
        return Parts(jsondata=body["jsonData"], meta=meta)
    # Empty-payload message (e.g. health probe predict) — treat as jsonData {}.
    return Parts(jsondata=None, meta=meta)


def extract_parts_proto(msg: pb.SeldonMessage) -> Parts:
    which = msg.WhichOneof("data_oneof")
    meta = meta_from_proto(msg.meta) if msg.HasField("meta") else {}
    if which == "data":
        return Parts(
            array=proto_data_to_array(msg.data),
            names=list(msg.data.names),
            meta=meta,
            datadef_type=msg.data.WhichOneof("data_oneof"),
        )
    if which == "bin_data":
        return Parts(binary=msg.bin_data, meta=meta)
    if which == "str_data":
        return Parts(string=msg.str_data, meta=meta)
    if which == "json_data":
        return Parts(jsondata=json.loads(msg.json_data) if msg.json_data else None, meta=meta)
    return Parts(jsondata=None, meta=meta)


def _is_arraylike(x) -> bool:
    if isinstance(x, np.ndarray):
        return True
    # jax.Array without importing jax at module scope
    return hasattr(x, "__array__") and hasattr(x, "dtype") and hasattr(x, "shape")


def build_json_response(
    result: Any,
    names: Optional[List[str]] = None,
    datadef_type: Optional[str] = None,
    meta: Optional[JsonDict] = None,
) -> JsonDict:
    """Wrap a user-hook return value in the requester's encoding."""
    out: JsonDict = {}
    if meta:
        out["meta"] = meta
    if result is None:
        out["jsonData"] = None
    elif isinstance(result, (list, tuple)) or _is_arraylike(result):
        arr = result if _is_arraylike(result) else np.asarray(result)
        out["data"] = array_to_json_data(
            arr, names, effective_encoding(arr, datadef_type)
        )
    elif isinstance(result, bytes):
        out["binData"] = base64.b64encode(result).decode("ascii")
    elif isinstance(result, str):
        out["strData"] = result
    else:
        out["jsonData"] = result
    return out


def build_proto_response(
    result: Any,
    names: Optional[List[str]] = None,
    datadef_type: Optional[str] = None,
    meta: Optional[JsonDict] = None,
) -> pb.SeldonMessage:
    msg = pb.SeldonMessage()
    if meta:
        from google.protobuf import json_format

        json_format.ParseDict(meta, msg.meta)
    if result is None:
        msg.json_data = "null"
    elif isinstance(result, (list, tuple)) or _is_arraylike(result):
        arr = result if _is_arraylike(result) else np.asarray(result)
        enc = effective_encoding(arr, datadef_type or "raw")
        msg.data.CopyFrom(array_to_proto_data(arr, names, enc))
    elif isinstance(result, bytes):
        msg.bin_data = result
    elif isinstance(result, str):
        msg.str_data = result
    else:
        msg.json_data = json.dumps(result)
    return msg


# ---------------------------------------------------------------------------
# proto <-> JSON transcoding for whole messages (engine boundary)
# ---------------------------------------------------------------------------


def has_raw_bytes(message: JsonDict) -> bool:
    """True when message.data.raw.data carries interior BYTES (the
    zero-copy representation) — the single predicate shared by the
    binary-hop/jsonable/proto fast paths."""
    data = message.get("data") if isinstance(message, dict) else None
    raw = data.get("raw") if isinstance(data, dict) else None
    return raw is not None and isinstance(
        raw.get("data"), (bytes, bytearray, memoryview)
    )


def jsonable(body: JsonDict) -> JsonDict:
    """Return a json.dumps-safe copy: raw tensor bytes (the zero-copy
    interior representation) become base64 strings. Recurses through the
    message shapes that can nest tensors — Feedback's request/response/
    truth and SeldonMessageList — and is a no-op (same object) when the
    body carries no bytes."""
    if not isinstance(body, dict):
        return body
    out = None  # copy-on-write: only allocate when something changes

    def put(key, value):
        nonlocal out
        if out is None:
            out = dict(body)
        out[key] = value

    if has_raw_bytes(body):
        data = body["data"]
        new_data = dict(data)
        new_data["raw"] = dict(data["raw"])
        new_data["raw"]["data"] = base64.b64encode(bytes(data["raw"]["data"])).decode("ascii")
        put("data", new_data)
    for key in ("request", "response", "truth"):
        nested = body.get(key)
        if isinstance(nested, dict):
            converted = jsonable(nested)
            if converted is not nested:
                put(key, converted)
    for key in ("seldonMessages", "requests"):
        nested = body.get(key)
        if isinstance(nested, list):
            converted_list = [jsonable(m) for m in nested]
            if any(c is not m for c, m in zip(converted_list, nested)):
                put(key, converted_list)
    return out if out is not None else body


def proto_to_json(msg) -> JsonDict:
    from google.protobuf import json_format

    if (
        isinstance(msg, pb.SeldonMessage)
        and msg.HasField("data")
        and msg.data.WhichOneof("data_oneof") == "raw"
    ):
        # fast path: keep the raw tensor's bytes as bytes instead of paying
        # MessageToDict's base64 encode (which the unit would immediately
        # decode again) — measured ~27 ms/request host CPU for a 4.8 MB
        # batch of images on one core
        out: JsonDict = {}
        if msg.HasField("meta"):
            out["meta"] = json_format.MessageToDict(msg.meta)
        if msg.HasField("status"):
            out["status"] = json_format.MessageToDict(msg.status)
        raw = msg.data.raw
        out["data"] = {
            "names": list(msg.data.names),
            "raw": {
                "dtype": raw.dtype,
                "shape": list(raw.shape),
                "data": raw.data,
                **({"encoding": raw.encoding} if raw.encoding else {}),
            },
        }
        return out
    out = json_format.MessageToDict(msg)
    # proto json_data is a STRING field; the JSON-side convention (REST
    # bodies, unit hooks) is the decoded structure — decode here so the
    # gRPC front hands units the same shape the REST front does
    if isinstance(out.get("jsonData"), str):
        try:
            out["jsonData"] = json.loads(out["jsonData"])
        except ValueError as e:
            raise PayloadError(f"malformed jsonData payload: {e}") from e
    return out


def json_to_proto(body: JsonDict, msg_cls=pb.SeldonMessage):
    from google.protobuf import json_format

    # composite messages nest SeldonMessages that may carry interior raw
    # BYTES: build recursively so every level takes the bytes fast path
    # (ParseDict on a bytes value would silently base64-"decode" garbage)
    if msg_cls is pb.Feedback:
        unknown = set(body) - {"request", "response", "truth", "reward"}
        if unknown:
            # preserve ParseDict's strictness: a typo'd key must 400, not
            # silently drop the field it was meant to set
            raise PayloadError(f"unknown Feedback fields {sorted(unknown)}")
        msg = pb.Feedback()
        for key, field in (("request", msg.request), ("response", msg.response),
                           ("truth", msg.truth)):
            if isinstance(body.get(key), dict):
                field.CopyFrom(json_to_proto(body[key]))
        if "reward" in body:
            msg.reward = float(body["reward"])
        return msg
    if msg_cls is pb.SeldonMessageList:
        unknown = set(body) - {"seldonMessages", "seldon_messages"}
        if unknown:
            raise PayloadError(f"unknown SeldonMessageList fields {sorted(unknown)}")
        msg = pb.SeldonMessageList()
        for m in body.get("seldonMessages") or body.get("seldon_messages") or []:
            msg.seldon_messages.append(json_to_proto(m))
        return msg
    if msg_cls is pb.SeldonMessage and has_raw_bytes(body):
        # bytes fast path (mirror of proto_to_json's): build the proto
        # directly, ParseDict only sees the remaining JSON-safe fields
        raw = body["data"]["raw"]
        rest = {k: v for k, v in body.items() if k != "data"}
        msg = pb.SeldonMessage()
        try:
            json_format.ParseDict(rest, msg)
        except json_format.ParseError as e:
            raise PayloadError(str(e)) from e
        msg.data.names.extend(body["data"].get("names") or [])
        msg.data.raw.dtype = raw.get("dtype", "float32")
        msg.data.raw.shape.extend(int(s) for s in raw.get("shape", ()))
        msg.data.raw.data = bytes(raw["data"])
        msg.data.raw.encoding = raw.get("encoding", "")
        return msg
    if (
        msg_cls is pb.SeldonMessage
        and "jsonData" in body
        and not isinstance(body["jsonData"], (str, type(None)))
    ):
        # inverse of proto_to_json's decode: the structured payload goes
        # back into the proto's string field
        body = {**body, "jsonData": json.dumps(body["jsonData"])}
    msg = msg_cls()
    try:
        # jsonable() base64-encodes any interior bytes the fast paths above
        # did not consume, so ParseDict round-trips them correctly
        json_format.ParseDict(jsonable(body), msg)
    except json_format.ParseError as e:
        raise PayloadError(str(e)) from e
    return msg


# ---------------------------------------------------------------------------
# Device placement
# ---------------------------------------------------------------------------


def to_device(arr: ArrayLike, sharding=None, dtype=None):
    """Host array -> HBM-resident jax.Array (optionally sharded/cast).

    The cast happens host-side for downcasts (bf16) to halve the PCIe/DMA
    bytes, device-side otherwise.
    """
    import jax

    np_arr = _to_numpy(arr)
    if dtype is not None and np.dtype(dtype).itemsize < np_arr.dtype.itemsize:
        np_arr = np_arr.astype(dtype)
    out = jax.device_put(np_arr, sharding) if sharding is not None else jax.device_put(np_arr)
    if dtype is not None and out.dtype != np.dtype(dtype):
        out = out.astype(dtype)
    return out
