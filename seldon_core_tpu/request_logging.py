"""Request/response payload logging — observability plane 3.

Two pieces, mirroring the reference's design:

* :class:`CloudEventsSink` — posts request/response pairs from the engine
  as CloudEvents over HTTP to ``SELDON_MESSAGE_LOGGING_SERVICE``
  (reference: engine/.../service/PredictionService.java:121-190 — CE-Type
  ``seldon.message.pair`` POSTed to a knative broker). TPU-serving twist:
  the engine's event loop must never block on a slow sink, so the sink is
  a bounded queue drained by one daemon thread; overflow drops events and
  counts them instead of applying back-pressure to predictions.

* :class:`RequestLoggerApp` — the collector service: unpacks each pair
  and flattens it per data row so every element is an indexable document
  (reference: seldon-request-logger/app/app.py:15-51, which flattened
  pairs for Elasticsearch). Documents are held in a bounded ring and
  exposed at ``GET /entries``; an ``index_sink`` callback supports
  shipping them to a real index.

CLI: ``python -m seldon_core_tpu.request_logging --port 2222``.
"""

from __future__ import annotations

import argparse
import collections
import json
import logging
import queue
import threading
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from .http_server import HTTPServer, Request, Response, error_body

logger = logging.getLogger(__name__)

CE_TYPE = "seldon.message.pair"


class CloudEventsSink:
    """Non-blocking CloudEvents poster: ``sink(event)`` enqueues, a daemon
    thread POSTs. Use as the ``RequestLogger`` sink."""

    def __init__(
        self,
        url: str,
        source: str = "seldon-tpu-engine",
        maxsize: int = 1024,
        timeout_s: float = 2.0,
    ):
        self.url = url
        self.source = source
        self.timeout_s = timeout_s
        self._queue: "queue.Queue[Optional[Dict]]" = queue.Queue(maxsize=maxsize)
        self._closing = threading.Event()
        self.stats = {"posted": 0, "dropped": 0, "errors": 0}
        self._thread = threading.Thread(
            target=self._worker, name="cloudevents-sink", daemon=True
        )
        self._thread.start()

    def __call__(self, event: Dict[str, Any]) -> None:
        if self._closing.is_set():
            self.stats["dropped"] += 1
            return
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            # never back-pressure the serving path; count the loss
            self.stats["dropped"] += 1

    def _worker(self) -> None:
        while True:
            try:
                event = self._queue.get(timeout=0.5)
            except queue.Empty:
                if self._closing.is_set():
                    return  # backlog fully drained
                continue
            if event is None:
                # close() sentinel: drain the backlog, then exit — queued
                # events are posted, not discarded
                while True:
                    try:
                        event = self._queue.get_nowait()
                    except queue.Empty:
                        return
                    if event is not None:
                        self._post(event)
            else:
                self._post(event)

    def _post(self, event: Dict[str, Any]) -> None:
        try:
            event.setdefault("source", self.source)
            body = json.dumps(event).encode()
            req = urllib.request.Request(
                self.url,
                data=body,
                headers={
                    "Content-Type": "application/cloudevents+json",
                    "ce-specversion": event.get("specversion", "1.0"),
                    "ce-type": event.get("type", CE_TYPE),
                    "ce-id": str(event.get("id", "")),
                    "ce-source": self.source,
                },
            )
            urllib.request.urlopen(req, timeout=self.timeout_s).read()
            self.stats["posted"] += 1
        except Exception as e:  # noqa: BLE001 - logging must never crash
            self.stats["errors"] += 1
            logger.warning("cloudevents post to %s failed: %s", self.url, e)

    def close(self) -> None:
        # never blocks on a full queue: the flag stops intake immediately,
        # the worker drains the backlog (posting, not discarding) and the
        # bounded join returns even if a hung collector delays the drain
        self._closing.set()
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=self.timeout_s + 1.0)


def _rows(message: Dict[str, Any]) -> List[Any]:
    """Decode a message's payload into per-row python values (or [] when
    the message carries no tensor data)."""
    from .payload import PayloadError, json_data_to_array

    data = message.get("data")
    if isinstance(data, dict):
        try:
            arr = json_data_to_array(data)
        except PayloadError:
            return []
        return [row.tolist() if hasattr(row, "tolist") else row for row in arr]
    if "strData" in message:
        return [message["strData"]]
    if "jsonData" in message:
        return [message["jsonData"]]
    return []


def flatten_pair(event: Dict[str, Any]) -> List[Dict[str, Any]]:
    """One document per request row, pairing it with the matching response
    row (reference flattened exactly this way for per-element indexing —
    seldon-request-logger/app/app.py:15-51)."""
    data = event.get("data") or {}
    request = data.get("request") or {}
    response = data.get("response") or {}
    req_rows = _rows(request)
    resp_rows = _rows(response)
    req_names = (request.get("data") or {}).get("names") or []
    resp_names = (response.get("data") or {}).get("names") or []
    meta = response.get("meta") or request.get("meta") or {}
    n = max(len(req_rows), len(resp_rows), 1)
    docs = []
    for i in range(n):
        doc: Dict[str, Any] = {
            "ce_id": event.get("id", ""),
            "ce_source": event.get("source", ""),
            "puid": (meta or {}).get("puid", event.get("id", "")),
            "index": i,
        }
        if i < len(req_rows):
            doc["request"] = req_rows[i]
            if req_names:
                doc["request_names"] = req_names
        if i < len(resp_rows):
            doc["response"] = resp_rows[i]
            if resp_names:
                doc["response_names"] = resp_names
        if meta.get("tags"):
            doc["tags"] = meta["tags"]
        if meta.get("requestPath"):
            doc["requestPath"] = meta["requestPath"]
        docs.append(doc)
    return docs


class RequestLoggerApp:
    """Collector service: ingests CloudEvents pairs, keeps flattened docs
    in a bounded ring, optionally forwards each doc to ``index_sink``."""

    def __init__(self, capacity: int = 10000,
                 index_sink: Optional[Callable[[Dict], None]] = None):
        self.entries: "collections.deque[Dict]" = collections.deque(maxlen=capacity)
        self.index_sink = index_sink
        self.stats = {"events": 0, "docs": 0, "bad": 0}

    def ingest(self, event: Dict[str, Any]) -> List[Dict[str, Any]]:
        docs = flatten_pair(event)
        self.stats["events"] += 1
        self.stats["docs"] += len(docs)
        for doc in docs:
            self.entries.append(doc)
            if self.index_sink is not None:
                try:
                    self.index_sink(doc)
                except Exception as e:  # noqa: BLE001
                    logger.warning("index sink failed: %s", e)
        return docs

    def app(self) -> HTTPServer:
        from .http_server import max_body_from_env

        srv = HTTPServer("request-logger", max_body_bytes=max_body_from_env())

        async def index(req: Request) -> Response:
            body = req.json()
            if not isinstance(body, dict):
                self.stats["bad"] += 1
                return Response(error_body(400, "expected a CloudEvent JSON object"), 400)
            # binary content mode: attributes ride in ce-* headers and the
            # body is the bare data payload
            if "data" not in body and "request" in body:
                body = {
                    "id": req.headers.get("ce-id", ""),
                    "source": req.headers.get("ce-source", ""),
                    "type": req.headers.get("ce-type", CE_TYPE),
                    "data": body,
                }
            docs = self.ingest(body)
            return Response({"indexed": len(docs)})

        async def entries(req: Request) -> Response:
            return Response({"entries": list(self.entries), "stats": self.stats})

        async def ping(req: Request) -> Response:
            return Response("pong", content_type="text/plain")

        srv.add_route("/", index)
        srv.add_route("/api/v0.1/index", index)
        srv.add_route("/entries", entries)
        srv.add_route("/ping", ping)
        srv.add_route("/ready", ping)
        return srv


def main(argv=None) -> None:
    import asyncio

    parser = argparse.ArgumentParser("seldon-tpu-request-logger")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=2222)
    parser.add_argument("--capacity", type=int, default=10000)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    service = RequestLoggerApp(capacity=args.capacity)
    asyncio.run(service.app().serve_forever(args.host, args.port))


if __name__ == "__main__":
    main()
