"""Online autonomic planner: burn verdicts + gauges -> knob decisions.

The InferLine-shaped online half (PAPERS.md, arxiv 1812.01776): every
planner tick consumes the PR 18 telemetry the reconciler already
scrapes — per-(tenant, slo) burn-rate verdicts, the device-time
ledger's live gauges, the shed/preempt counter plane — walks the SPF1
cost model, and emits ONE typed :class:`Decision`. It never touches an
engine itself: the reconciler actuates decisions exclusively through
existing safe mechanisms (``ContinuousBatcher.retune()`` at a poll
boundary, the autoscaler's clamped replica rewrite), so the planner
can be unit-tested as a pure decision table.

The decision table, in precedence order (first match wins — the order
IS the same-tick conflict resolution, see docs/operate.md §"Autonomic
planning"):

====  ==========================================  =================
rank  condition                                   decision
====  ==========================================  =================
1     any ``page`` burn verdict                   ``scale_up``
2     shed/preempt deltas for ``hot_ticks``       ``scale_up``
      consecutive ticks
3     ``warn`` burn + cost model knows a config   ``retune``
      that meets the objectives (census-pinned)
4     ``warn`` burn, no meeting config            ``scale_up``
5     sheds with quiet burn + watermark headroom  ``retune``
      (raise ``pressure_high``)
6     quiet burn + idle device for                ``scale_down``
      ``scale_down_ticks`` consecutive ticks
7     otherwise                                   ``hold``
====  ==========================================  =================

Hysteresis is structural, and SHARED with the PR 18 autoscaler so the
two controllers cannot fight: ``scale_down_ticks`` is the same
stabilization window the HPA loop uses (the reconciler constructs the
planner with its own value), any non-quiet tick resets the idle
streak, a retune starts a ``retune_cooldown_ticks`` refractory period
(thrash guard — flight ``planner_retune`` records carry the evidence
when it trips), and rank 1 means a paging tick can never emit the
scale-down a quiet streak earned. The reconciler enforces the same
precedence at the actuation site: a burn-verdict page VETOES any
scale-down in the same tick, counted, deterministically.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional

from .artifact import CostModel, ProfileError

logger = logging.getLogger(__name__)

# profile config axes the batcher can actually retune live (subset of
# continuous.RETUNABLE_KNOBS that the SPF1 grid sweeps); slots and
# kv-tier bytes are boot-time — changing those is a scale/redeploy
# decision, never a retune
RETUNABLE_AXES = (
    "fused_steps_per_dispatch",
    "prefill_chunk",
    "depth_groups",
    "depth_group_split_bytes",
)


@dataclasses.dataclass
class Decision:
    """One planner tick's verdict. ``action`` is one of ``hold`` /
    ``retune`` / ``scale_up`` / ``scale_down``; ``knobs`` is non-empty
    only for ``retune`` (the exact kwargs for ``retune()``)."""

    action: str
    reason: str
    knobs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    rank: int = 0


class ServingPlanner:
    """Pure decision table over one predictor's telemetry; all state
    is tick counters (streaks, cooldowns, last counter totals)."""

    def __init__(
        self,
        cost_model: Optional[CostModel] = None,
        ttft_p99_ms: Optional[float] = None,
        tpot_p99_ms: Optional[float] = None,
        scale_down_ticks: int = 3,
        hot_ticks: int = 2,
        retune_cooldown_ticks: int = 3,
        idle_busy_frac: float = 0.10,
        pressure_high_ceiling: float = 0.95,
    ):
        self.cost_model = cost_model
        self.ttft_p99_ms = ttft_p99_ms
        self.tpot_p99_ms = tpot_p99_ms
        self.scale_down_ticks = max(1, int(scale_down_ticks))
        self.hot_ticks = max(1, int(hot_ticks))
        self.retune_cooldown_ticks = max(0, int(retune_cooldown_ticks))
        self.idle_busy_frac = float(idle_busy_frac)
        self.pressure_high_ceiling = float(pressure_high_ceiling)
        self._quiet_streak = 0
        self._hot_streak = 0
        self._cooldown = 0
        self._last_totals: Dict[str, float] = {}
        self.stats = {
            "ticks": 0, "retunes": 0, "scale_ups": 0,
            "scale_downs": 0, "holds": 0,
        }

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _worst(verdicts: List[Dict[str, Any]]) -> str:
        from ..serving.slo_burn import SEVERITIES

        worst = 0
        for v in verdicts or []:
            sev = v.get("severity")
            if sev in SEVERITIES:
                worst = max(worst, SEVERITIES.index(sev))
        return SEVERITIES[worst]

    def _deltas(self, totals: Dict[str, float]) -> Dict[str, float]:
        """Per-tick deltas of cumulative counters (sheds/preempts);
        a counter reset (member restart) clamps at zero."""
        out = {}
        for k, v in (totals or {}).items():
            prev = self._last_totals.get(k, 0.0)
            out[k] = max(0.0, float(v) - prev)
        self._last_totals = dict(totals or {})
        return out

    def _objectives(self, verdicts: List[Dict[str, Any]]):
        """Declared objectives win; else infer from the breached
        verdicts' own thresholds (slo names carry the phase)."""
        ttft, tpot = self.ttft_p99_ms, self.tpot_p99_ms
        for v in verdicts or []:
            if v.get("severity") not in ("warn", "page"):
                continue
            name = str(v.get("slo") or "").lower()
            thr_ms = float(v.get("threshold_s") or 0.0) * 1e3
            if thr_ms <= 0:
                continue
            if "ttft" in name and ttft is None:
                ttft = thr_ms
            elif "tpot" in name and tpot is None:
                tpot = thr_ms
        return ttft, tpot

    def _retune_target(
        self,
        verdicts: List[Dict[str, Any]],
        current_config: Optional[Dict[str, Any]],
        census: Optional[Dict[str, Any]],
    ) -> Optional[Dict[str, Any]]:
        """Knob diff toward the best census-compatible measured config
        meeting the objectives, or None when the profile has nothing
        better (then the breach is a capacity problem, not a tuning
        one). Only RETUNABLE_AXES ever appear in the diff."""
        if self.cost_model is None or not current_config:
            return None
        ttft, tpot = self._objectives(verdicts)
        if ttft is None and tpot is None:
            return None
        require: Dict[str, Any] = {"slots": current_config.get("slots")}
        if census:
            # out-of-census configs would be refused typed by retune();
            # don't even rank them. depth-group variants and the chunk
            # executable only exist when the boot census built them.
            if int(census.get("depth_groups") or 0) <= 1:
                require["depth_groups"] = int(
                    current_config.get("depth_groups") or 0
                )
        try:
            best = self.cost_model.best(
                ttft_p99_ms=ttft, tpot_p99_ms=tpot, require=require,
            )
        except ProfileError:
            return None
        if not best["meets"]:
            return None
        knobs = {}
        for axis in RETUNABLE_AXES:
            want = best["config"].get(axis)
            have = current_config.get(axis)
            if want is None or int(want) == int(have or 0):
                continue
            # an axis the profile never SWEPT carries no evidence: the
            # grid's constant is the driver's choice, not a measured
            # preference over the member's live value (e.g. the
            # batcher's own split-bytes heuristic) — never churn it
            swept = {
                int(e["config"].get(axis) or 0)
                for e in self.cost_model.grid
            }
            if len(swept) <= 1:
                continue
            knobs[axis] = int(want)
        if census and "prefill_chunk" in knobs:
            if knobs["prefill_chunk"] not in (
                0, int(census.get("prefill_chunk") or 0)
            ):
                del knobs["prefill_chunk"]
        return knobs or None

    # -- the decision table --------------------------------------------------

    def tick(
        self,
        verdicts: Optional[List[Dict[str, Any]]] = None,
        gauges: Optional[Dict[str, float]] = None,
        counter_totals: Optional[Dict[str, float]] = None,
        current_config: Optional[Dict[str, Any]] = None,
        census: Optional[Dict[str, Any]] = None,
    ) -> Decision:
        """One pass of the table. ``gauges`` carries the merged live
        gauges (``device_busy_frac``, ``pressure_high``...);
        ``counter_totals`` the cumulative shed/preempt counters this
        planner diffs per tick."""
        verdicts = verdicts or []
        gauges = gauges or {}
        self.stats["ticks"] += 1
        if self._cooldown > 0:
            self._cooldown -= 1
        worst = self._worst(verdicts)
        deltas = self._deltas(counter_totals or {})
        pressure_events = sum(
            deltas.get(k, 0.0) for k in ("sheds", "preemptions")
        )

        decision = self._decide(
            worst, verdicts, gauges, pressure_events,
            current_config, census,
        )
        if decision.action == "retune":
            self._cooldown = self.retune_cooldown_ticks
            self.stats["retunes"] += 1
        elif decision.action == "scale_up":
            self.stats["scale_ups"] += 1
        elif decision.action == "scale_down":
            self.stats["scale_downs"] += 1
        else:
            self.stats["holds"] += 1
        return decision

    def _decide(
        self, worst, verdicts, gauges, pressure_events,
        current_config, census,
    ) -> Decision:
        # rank 1: paging burn — capacity, now. Resets every streak: a
        # page tick can never also bank idle credit toward scale-down.
        if worst == "page":
            self._quiet_streak = 0
            self._hot_streak = 0
            return Decision("scale_up", "paging SLO burn", rank=1)

        # rank 2: sustained shed/preempt pressure — HBM or admission
        # capacity, not a knob the profile can tune away
        if pressure_events > 0 and worst != "ok":
            self._hot_streak += 1
            self._quiet_streak = 0
            if self._hot_streak >= self.hot_ticks:
                self._hot_streak = 0
                return Decision(
                    "scale_up",
                    f"shed/preempt burn for {self.hot_ticks} ticks",
                    rank=2,
                )
            return Decision(
                "hold",
                f"pressure streak {self._hot_streak}/{self.hot_ticks}",
                rank=2,
            )
        self._hot_streak = 0

        # ranks 3/4: warn-level burn — first try to tune it away with a
        # measured, census-compatible config; profile says impossible →
        # it is a capacity signal
        if worst == "warn":
            self._quiet_streak = 0
            if self._cooldown > 0:
                return Decision(
                    "hold", f"retune cooldown ({self._cooldown} ticks left)",
                    rank=3,
                )
            knobs = self._retune_target(verdicts, current_config, census)
            if knobs:
                return Decision(
                    "retune", "warn burn: profile knows a meeting config",
                    knobs=knobs, rank=3,
                )
            return Decision(
                "scale_up", "warn burn and no profile config meets", rank=4,
            )

        # rank 5: sheds while burn is quiet — deadlines are being shed
        # at admission yet tenants aren't burning budget: the watermark
        # is too conservative for this traffic; nudge it (bounded)
        if pressure_events > 0:
            self._quiet_streak = 0
            high = gauges.get("pressure_high")
            if (
                self._cooldown == 0
                and high is not None
                and high + 0.02 < self.pressure_high_ceiling
            ):
                return Decision(
                    "retune", "sheds with quiet burn: raise admit watermark",
                    knobs={
                        "pressure_high": round(
                            min(self.pressure_high_ceiling, high + 0.05), 4
                        ),
                    },
                    rank=5,
                )
            return Decision("hold", "sheds with quiet burn", rank=5)

        # rank 6: quiet burn + idle device — bank a tick toward the
        # shared stabilization window
        busy = gauges.get("device_busy_frac")
        if busy is not None and busy < self.idle_busy_frac:
            self._quiet_streak += 1
            if self._quiet_streak >= self.scale_down_ticks:
                self._quiet_streak = 0
                return Decision(
                    "scale_down",
                    f"idle pools + quiet burn for "
                    f"{self.scale_down_ticks} ticks",
                    rank=6,
                )
            return Decision(
                "hold",
                f"idle streak {self._quiet_streak}/{self.scale_down_ticks}",
                rank=6,
            )
        self._quiet_streak = 0
        return Decision("hold", "objectives met", rank=7)


__all__ = ["Decision", "RETUNABLE_AXES", "ServingPlanner"]
