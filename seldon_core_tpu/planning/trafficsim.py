"""Seeded million-user traffic simulator: trace-replay load generation.

The planner's closed loop is only a reproducible claim if the load that
exercises it is reproducible, so this module generates the entire
workload of a serving fleet — arrival times, tenants, prompts,
deadlines — from ONE integer seed and nothing else:

* **Arrivals** are a Markov-modulated Gamma renewal process riding a
  diurnal sinusoid: the base rate swings ``diurnal_amplitude`` over
  ``diurnal_period_s``, a two-state (calm/burst) Markov chain multiplies
  it by ``burst_mult`` during bursts, and inter-arrival gaps draw from
  ``Gamma(shape, 1/(rate*shape))`` — shape < 1 gives the heavy-tailed
  clumping real traffic has; shape = 1 degrades to Poisson.
* **Tenants** follow a Zipf mix (rank ``r`` with weight ``1/r^s``) —
  a few hot tenants and a long tail, the shape multi-tenant SLO
  isolation has to survive.
* **Prompts** come from prefix-sharing families: each family owns a
  seeded shared prefix (the "system prompt" of one app) plus a
  per-request suffix, so prefix-cache hit rates are realistic and
  deterministic. Hot families follow their own Zipf rank.
* **Deadlines** are log-uniform between bounds, so some requests are
  always near the shed boundary.

Everything derives from ``random.Random(seed)`` — the same seed yields
the byte-identical trace on every run (asserted by
tests/test_planning.py), which is what lets modelbench's
``llm_1b_storm`` gate planner convergence instead of anecdotes.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


@dataclasses.dataclass
class TrafficEvent:
    """One arriving request, fully determined by the trace seed."""

    t: float                       # arrival offset from trace start, seconds
    tenant: str
    family: int                    # prompt-family id (prefix-sharing group)
    prompt: List[int]
    max_new_tokens: int
    deadline_s: Optional[float]
    slo: str = "standard"


class TrafficSim:
    """Seeded trace generator; see module docstring for the processes."""

    def __init__(
        self,
        seed: int,
        duration_s: float = 60.0,
        base_rps: float = 10.0,
        diurnal_amplitude: float = 0.6,
        diurnal_period_s: float = 240.0,
        burst_mult: float = 4.0,
        burst_on_prob: float = 0.05,
        burst_off_prob: float = 0.35,
        gamma_shape: float = 0.7,
        tenants: int = 8,
        zipf_s: float = 1.1,
        prompt_families: int = 12,
        prefix_len: int = 24,
        suffix_len: Tuple[int, int] = (4, 48),
        vocab: int = 32000,
        max_new_tokens: Tuple[int, int] = (8, 64),
        deadline_s: Optional[Tuple[float, float]] = (0.5, 8.0),
        deadline_frac: float = 0.5,
    ):
        if duration_s <= 0 or base_rps <= 0:
            raise ValueError("duration_s and base_rps must be > 0")
        if not (0.0 <= diurnal_amplitude < 1.0):
            raise ValueError("diurnal_amplitude must be in [0, 1)")
        if gamma_shape <= 0:
            raise ValueError("gamma_shape must be > 0")
        if tenants < 1 or prompt_families < 1:
            raise ValueError("need >= 1 tenant and >= 1 prompt family")
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.base_rps = float(base_rps)
        self.diurnal_amplitude = float(diurnal_amplitude)
        self.diurnal_period_s = float(diurnal_period_s)
        self.burst_mult = float(burst_mult)
        self.burst_on_prob = float(burst_on_prob)
        self.burst_off_prob = float(burst_off_prob)
        self.gamma_shape = float(gamma_shape)
        self.n_tenants = int(tenants)
        self.zipf_s = float(zipf_s)
        self.n_families = int(prompt_families)
        self.prefix_len = int(prefix_len)
        self.suffix_len = (int(suffix_len[0]), int(suffix_len[1]))
        self.vocab = int(vocab)
        self.max_new = (int(max_new_tokens[0]), int(max_new_tokens[1]))
        self.deadline_bounds = (
            (float(deadline_s[0]), float(deadline_s[1]))
            if deadline_s is not None else None
        )
        self.deadline_frac = float(deadline_frac)
        # Zipf cumulative weights for tenants and prompt families
        self._tenant_cdf = self._zipf_cdf(self.n_tenants, self.zipf_s)
        self._family_cdf = self._zipf_cdf(self.n_families, self.zipf_s)
        # family prefixes derive from the trace seed alone, not from the
        # arrival stream's rng position — an arrival-knob change must
        # not reshuffle every family's shared prefix
        self._prefixes = [
            [
                random.Random(f"{self.seed}:family:{f}").randrange(
                    1, self.vocab
                )
                for _ in range(self.prefix_len)
            ]
            for f in range(self.n_families)
        ]

    @staticmethod
    def _zipf_cdf(n: int, s: float) -> List[float]:
        weights = [1.0 / (r ** s) for r in range(1, n + 1)]
        total = sum(weights)
        acc, cdf = 0.0, []
        for w in weights:
            acc += w / total
            cdf.append(acc)
        cdf[-1] = 1.0
        return cdf

    @staticmethod
    def _pick(cdf: List[float], u: float) -> int:
        for i, c in enumerate(cdf):
            if u <= c:
                return i
        return len(cdf) - 1

    def rate_at(self, t: float, bursting: bool) -> float:
        """Instantaneous arrival rate: diurnal sinusoid x burst state."""
        diurnal = 1.0 + self.diurnal_amplitude * math.sin(
            2.0 * math.pi * t / self.diurnal_period_s
        )
        rate = self.base_rps * max(1e-6, diurnal)
        return rate * (self.burst_mult if bursting else 1.0)

    def events(self) -> Iterator[TrafficEvent]:
        """The deterministic event stream, in arrival order."""
        rng = random.Random(self.seed)
        t = 0.0
        bursting = False
        while True:
            rate = self.rate_at(t, bursting)
            # Gamma renewal gap with mean 1/rate (shape-scale form)
            gap = rng.gammavariate(self.gamma_shape, 1.0 / (rate * self.gamma_shape))
            t += gap
            if t >= self.duration_s:
                return
            # two-state Markov chain steps once per arrival
            if bursting:
                if rng.random() < self.burst_off_prob:
                    bursting = False
            elif rng.random() < self.burst_on_prob:
                bursting = True
            tenant = self._pick(self._tenant_cdf, rng.random())
            family = self._pick(self._family_cdf, rng.random())
            suffix_n = rng.randint(*self.suffix_len)
            prompt = list(self._prefixes[family]) + [
                rng.randrange(1, self.vocab) for _ in range(suffix_n)
            ]
            deadline = None
            if self.deadline_bounds is not None and rng.random() < self.deadline_frac:
                lo, hi = self.deadline_bounds
                # log-uniform: most deadlines loose, a steady trickle tight
                deadline = math.exp(
                    rng.uniform(math.log(lo), math.log(hi))
                )
            yield TrafficEvent(
                t=round(t, 6),
                tenant=f"tenant-{tenant}",
                family=family,
                prompt=prompt,
                max_new_tokens=rng.randint(*self.max_new),
                deadline_s=round(deadline, 6) if deadline is not None else None,
            )

    def trace(self, max_events: Optional[int] = None) -> List[TrafficEvent]:
        out: List[TrafficEvent] = []
        for ev in self.events():
            out.append(ev)
            if max_events is not None and len(out) >= max_events:
                break
        return out

    def summary(self, trace: Optional[List[TrafficEvent]] = None) -> Dict[str, Any]:
        """Aggregate shape of a trace (modelbench scenario text)."""
        trace = self.trace() if trace is None else trace
        if not trace:
            return {"events": 0}
        per_tenant: Dict[str, int] = {}
        for ev in trace:
            per_tenant[ev.tenant] = per_tenant.get(ev.tenant, 0) + 1
        span = max(ev.t for ev in trace) or 1.0
        return {
            "events": len(trace),
            "span_s": round(span, 3),
            "mean_rps": round(len(trace) / span, 3),
            "tenants": len(per_tenant),
            "hottest_tenant_frac": round(max(per_tenant.values()) / len(trace), 4),
            "prompt_tokens": sum(len(ev.prompt) for ev in trace),
            "deadline_frac": round(
                sum(1 for ev in trace if ev.deadline_s is not None) / len(trace), 4
            ),
        }


def replay(
    trace: List[TrafficEvent],
    submit: Callable[[TrafficEvent], Any],
    time_scale: float = 0.0,
    clock: Optional[Callable[[], float]] = None,
    sleep: Optional[Callable[[float], None]] = None,
) -> List[Any]:
    """Feed a trace into ``submit`` (one handle per event, returned in
    trace order). ``time_scale`` 0 replays as fast as the engine admits
    (offline sweep); > 0 paces arrivals at ``trace_time * time_scale``
    (1.0 = real time) so burst clumps actually contend."""
    handles: List[Any] = []
    if time_scale > 0:
        import time as _time

        clock = clock or _time.monotonic
        sleep = sleep or _time.sleep
        t0 = clock()
        for ev in trace:
            due = t0 + ev.t * time_scale
            delay = due - clock()
            if delay > 0:
                sleep(delay)
            handles.append(submit(ev))
    else:
        for ev in trace:
            handles.append(submit(ev))
    return handles
