"""Offline config-sweep profiler: live engine -> SPF1 cost model.

InferLine-style (PAPERS.md, arxiv 1812.01776) offline stage: drive a
REAL generate engine — not a simulator — through a grid of serving
configs (slots x prefill chunk x fused K x depth-group split x kv-tier
bytes) under one seeded :class:`~.trafficsim.TrafficSim` trace, and
price every config from the telemetry PR 18 already exports:

* tokens/s from the replay wall clock,
* TTFT/TPOT/queue-wait quantiles from the scheduler's SLO reservoir
  (``slo_summary()`` — the same samples /prometheus exports),
* HBM footprint from the engine's own weight + KV-cache accounting,
* per-kind device-time split from the DeviceTimeLedger,
* a compile census (variant count + wall build/warm seconds) so the
  planner — and the fusion cost gate — can price what a config change
  COSTS, not just what it yields.

The caller owns engine construction (``factory(config) -> batcher``)
because only the caller knows the model family, mesh and runtime tier;
the sweep owns measurement and artifact assembly, so every profile on
disk has the same shape regardless of who drove it. Factories build,
warm and return a live ``ContinuousBatcher`` (or anything matching its
``submit/slo_summary/stats/retune_census/close`` surface); the sweep
closes each instance before building the next so two grid points never
contend for the same chips.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

from .artifact import CONFIG_KEYS, build_profile, normalize_config
from .trafficsim import TrafficEvent, TrafficSim, replay

logger = logging.getLogger(__name__)


def sweep_grid(
    slots: Sequence[int] = (4, 8),
    prefill_chunk: Sequence[int] = (0,),
    fused_steps: Sequence[int] = (0, 4, 8),
    depth_groups: Sequence[int] = (0,),
    depth_group_split_bytes: Sequence[int] = (0,),
    kv_tier_bytes: Sequence[int] = (0,),
) -> List[Dict[str, int]]:
    """The cartesian config grid, normalized to CONFIG_KEYS. Axes
    default to singletons so callers only pay for what they sweep."""
    out: List[Dict[str, int]] = []
    for s in slots:
        for pc in prefill_chunk:
            for fk in fused_steps:
                for dg in depth_groups:
                    for sb in depth_group_split_bytes:
                        for kt in kv_tier_bytes:
                            out.append(normalize_config({
                                "slots": s,
                                "prefill_chunk": pc,
                                "fused_steps_per_dispatch": fk,
                                "depth_groups": dg,
                                "depth_group_split_bytes": sb,
                                "kv_tier_bytes": kt,
                            }))
    return out


def _quant(slo: Optional[Dict[str, Any]], phase: str, q: str) -> float:
    if not slo:
        return 0.0
    block = slo.get(phase)
    if not block:
        return 0.0
    return float(block.get(q, 0.0) or 0.0)


def _compile_variants(census: Optional[Dict[str, Any]]) -> int:
    """Warmed-executable count implied by a boot census — the same
    vocabulary retune validation speaks (fused K variants x group-burst
    doubling, plus the chunked-prefill executable when enabled)."""
    if not census:
        return 1
    n = max(1, len(census.get("fused_ks") or ()))
    if int(census.get("depth_groups") or 0) > 1:
        n *= 2
    if int(census.get("prefill_chunk") or 0) > 0:
        n += 1
    return n


def measure_config(
    batcher,
    trace: List[TrafficEvent],
    build_s: float = 0.0,
    timeout_s: float = 120.0,
) -> Dict[str, Any]:
    """Replay ``trace`` through one live engine as fast as it admits
    and harvest the prices. Shed/expired requests are expected under
    pressure sweeps — they count as not-generated, never as failure."""
    t0 = time.monotonic()
    done = 0
    tokens = 0
    shed = 0

    def submit(ev: TrafficEvent):
        try:
            return batcher.submit(
                ev.prompt,
                max_new_tokens=ev.max_new_tokens,
                tenant=ev.tenant,
                deadline_s=ev.deadline_s,
            )
        except Exception:  # noqa: BLE001 - admission shed IS a datum
            return None

    handles = replay(trace, submit)
    deadline = t0 + timeout_s
    for h in handles:
        if h is None:
            shed += 1
            continue
        try:
            out = h.result(timeout=max(0.1, deadline - time.monotonic()))
            tokens += len(out)
            done += 1
        except Exception:  # noqa: BLE001 - per-request expiry/preempt
            shed += 1
    elapsed = max(1e-6, time.monotonic() - t0)
    slo = batcher.slo_summary() if hasattr(batcher, "slo_summary") else None
    census = (
        batcher.retune_census() if hasattr(batcher, "retune_census") else None
    )
    prof = getattr(batcher, "_prof", None)
    device = {}
    if prof is not None and getattr(prof, "enabled", False):
        try:
            device = dict(prof.summary().get("by_kind") or {})
        except Exception:  # noqa: BLE001 - telemetry must not fail a sweep
            device = {}
    kv_bytes = int(getattr(batcher, "_kv_key_bytes", 0) or 0)
    hbm = int(
        int(getattr(batcher, "_param_bytes", 0) or 0)
        + int(getattr(batcher, "slots", 0) or 0)
        * int(getattr(batcher, "max_seq", 0) or 0)
        * kv_bytes
    )
    return {
        "tokens_per_s": round(tokens / elapsed, 3),
        "ttft_p50_ms": _quant(slo, "ttft_ms", "p50_ms"),
        "ttft_p99_ms": _quant(slo, "ttft_ms", "p99_ms"),
        "tpot_p50_ms": _quant(slo, "tpot_ms", "p50_ms"),
        "tpot_p99_ms": _quant(slo, "tpot_ms", "p99_ms"),
        "hbm_bytes": hbm,
        "requests": done,
        "shed": shed,
        "compile_census": {
            "variants": _compile_variants(census),
            "compile_s": round(max(0.0, build_s), 3),
        },
        "device_time": device,
    }


def run_sweep(
    factory: Callable[[Dict[str, int]], Any],
    grid: Iterable[Dict[str, Any]],
    sim: TrafficSim,
    model_family: str,
    mesh_shape: Optional[Dict[str, int]] = None,
    max_events: Optional[int] = None,
    created: Optional[str] = None,
) -> Dict[str, Any]:
    """Sweep the grid and return a validated SPF1 profile dict (write
    it with :func:`~.artifact.write_profile`). The SAME seeded trace
    replays against every config — the grid prices configs, not luck.
    A config the factory refuses to build (e.g. slots past the chip's
    HBM) is logged and skipped, never silently priced as zero."""
    trace = sim.trace(max_events=max_events)
    if not trace:
        raise ValueError("traffic sim produced an empty trace")
    entries: List[Dict[str, Any]] = []
    skipped = 0
    for config in grid:
        config = normalize_config(config)
        t_build = time.monotonic()
        try:
            batcher = factory(config)
        except Exception as e:  # noqa: BLE001 - unbuildable grid point
            skipped += 1
            logger.warning("sweep: config %s unbuildable: %s", config, e)
            continue
        build_s = time.monotonic() - t_build
        try:
            prices = measure_config(batcher, trace, build_s=build_s)
        finally:
            close = getattr(batcher, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    logger.exception("sweep: close failed for %s", config)
        entries.append({"config": config, **prices})
        logger.info(
            "sweep: %s -> %.1f tok/s ttft_p99=%.1fms tpot_p99=%.1fms",
            {k: v for k, v in config.items() if v},
            prices["tokens_per_s"], prices["ttft_p99_ms"],
            prices["tpot_p99_ms"],
        )
    if not entries:
        raise ValueError(
            f"sweep produced no measurable configs ({skipped} skipped)"
        )
    if skipped:
        logger.warning("sweep: %d of %d grid points skipped",
                       skipped, skipped + len(entries))
    return build_profile(
        model_family, entries, mesh_shape=mesh_shape, created=created,
    )


__all__ = [
    "CONFIG_KEYS",
    "measure_config",
    "run_sweep",
    "sweep_grid",
]
