"""Serving profile artifact (SPF1) + the cost model the planner walks.

The offline profiler (``profiler_sweep.py``) sweeps a live generate
engine through a config grid and prices every config as measured
(tokens/s, TTFT/TPOT quantiles, HBM footprint, compile census,
device-time split). That grid persists as ONE versioned, CRC-framed
artifact — ``SPF1``, a sibling of the KV-slab (SKV1), generate
checkpoint (SGC1) and weight-pager (SWP1) frames, with the same typed
refusals: short frame → :class:`~..serving.disagg.TruncatedStream`,
bit flip → :class:`~..serving.disagg.ChecksumError`, wrong magic /
version / malformed grid → :class:`ProfileError`. A corrupt profile
must refuse BEFORE the planner acts on it — a half-read cost model
steering live retunes is strictly worse than no planner at all.

:class:`CostModel` answers the two questions the online planner asks:

* ``price(config)`` — the measured entry for a swept config (exact
  match only; the planner never extrapolates a retune target it has
  no measurement for).
* ``predict(config)`` — an InferLine-style analytic fit for ranking
  between measured points: per-token time is modeled as
  ``t_step + floor / max(1, fused_k)`` (a per-dispatch floor amortized
  over the fused burst), HBM as ``base + slots * per_slot_bytes``.
  Both fits are clamped non-negative, which makes the two planner-load
  monotonicities structural: predicted tokens/s never decreases in
  fused K, predicted HBM never decreases in slots
  (tests/test_planning.py asserts both).

``best(...)`` walks the measured grid under TTFT/TPOT p99 objectives
and an optional HBM budget and returns the highest-throughput config
that meets them — or, when nothing does, the one with the smallest
worst breach ratio, flagged ``meets=False`` so the planner can treat
it as a scale signal instead of a retune.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..serving.disagg import ChecksumError, DisaggError, TruncatedStream

MAGIC = b"SPF1"
PROFILE_VERSION = 1

# the knobs a profile grid entry is keyed on — the sweep axes. Order is
# the canonical config identity (``config_key``); every grid entry must
# carry every key so two profiles are always comparable.
CONFIG_KEYS = (
    "slots",
    "prefill_chunk",
    "fused_steps_per_dispatch",
    "depth_groups",
    "depth_group_split_bytes",
    "kv_tier_bytes",
)

# the measured prices every grid entry must carry
PRICE_KEYS = (
    "tokens_per_s",
    "ttft_p50_ms",
    "ttft_p99_ms",
    "tpot_p50_ms",
    "tpot_p99_ms",
    "hbm_bytes",
)


class ProfileError(DisaggError):
    """A profile frame parsed but is not a usable SPF1 artifact (bad
    magic, wrong version, malformed grid). Typed so callers can tell
    "corrupt file" from "wire truncation" from "bit flip"."""


def config_key(config: Dict[str, Any]) -> Tuple:
    """Canonical identity of one swept config (CONFIG_KEYS order)."""
    return tuple(config.get(k) for k in CONFIG_KEYS)


def normalize_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Fill every CONFIG_KEYS slot (missing -> 0) and drop extras, so
    sweep grids written by different drivers stay comparable."""
    return {k: int(config.get(k) or 0) for k in CONFIG_KEYS}


def validate_profile(profile: Dict[str, Any]) -> Dict[str, Any]:
    """Structural validation shared by encode and decode — a profile
    that cannot steer the planner refuses here, typed, on BOTH sides
    (writing a bad artifact is as much a bug as reading one)."""
    if not isinstance(profile, dict):
        raise ProfileError(f"profile must be a dict, got {type(profile).__name__}")
    if profile.get("v") != PROFILE_VERSION:
        raise ProfileError(f"unsupported profile version {profile.get('v')!r}")
    fam = profile.get("model_family")
    if not fam or not isinstance(fam, str):
        raise ProfileError(f"profile needs a model_family, got {fam!r}")
    mesh = profile.get("mesh_shape")
    if mesh is not None and not isinstance(mesh, dict):
        raise ProfileError(f"mesh_shape must be a dict or null, got {mesh!r}")
    grid = profile.get("grid")
    if not isinstance(grid, list) or not grid:
        raise ProfileError("profile grid is empty — nothing to plan over")
    seen = set()
    for i, entry in enumerate(grid):
        if not isinstance(entry, dict):
            raise ProfileError(f"grid[{i}] is not a dict")
        cfg = entry.get("config")
        if not isinstance(cfg, dict):
            raise ProfileError(f"grid[{i}] has no config dict")
        for k in CONFIG_KEYS:
            v = cfg.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise ProfileError(
                    f"grid[{i}].config[{k!r}] must be an int >= 0, got {v!r}"
                )
        key = config_key(cfg)
        if key in seen:
            raise ProfileError(f"grid[{i}] duplicates config {dict(cfg)}")
        seen.add(key)
        for k in PRICE_KEYS:
            v = entry.get(k)
            if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
                raise ProfileError(
                    f"grid[{i}].{k} must be a number >= 0, got {v!r}"
                )
    return profile


def encode_profile(profile: Dict[str, Any]) -> bytes:
    """One SPF1 frame: magic | length | CRC | JSON payload."""
    validate_profile(profile)
    payload = json.dumps(profile, separators=(",", ":"), sort_keys=True).encode()
    return MAGIC + struct.pack("<II", len(payload), zlib.crc32(payload)) + payload


def decode_profile(data: bytes) -> Dict[str, Any]:
    """Decode + validate one SPF1 frame. Typed refusals BEFORE the
    planner can act: short buffer → :class:`~..serving.disagg.TruncatedStream`,
    CRC mismatch → :class:`~..serving.disagg.ChecksumError`, bad
    magic / version / grid → :class:`ProfileError`."""
    if len(data) < 12:
        raise TruncatedStream(f"profile frame is {len(data)} bytes, need >= 12")
    if data[:4] != MAGIC:
        raise ProfileError(f"bad profile magic {data[:4]!r} (want {MAGIC!r})")
    n, crc = struct.unpack("<II", data[4:12])
    payload = data[12:12 + n]
    if len(payload) < n:
        raise TruncatedStream(f"profile payload is {len(payload)} of {n} bytes")
    if zlib.crc32(payload) != crc:
        raise ChecksumError("profile frame failed its checksum")
    try:
        profile = json.loads(payload)
    except ValueError as e:
        raise ProfileError(f"profile payload is not JSON: {e}") from e
    return validate_profile(profile)


def write_profile(path: str, profile: Dict[str, Any]) -> None:
    with open(path, "wb") as f:
        f.write(encode_profile(profile))


def read_profile(path: str) -> Dict[str, Any]:
    with open(path, "rb") as f:
        return decode_profile(f.read())


class CostModel:
    """Measured grid + clamped analytic fit over one decoded profile."""

    def __init__(self, profile: Dict[str, Any]):
        self.profile = validate_profile(profile)
        self.grid: List[Dict[str, Any]] = list(profile["grid"])
        self._by_key = {config_key(e["config"]): e for e in self.grid}
        self._fit_throughput()
        self._fit_hbm()

    # -- fits ---------------------------------------------------------------

    def _fit_throughput(self) -> None:
        # least squares of 1/tps = t_step + floor * (1/k_eff) over the
        # measured grid; k_eff = max(1, fused K). Clamping both
        # coefficients at >= 0 is what makes predict() monotone in K.
        pts = []
        for e in self.grid:
            tps = float(e["tokens_per_s"])
            if tps <= 0:
                continue
            k_eff = max(1, int(e["config"]["fused_steps_per_dispatch"]))
            pts.append((1.0 / k_eff, 1.0 / tps))
        if not pts:
            self._t_step, self._floor = 1e-3, 0.0
            return
        n = len(pts)
        mx = sum(x for x, _ in pts) / n
        my = sum(y for _, y in pts) / n
        sxx = sum((x - mx) ** 2 for x, _ in pts)
        sxy = sum((x - mx) * (y - my) for x, y in pts)
        floor = (sxy / sxx) if sxx > 0 else 0.0
        floor = max(0.0, floor)
        t_step = max(1e-9, my - floor * mx)
        self._t_step, self._floor = t_step, floor

    def _fit_hbm(self) -> None:
        # hbm = base + slots * per_slot, per_slot clamped >= 0 so
        # predicted footprint is monotone in slots.
        pts = [(int(e["config"]["slots"]), float(e["hbm_bytes"])) for e in self.grid]
        n = len(pts)
        mx = sum(x for x, _ in pts) / n
        my = sum(y for _, y in pts) / n
        sxx = sum((x - mx) ** 2 for x, _ in pts)
        sxy = sum((x - mx) * (y - my) for x, y in pts)
        per_slot = (sxy / sxx) if sxx > 0 else 0.0
        per_slot = max(0.0, per_slot)
        self._hbm_base = max(0.0, my - per_slot * mx)
        self._hbm_per_slot = per_slot

    # -- queries ------------------------------------------------------------

    def price(self, config: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """The measured grid entry for ``config`` (exact match), or
        None — the planner only retunes toward measured points."""
        return self._by_key.get(config_key(normalize_config(config)))

    def predict(self, config: Dict[str, Any]) -> Dict[str, float]:
        """Analytic prices for an unswept config (ranking only — never
        a retune target by itself)."""
        cfg = normalize_config(config)
        k_eff = max(1, cfg["fused_steps_per_dispatch"])
        per_token_s = self._t_step + self._floor / k_eff
        return {
            "tokens_per_s": 1.0 / per_token_s,
            "hbm_bytes": self._hbm_base + self._hbm_per_slot * cfg["slots"],
        }

    def best(
        self,
        ttft_p99_ms: Optional[float] = None,
        tpot_p99_ms: Optional[float] = None,
        hbm_budget_bytes: Optional[int] = None,
        require: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Highest-throughput measured config meeting the objectives
        (``meets=True``), else the smallest-worst-breach one
        (``meets=False`` — a scale signal, not a retune target).
        ``require`` pins config keys (e.g. the boot census only admits
        one prefill_chunk value — out-of-census retunes are refused by
        the batcher anyway, so don't even rank them)."""
        candidates = []
        for e in self.grid:
            cfg = e["config"]
            if require and any(
                cfg.get(k) != v for k, v in require.items() if v is not None
            ):
                continue
            if hbm_budget_bytes is not None and e["hbm_bytes"] > hbm_budget_bytes:
                continue
            breach = 0.0
            if ttft_p99_ms is not None and ttft_p99_ms > 0:
                breach = max(breach, e["ttft_p99_ms"] / ttft_p99_ms)
            if tpot_p99_ms is not None and tpot_p99_ms > 0:
                breach = max(breach, e["tpot_p99_ms"] / tpot_p99_ms)
            candidates.append((breach, e))
        if not candidates:
            raise ProfileError(
                "no profile entry satisfies the hard constraints "
                f"(require={require!r}, hbm_budget={hbm_budget_bytes!r})"
            )
        meeting = [e for breach, e in candidates if breach <= 1.0]
        if meeting:
            # deterministic: max tokens/s, ties broken by fewer slots
            # then the canonical config key
            win = max(
                meeting,
                key=lambda e: (
                    e["tokens_per_s"],
                    -e["config"]["slots"],
                    tuple(-(v or 0) for v in config_key(e["config"])),
                ),
            )
            return {"meets": True, "entry": win, "config": dict(win["config"])}
        breach, win = min(candidates, key=lambda be: (be[0], config_key(be[1]["config"])))
        return {
            "meets": False,
            "entry": win,
            "config": dict(win["config"]),
            "worst_breach": round(breach, 4),
        }

    # -- fusion cost gate ----------------------------------------------------

    def fusion_gate(self, expected_dispatches: int = 100_000) -> Dict[str, float]:
        """The compile-cost-vs-dispatch-savings gate the graph fusion
        planner consumes (graph/fusion.py): the profile's dispatch
        floor (fitted above, us per dispatch) and the measured compile
        census cost per executable variant, amortized over the
        expected dispatch count."""
        census_s = []
        for e in self.grid:
            cc = e.get("compile_census") or {}
            v, t = cc.get("variants"), cc.get("compile_s")
            if v and t is not None and v > 0:
                census_s.append(float(t) / float(v))
        per_variant_s = (sum(census_s) / len(census_s)) if census_s else 0.0
        return {
            "dispatch_floor_us": self._floor * 1e6,
            "compile_cost_s": per_variant_s,
            "expected_dispatches": int(expected_dispatches),
        }


def build_profile(
    model_family: str,
    grid: Sequence[Dict[str, Any]],
    mesh_shape: Optional[Dict[str, int]] = None,
    created: Optional[str] = None,
) -> Dict[str, Any]:
    """Assemble + validate a profile dict from sweep measurements."""
    return validate_profile({
        "v": PROFILE_VERSION,
        "model_family": str(model_family),
        "mesh_shape": dict(mesh_shape) if mesh_shape else None,
        "created": created,
        "grid": list(grid),
    })
