"""Autonomic serving planner: offline profile sweep -> SPF1 cost model
-> online decision table -> safe actuation (retune / scale), plus the
seeded traffic simulator that makes the closed loop a reproducible
bench scenario (docs/operate.md §"Autonomic planning")."""

from .artifact import (
    CONFIG_KEYS,
    CostModel,
    ProfileError,
    build_profile,
    decode_profile,
    encode_profile,
    read_profile,
    write_profile,
)
from .planner import Decision, RETUNABLE_AXES, ServingPlanner
from .profiler_sweep import measure_config, run_sweep, sweep_grid
from .trafficsim import TrafficEvent, TrafficSim, replay

__all__ = [
    "CONFIG_KEYS",
    "CostModel",
    "Decision",
    "ProfileError",
    "RETUNABLE_AXES",
    "ServingPlanner",
    "TrafficEvent",
    "TrafficSim",
    "build_profile",
    "decode_profile",
    "encode_profile",
    "measure_config",
    "read_profile",
    "replay",
    "run_sweep",
    "sweep_grid",
    "write_profile",
]
