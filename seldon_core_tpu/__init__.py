"""seldon_core_tpu: a TPU-native model-serving framework.

A ground-up re-design of the Seldon Core feature set
(reference: /root/reference, Seldon Core v0.5) for Cloud TPU:

  * wire contract with a zero-copy RawTensor encoding  (`proto/`, `payload`)
  * microservice runtime wrapping user components      (`user_model`, `wrapper`,
    `microservice`) — predict() is a jit-compiled XLA executable
  * inference-graph engine with routers/combiners/
    transformers, dynamic micro-batching, feedback     (`graph/`)
  * prepackaged model servers                          (`servers/`)
  * bandit routers & outlier detectors                 (`routers/`, `outliers/`)
  * flagship JAX models (ResNet-50, BERT, LLM)         (`models/`)
  * Pallas TPU kernels                                 (`ops/`)
  * mesh parallelism: dp/tp/pp/sp/ep + ring attention  (`parallel/`)
  * deployment schema + local scheduler                (`deploy/`)
"""

__version__ = "0.1.0"

from . import metrics, payload, seldon_methods, user_model  # noqa: F401
from .user_model import JAXComponent, SeldonComponent  # noqa: F401
