"""Resource store: the control plane's stand-in for the K8s API server.

Apply/get/list/delete with generation bumps on spec change, async watch
streams feeding the reconciler (reference: controller-runtime watches with
owner references, operator/controllers/seldondeployment_controller.go:
1129-1199), and optional JSON-file persistence so `sdctl` CLI invocations
and a long-running controller share state.
"""

from __future__ import annotations

import asyncio
import contextlib
import fcntl
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from .resource import SeldonDeployment

EVENT_ADDED = "ADDED"
EVENT_MODIFIED = "MODIFIED"
EVENT_DELETED = "DELETED"


class ResourceStore:
    def __init__(self, persist_dir: Optional[str] = None):
        self._items: Dict[str, SeldonDeployment] = {}
        self._lock = threading.Lock()
        self._watchers: List[asyncio.Queue] = []
        self._persist_dir = persist_dir
        if persist_dir:
            os.makedirs(persist_dir, exist_ok=True)
            self._load()

    # -- persistence --------------------------------------------------------

    def _path(self, key: str) -> str:
        assert self._persist_dir
        return os.path.join(self._persist_dir, key.replace("/", "__") + ".json")

    def _load(self) -> None:
        for fn in os.listdir(self._persist_dir):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(self._persist_dir, fn)) as f:
                    d = json.load(f)
                dep = SeldonDeployment.from_dict(d)
                dep.generation = (d.get("metadata") or {}).get("generation", 1)
                if "status" in d:
                    from .resource import DeploymentStatus

                    dep.status = DeploymentStatus.from_dict(d["status"])
            except Exception as e:  # noqa: BLE001 - a torn write or schema
                # drift in one file must not brick the whole control plane
                import logging

                logging.getLogger(__name__).warning("skipping unreadable %s: %s", fn, e)
                continue
            self._items[dep.key] = dep

    @staticmethod
    @contextlib.contextmanager
    def _file_lock(path: str):
        """Cross-process exclusive lock scoped to one store file, so a CLI
        ``apply`` and a controller status write serialize their
        read-modify-write cycles instead of clobbering each other."""
        with open(path + ".lock", "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

    @staticmethod
    def _write_json(path: str, doc: dict) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, path)  # atomic: readers never see a torn file

    def _persist(self, dep: SeldonDeployment) -> None:
        if self._persist_dir:
            path = self._path(dep.key)
            with self._file_lock(path):
                self._write_json(path, dep.to_dict())

    def _unpersist(self, key: str) -> None:
        if self._persist_dir and os.path.exists(self._path(key)):
            os.remove(self._path(key))

    # -- api ----------------------------------------------------------------

    def apply(self, dep: SeldonDeployment) -> Tuple[SeldonDeployment, str]:
        """Create or update; bumps generation when the spec changed
        (no-op applies do not retrigger reconcile, like jsonEquals at
        seldondeployment_controller.go:842-853)."""
        with self._lock:
            existing = self._items.get(dep.key)
            if existing is None:
                dep.generation = 1
                self._items[dep.key] = dep
                self._persist(dep)
                event = EVENT_ADDED
            elif existing.spec_hash() == dep.spec_hash() and existing.annotations == dep.annotations:
                return existing, "UNCHANGED"
            else:
                dep.generation = existing.generation + 1
                dep.status = existing.status
                self._items[dep.key] = dep
                self._persist(dep)
                event = EVENT_MODIFIED
        self._notify(event, dep)
        return dep, event

    def get(self, name: str, namespace: str = "default") -> Optional[SeldonDeployment]:
        return self._items.get(f"{namespace}/{name}")

    def list(self, namespace: Optional[str] = None) -> List[SeldonDeployment]:
        return [
            d for d in self._items.values() if namespace is None or d.namespace == namespace
        ]

    def delete(self, name: str, namespace: str = "default") -> bool:
        key = f"{namespace}/{name}"
        with self._lock:
            dep = self._items.pop(key, None)
            if dep is None:
                return False
            self._unpersist(key)
        self._notify(EVENT_DELETED, dep)
        return True

    def update_status(self, dep: SeldonDeployment) -> None:
        """Status-only write: no generation bump, no reconcile retrigger.

        Persists via read-merge-write of only the ``status`` field, under
        the per-file flock, so a concurrent CLI ``apply`` that already
        wrote a newer spec to the store file is not clobbered by an
        in-flight reconcile's status rollup (the rescan would otherwise
        see no diff and drop the apply).
        """
        with self._lock:
            if dep.key not in self._items:
                return
            self._items[dep.key].status = dep.status
            if not self._persist_dir:
                return
            path = self._path(dep.key)
            with self._file_lock(path):
                doc = None
                if os.path.exists(path):
                    try:
                        with open(path) as f:
                            doc = json.load(f)
                    except Exception:  # torn write: rewrite from memory
                        doc = None
                if doc is None:
                    doc = self._items[dep.key].to_dict()
                doc["status"] = dep.status.to_dict()
                self._write_json(path, doc)

    # -- watch --------------------------------------------------------------

    def watch(self) -> asyncio.Queue:
        """Subscribe to (event, deployment) tuples; caller consumes the
        queue from its own event loop."""
        q: asyncio.Queue = asyncio.Queue()
        self._watchers.append(q)
        return q

    def unwatch(self, q: asyncio.Queue) -> None:
        if q in self._watchers:
            self._watchers.remove(q)

    def _notify(self, event: str, dep: SeldonDeployment) -> None:
        for q in list(self._watchers):
            q.put_nowait((event, dep))
