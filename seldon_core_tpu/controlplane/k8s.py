"""Kubernetes manifest renderer: SeldonDeployment -> Deployments/Services/
HPAs/VirtualService for GKE TPU node pools.

The reference materializes these objects imperatively inside its Go
controller (reference: operator/controllers/seldondeployment_controller.go:
855-1122 createDeployments/createServices/createHpas, engine sidecar
injection seldondeployment_engine.go:101-214, Istio canary routing
istio.go + seldondeployment_controller.go:113-224). The TPU-native
control plane in this repo is self-hosted (reconciler.py), so the K8s
path is a *renderer*: ``sdctl render -f dep.json`` emits the YAML an
operator would have applied, letting a cluster (GKE + TPU node pools)
run the same SeldonDeployment without the in-process runtime.

TPU-first redesign notes (vs the reference's output):

* **One pod per predictor replica, whole graph inside.** The reference
  spreads graph units across pods and fans out over the pod network; on
  TPU the engine hosts in-process units sharing one device mesh (ICI
  locality — graph hops are function calls, not network hops), so the
  unit of K8s scheduling is the predictor, not the unit.
* **TPU node-pool scheduling** comes from the predictor's ``tpuMesh``:
  chips = prod(mesh axes) -> ``google.com/tpu`` resource +
  ``cloud.google.com/gke-tpu-accelerator``/``-topology`` selectors and
  the TPU taint toleration.
* **Multi-host slices render as a StatefulSet** + headless Service with
  stable worker identities (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES), the
  GKE multi-host TPU recipe — the reference has no analogue.
* **Exact preStop drain**: ``/pause`` then poll ``/inflight`` to zero
  (the engine exposes an exact gauge) instead of the reference's blind
  ``curl /pause; sleep 10`` (seldondeployment_engine.go:173-177).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..graph.spec import PredictorSpec, default_predictor
from .resource import SeldonDeployment

ENGINE_HTTP_PORT = 8000
ENGINE_GRPC_PORT = 5001

# single-host chip counts for common TPU generations; beyond the per-host
# count a slice spans hosts and renders as a StatefulSet
_DEFAULT_CHIPS_PER_HOST = 4
# v5e/v5p-style 2D slice topologies by chip count
_TOPOLOGY = {1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4", 32: "4x8",
             64: "8x8", 128: "8x16", 256: "16x16"}

ANNOTATION_ENGINE_IMAGE = "seldon.io/engine-image"
ANNOTATION_TPU_ACCELERATOR = "seldon.io/tpu-accelerator"
ANNOTATION_TPU_CHIPS_PER_HOST = "seldon.io/tpu-chips-per-host"
ANNOTATION_ENGINE_CPU = "seldon.io/engine-cpu"
# reference: getEngineEnvAnnotations / ANNOTATION_JAVA_OPTS idiom — any
# annotation under this prefix becomes an engine-container env var
ENGINE_ENV_ANNOTATION_PREFIX = "seldon.io/engine-env-"

DEFAULT_ENGINE_IMAGE = "ghcr.io/seldon-core-tpu/engine:latest"
DEFAULT_TPU_ACCELERATOR = "tpu-v5-lite-podslice"


def _chips(mesh: Dict[str, int]) -> int:
    n = 1
    for v in mesh.values():
        n *= int(v)
    return n


def _topology_for(chips: int) -> str:
    if chips in _TOPOLOGY:
        return _TOPOLOGY[chips]
    raise ValueError(
        f"no standard slice topology for {chips} chips; "
        f"supported: {sorted(_TOPOLOGY)}"
    )


def _labels(dep: SeldonDeployment, p: PredictorSpec) -> Dict[str, str]:
    """Selector labels (reference: createComponents labels app.kubernetes.io
    + seldon-deployment-id, seldondeployment_controller.go:509-511)."""
    return {
        "app.kubernetes.io/managed-by": "seldon-core-tpu",
        "seldon-deployment-id": dep.name,
        "seldon-predictor": p.name,
    }


def _meta(name: str, dep: SeldonDeployment, p: Optional[PredictorSpec] = None,
          extra_labels: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    labels = dict(dep.labels)
    if p is not None:
        labels.update(_labels(dep, p))
        labels.update(p.labels)
    if extra_labels:
        labels.update(extra_labels)
    # every rendered object is findable by owner: the kube controller prunes
    # orphans via these two labels (reference does it with ownerReferences +
    # the GC, seldondeployment_controller.go:1129-1199 owner-indexed watches)
    labels.setdefault("app.kubernetes.io/managed-by", "seldon-core-tpu")
    labels.setdefault("seldon-deployment-id", dep.name)
    meta: Dict[str, Any] = {"name": name, "namespace": dep.namespace}
    if labels:
        meta["labels"] = labels
    if dep.annotations:
        meta["annotations"] = dict(dep.annotations)
    return meta


def _engine_container(dep: SeldonDeployment, p: PredictorSpec) -> Dict[str, Any]:
    """The engine container (reference: createEngineContainer
    seldondeployment_engine.go:101-214 — env names, probe cadence and the
    traffic-zeroed ENGINE_PREDICTOR are kept for parity; JAVA_OPTS and the
    jmx/admin ports have no TPU counterpart)."""
    p_env = PredictorSpec.from_dict(p.to_dict())  # deep copy
    p_env.traffic = 0  # reference parity: zero so canary flips don't re-roll pods
    env = [
        {"name": "ENGINE_PREDICTOR", "value": p_env.to_env_b64()},
        {"name": "DEPLOYMENT_NAME", "value": dep.name},
        {"name": "DEPLOYMENT_NAMESPACE", "value": dep.namespace},
        {"name": "ENGINE_SERVER_PORT", "value": str(ENGINE_HTTP_PORT)},
        {"name": "ENGINE_SERVER_GRPC_PORT", "value": str(ENGINE_GRPC_PORT)},
    ]
    seen = {e["name"] for e in env}
    ann = {**dep.annotations, **p.annotations}
    for key, value in sorted(ann.items()):
        if key.startswith(ENGINE_ENV_ANNOTATION_PREFIX):
            name = key[len(ENGINE_ENV_ANNOTATION_PREFIX):].upper().replace("-", "_")
            if name not in seen:
                env.append({"name": name, "value": value})
                seen.add(name)
    if "SELDON_LOG_MESSAGES_EXTERNALLY" not in seen:
        env.append({"name": "SELDON_LOG_MESSAGES_EXTERNALLY", "value": "false"})
    drain = (
        f"curl -s 127.0.0.1:{ENGINE_HTTP_PORT}/pause; "
        f"for i in $(seq 1 60); do "
        f'[ "$(curl -s 127.0.0.1:{ENGINE_HTTP_PORT}/inflight)" = "0" ] && break; '
        f"sleep 1; done"
    )
    return {
        "name": "seldon-engine",
        "image": ann.get(ANNOTATION_ENGINE_IMAGE, DEFAULT_ENGINE_IMAGE),
        "command": ["seldon-tpu-engine"],
        "env": env,
        "ports": [
            {"containerPort": ENGINE_HTTP_PORT, "name": "http", "protocol": "TCP"},
            {"containerPort": ENGINE_GRPC_PORT, "name": "grpc", "protocol": "TCP"},
        ],
        "readinessProbe": {
            "httpGet": {"path": "/ready", "port": "http", "scheme": "HTTP"},
            "initialDelaySeconds": 20, "periodSeconds": 5,
            "failureThreshold": 3, "successThreshold": 1, "timeoutSeconds": 60,
        },
        "livenessProbe": {
            "httpGet": {"path": "/live", "port": "http", "scheme": "HTTP"},
            "initialDelaySeconds": 20, "periodSeconds": 5,
            "failureThreshold": 3, "successThreshold": 1, "timeoutSeconds": 60,
        },
        "lifecycle": {"preStop": {"exec": {"command": ["/bin/sh", "-c", drain]}}},
        "resources": {
            "requests": {"cpu": ann.get(ANNOTATION_ENGINE_CPU, "0.1")},
        },
    }


def _tpu_scheduling(p: PredictorSpec, ann: Dict[str, str]) -> Dict[str, Any]:
    """nodeSelector/tolerations/resources for a GKE TPU node pool
    (SURVEY §7.6: topology-aware placement, google.com/tpu resources)."""
    chips = _chips(p.tpu_mesh or {})
    chips_per_host = int(ann.get(ANNOTATION_TPU_CHIPS_PER_HOST, _DEFAULT_CHIPS_PER_HOST))
    per_pod = min(chips, chips_per_host)
    return {
        "chips": chips,
        "hosts": max(1, -(-chips // chips_per_host)),
        "nodeSelector": {
            "cloud.google.com/gke-tpu-accelerator": ann.get(
                ANNOTATION_TPU_ACCELERATOR, DEFAULT_TPU_ACCELERATOR
            ),
            "cloud.google.com/gke-tpu-topology": _topology_for(chips),
        },
        "tolerations": [
            {"key": "google.com/tpu", "operator": "Exists", "effect": "NoSchedule"}
        ],
        "resources": {"google.com/tpu": str(per_pod)},
    }


def _pod_spec(dep: SeldonDeployment, p: PredictorSpec) -> Dict[str, Any]:
    container = _engine_container(dep, p)
    pod: Dict[str, Any] = {"containers": [container], "terminationGracePeriodSeconds": 90}
    if p.tpu_mesh:
        sched = _tpu_scheduling(p, {**dep.annotations, **p.annotations})
        pod["nodeSelector"] = sched["nodeSelector"]
        pod["tolerations"] = sched["tolerations"]
        limits = container.setdefault("resources", {}).setdefault("limits", {})
        limits.update(sched["resources"])
    return pod


def _workload(dep: SeldonDeployment, p: PredictorSpec) -> List[Dict[str, Any]]:
    """Deployment for single-host predictors, StatefulSet (+ headless
    Service) for multi-host TPU slices."""
    name = f"{dep.name}-{p.name}"
    labels = _labels(dep, p)
    pod = _pod_spec(dep, p)
    # seldon-traffic rides only on the POD template (not the per-predictor
    # selector, which predates it): it lets the deployment-wide Service
    # backing the VirtualService host select live pods while excluding
    # shadow predictors from default routing.
    traffic = {"seldon-traffic": "shadow" if _is_shadow(p) else "live"}
    template = {
        "metadata": {"labels": {**labels, **traffic, **p.labels}},
        "spec": pod,
    }
    ann = {**dep.annotations, **p.annotations}
    multihost = False
    if p.tpu_mesh:
        sched = _tpu_scheduling(p, ann)
        multihost = sched["hosts"] > 1
    if multihost and p.replicas > 1:
        raise ValueError(
            f"predictor {p.name!r}: replicas={p.replicas} with a multi-host "
            f"tpuMesh is not renderable — a StatefulSet models ONE slice "
            f"(its replicas are slice workers); deploy one SeldonDeployment "
            f"per serving replica, or use a single-host mesh"
        )
    if multihost and p.hpa_spec:
        raise ValueError(
            f"predictor {p.name!r}: hpaSpec with a multi-host tpuMesh is not "
            f"renderable — an HPA would resize slice WORKERS and break the "
            f"slice; scale multi-host predictors by whole slices"
        )
    if not multihost:
        return [{
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": _meta(name, dep, p),
            "spec": {
                "replicas": p.replicas,
                "selector": {"matchLabels": labels},
                "template": template,
            },
        }]
    # multi-host slice: every worker needs a stable identity so the TPU
    # runtime forms the slice; pod-index label is the ordinal (k8s >=1.28)
    sched = _tpu_scheduling(p, ann)
    hosts = sched["hosts"]
    headless = f"{name}-workers"
    hostnames = ",".join(
        f"{name}-{i}.{headless}.{dep.namespace}.svc" for i in range(hosts)
    )
    env = template["spec"]["containers"][0]["env"]
    env.append({"name": "TPU_WORKER_HOSTNAMES", "value": hostnames})
    env.append({
        "name": "TPU_WORKER_ID",
        "valueFrom": {"fieldRef": {
            "fieldPath": "metadata.labels['apps.kubernetes.io/pod-index']"
        }},
    })
    return [
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta(headless, dep, p),
            "spec": {"clusterIP": "None", "selector": labels,
                     "ports": [{"name": "http", "port": ENGINE_HTTP_PORT}]},
        },
        {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": _meta(name, dep, p),
            "spec": {
                # replicas here are slice WORKERS, not serving replicas:
                # one slice = hosts pods acting as one model instance
                "replicas": hosts,
                "podManagementPolicy": "Parallel",
                "serviceName": headless,
                "selector": {"matchLabels": labels},
                "template": template,
            },
        },
    ]


def _engine_service_ports() -> List[Dict[str, Any]]:
    """The one place the engine Service ports live — per-predictor and
    deployment-wide Services must stay in lockstep."""
    return [
        {"name": "http", "port": ENGINE_HTTP_PORT,
         "targetPort": ENGINE_HTTP_PORT, "protocol": "TCP"},
        {"name": "grpc", "port": ENGINE_GRPC_PORT,
         "targetPort": ENGINE_GRPC_PORT, "protocol": "TCP"},
    ]


def _service(dep: SeldonDeployment, p: PredictorSpec) -> Dict[str, Any]:
    """Per-predictor Service (reference: createServices
    seldondeployment_controller.go:747-803)."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(f"{dep.name}-{p.name}", dep, p),
        "spec": {
            "selector": _labels(dep, p),
            "ports": _engine_service_ports(),
        },
    }


def _hpa(dep: SeldonDeployment, p: PredictorSpec) -> Optional[Dict[str, Any]]:
    """HPA from hpaSpec (reference: createHpas
    seldondeployment_controller.go:805-853). The TPU-native metric is
    in-flight concurrency per engine replica — the engine exports
    seldon_engine_inflight on /prometheus (engine_metrics.py), scraped
    into a Pods metric."""
    if not p.hpa_spec:
        return None
    from ..graph.spec import parse_hpa_spec

    lo, hi, target = parse_hpa_spec(p.hpa_spec, who=f"{dep.name}/{p.name}")
    return {
        "apiVersion": "autoscaling/v2",
        "kind": "HorizontalPodAutoscaler",
        "metadata": _meta(f"{dep.name}-{p.name}", dep, p),
        "spec": {
            "scaleTargetRef": {
                "apiVersion": "apps/v1", "kind": "Deployment",
                "name": f"{dep.name}-{p.name}",
            },
            "minReplicas": lo,
            "maxReplicas": hi,
            "metrics": [{
                "type": "Pods",
                "pods": {
                    "metric": {"name": "seldon_engine_inflight"},
                    # k8s quantity syntax: integral values must not carry
                    # a decimal point; fractional targets use milli-units
                    "target": {
                        "type": "AverageValue",
                        "averageValue": (
                            str(int(target)) if float(target).is_integer()
                            else f"{int(float(target) * 1000)}m"
                        ),
                    },
                },
            }],
            # mirror the reconciler's scale-down stabilization streak
            "behavior": {"scaleDown": {"stabilizationWindowSeconds": 300}},
        },
    }


def _is_shadow(p: PredictorSpec) -> bool:
    return p.annotations.get("seldon.io/shadow", "false") == "true"


def _deployment_service(dep: SeldonDeployment) -> Dict[str, Any]:
    """ClusterIP Service named after the DEPLOYMENT, backing the
    VirtualService host: without it '<dep>.<ns>.svc.cluster.local' has no
    DNS entry and in-mesh clients can never reach the canary weights
    (the reference instead binds its VS to an Istio gateway with
    hosts:["*"], seldondeployment_controller.go:126-148). Selector spans
    every LIVE predictor via the pod-template-only seldon-traffic label,
    so shadows receive mirrored traffic only."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(dep.name, dep),
        "spec": {
            "selector": {
                "app.kubernetes.io/managed-by": "seldon-core-tpu",
                "seldon-deployment-id": dep.name,
                "seldon-traffic": "live",
            },
            "ports": _engine_service_ports(),
        },
    }


def _virtual_service(dep: SeldonDeployment) -> Optional[Dict[str, Any]]:
    """Istio VirtualService carrying the canary weights and shadow mirror
    (reference: createIstioResources seldondeployment_controller.go:113-224;
    shadow == Gateway mirroring in ingress.py)."""
    is_shadow = _is_shadow

    live = [p for p in dep.predictors if not is_shadow(p)]
    shadows = [p for p in dep.predictors if is_shadow(p)]
    if len(live) < 2 and not shadows:
        return None
    total = sum(p.traffic for p in live)

    def rule_for_port(port: int) -> Dict[str, Any]:
        routes = []
        for p in live:
            # no explicit weights -> even split (webhook-default parity)
            weight = p.traffic if total else 100 // len(live)
            routes.append({
                "destination": {
                    "host": (f"{dep.name}-{p.name}.{dep.namespace}"
                             ".svc.cluster.local"),
                    # subset pairs with the predictor's DestinationRule —
                    # on a mesh running mTLS/subset policies a bare host
                    # route is not routable (reference: HTTPRouteDestination
                    # {Host, Subset}, seldondeployment_controller.go:196-215)
                    "subset": p.name,
                    "port": {"number": port},
                },
                "weight": weight,
            })
        # weights must sum to 100 for Istio; pad the first route
        pad = 100 - sum(r["weight"] for r in routes)
        if routes and pad:
            routes[0]["weight"] += pad
        # port-scoped match: a port-free http rule would apply to EVERY
        # HTTP/gRPC port of the host, sending grpc:5001 traffic to the
        # REST port's destination
        rule: Dict[str, Any] = {"match": [{"port": port}], "route": routes}
        if shadows:
            s = shadows[0]
            rule["mirror"] = {
                "host": f"{dep.name}-{s.name}.{dep.namespace}.svc.cluster.local",
                "subset": s.name,
                "port": {"number": port},
            }
            rule["mirrorPercentage"] = {"value": 100.0}
        return rule

    return {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "VirtualService",
        "metadata": _meta(dep.name, dep),
        "spec": {
            "hosts": [f"{dep.name}.{dep.namespace}.svc.cluster.local"],
            "http": [rule_for_port(ENGINE_HTTP_PORT),
                     rule_for_port(ENGINE_GRPC_PORT)],
        },
    }


def _destination_rules(dep: SeldonDeployment) -> List[Dict[str, Any]]:
    """One DestinationRule per predictor: subset named after the predictor
    selecting its pods, mTLS ISTIO_MUTUAL so the canary weights route on
    a mesh with strict TLS (reference: createIstioResources' drules,
    seldondeployment_controller.go:171-193 — there the subset label is
    ``version``; here the renderer's own ``seldon-predictor`` pod label is
    the discriminator, present on every rendered pod template)."""
    rules = []
    for p in dep.predictors:
        host = f"{dep.name}-{p.name}.{dep.namespace}.svc.cluster.local"
        rules.append({
            "apiVersion": "networking.istio.io/v1beta1",
            "kind": "DestinationRule",
            "metadata": _meta(f"{dep.name}-{p.name}", dep, p),
            "spec": {
                "host": host,
                "trafficPolicy": {"tls": {"mode": "ISTIO_MUTUAL"}},
                "subsets": [
                    {
                        "name": p.name,
                        "labels": {"seldon-predictor": p.name},
                    }
                ],
            },
        })
    return rules


def render(dep: SeldonDeployment) -> List[Dict[str, Any]]:
    """SeldonDeployment -> ordered manifest list (workloads, services,
    HPAs, then routing), webhook-defaulted first like the operator."""
    manifests: List[Dict[str, Any]] = []
    defaulted = []
    for p in dep.predictors:
        defaulted.append(default_predictor(PredictorSpec.from_dict(p.to_dict())))
    dep = SeldonDeployment(
        name=dep.name, namespace=dep.namespace, predictors=defaulted,
        annotations=dep.annotations, labels=dep.labels, protocol=dep.protocol,
    )
    for p in dep.predictors:
        manifests.extend(_workload(dep, p))
    for p in dep.predictors:
        manifests.append(_service(dep, p))
    for p in dep.predictors:
        hpa = _hpa(dep, p)
        if hpa:
            manifests.append(hpa)
    vs = _virtual_service(dep)
    if vs:
        # the deployment-wide Service must exist for the VS host to resolve
        manifests.append(_deployment_service(dep))
        # DestinationRules BEFORE the VirtualService that names their
        # subsets: applying in manifest order never leaves the VS dangling
        manifests.extend(_destination_rules(dep))
        manifests.append(vs)
    return manifests


def to_yaml(manifests: List[Dict[str, Any]]) -> str:
    try:
        import yaml
    except Exception:  # pragma: no cover - pyyaml is in the image, but the
        # renderer must not hard-require it (kubectl accepts JSON streams)
        return "\n".join(json.dumps(m, indent=2) for m in manifests)
    return yaml.safe_dump_all(manifests, sort_keys=False, default_flow_style=False)


# -- minimal structural validation (no k8s client in the image) -------------

_REQUIRED_TOP = ("apiVersion", "kind", "metadata")


def validate_manifests(manifests: List[Dict[str, Any]]) -> None:
    """Schema sanity for rendered objects: required keys, selector/label
    coherence, container port/probe consistency. Raises ValueError."""
    names = set()
    for m in manifests:
        for k in _REQUIRED_TOP:
            if k not in m:
                raise ValueError(f"manifest missing {k}: {m}")
        meta = m["metadata"]
        if "name" not in meta or "namespace" not in meta:
            raise ValueError(f"metadata incomplete: {meta}")
        key = (m["kind"], meta["namespace"], meta["name"])
        if key in names:
            raise ValueError(f"duplicate object {key}")
        names.add(key)
        if m["kind"] in ("Deployment", "StatefulSet"):
            spec = m["spec"]
            sel = spec["selector"]["matchLabels"]
            tpl_labels = spec["template"]["metadata"]["labels"]
            for k, v in sel.items():
                if tpl_labels.get(k) != v:
                    raise ValueError(
                        f"{meta['name']}: selector {k}={v} not in template labels"
                    )
            for c in spec["template"]["spec"]["containers"]:
                port_names = {p.get("name") for p in c.get("ports", [])}
                for probe in ("readinessProbe", "livenessProbe"):
                    http = c.get(probe, {}).get("httpGet", {})
                    port = http.get("port")
                    if isinstance(port, str) and port not in port_names:
                        raise ValueError(
                            f"{meta['name']}/{c['name']}: {probe} references "
                            f"unknown port {port!r}"
                        )
        if m["kind"] == "HorizontalPodAutoscaler":
            spec = m["spec"]
            if spec["minReplicas"] > spec["maxReplicas"]:
                raise ValueError(f"{meta['name']}: minReplicas > maxReplicas")
    # every HPA must target a rendered workload of the SAME kind (an HPA
    # naming a Deployment that rendered as a StatefulSet FailedGetScales
    # forever on a real cluster)
    workloads = {(k, ns, n) for k, ns, n in names if k in ("Deployment", "StatefulSet")}
    for m in manifests:
        if m["kind"] == "HorizontalPodAutoscaler":
            ref = m["spec"]["scaleTargetRef"]
            if (ref["kind"], m["metadata"]["namespace"], ref["name"]) not in workloads:
                raise ValueError(
                    f"HPA targets unknown workload {ref['kind']}/{ref['name']}"
                )
