"""Deployment reconciler: SeldonDeployment resources → running components.

Parity with the operator's reconcile loop (reference:
operator/controllers/seldondeployment_controller.go:253-391,1067-1122):
per predictor it runs the admission defaulting/validation, the
model-initializer (modelUri download — reference:
operator/controllers/model_initializer_injector.go:65-242), prepackaged
server wiring (reference: seldondeployment_prepackaged_servers.go:30-176),
TPU device placement (replaces GKE scheduling), engine injection with the
b64 graph env (reference: seldondeployment_engine.go:101-214), explainer
components (reference: seldondeployment_explainers.go:32-187), then diffs
desired vs running components, performs create-before-delete rolling
updates, and rolls the status up to Creating/Available/Failed.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..graph.spec import (
    ANNOTATION_KV_TIER_BYTES,
    ANNOTATION_MESH,
    ANNOTATION_TENANTS,
    GraphSpecError,
    PREPACKAGED_SERVERS,
    PredictorSpec,
    default_predictor,
    inject_kv_tier_param,
    inject_tenants_param,
    parse_disagg_annotations,
    parse_kv_tier_annotation,
    parse_mesh_annotation,
    parse_tenants_annotation,
    validate_deployment,
)
from ..storage import Storage
from .resource import (
    STATE_AVAILABLE,
    STATE_CREATING,
    STATE_FAILED,
    DeploymentStatus,
    PredictorStatus,
    SeldonDeployment,
)
from .runtime import ComponentHandle, ComponentSpec, InProcessRuntime
from .store import EVENT_DELETED, ResourceStore
from .placement import PlacementError, TpuPlacement

logger = logging.getLogger(__name__)

# annotation keys (reference: seldondeployment_types.go:35-45 k8s
# annotations-as-feature-flags, delivered via podinfo downward API)
ANNOTATION_SEPARATE_ENGINE = "seldon.io/engine-separate-pod"
ANNOTATION_NO_ENGINE = "seldon.io/no-engine"


class DeploymentController:
    def __init__(
        self,
        store: ResourceStore,
        runtime: Optional[InProcessRuntime] = None,
        placement: Optional[TpuPlacement] = None,
        gateway=None,
        model_cache_dir: Optional[str] = None,
        ready_timeout_s: float = 30.0,
    ):
        self.store = store
        self.runtime = runtime or InProcessRuntime()
        self.placement = placement
        self.gateway = gateway
        self.model_cache_dir = model_cache_dir
        self.ready_timeout_s = ready_timeout_s
        # component-name -> (handle, spec_hash of owning deployment)
        self.components: Dict[str, Tuple[ComponentHandle, str]] = {}
        self._reconciling: Dict[str, asyncio.Lock] = {}
        # autoscaler state: (dep.key, predictor) -> consecutive ticks that
        # wanted a scale-DOWN (stabilization window, like the k8s HPA's)
        self.autoscale_period_s = 5.0
        self.scale_down_ticks = 3
        self._scale_down_streak: Dict[Tuple[str, str], int] = {}
        # progressive delivery: the rollout state machines tick alongside
        # the autoscaler (rollout/controller.py); weight updates land as
        # store.apply generation bumps this controller then reconciles
        from ..rollout import RolloutController

        self.rollout = RolloutController(store)
        self.rollout_period_s = 1.0
        # disaggregated serving: (dep.key, predictor, prefill index) ->
        # KV transport port. Allocated once and reused across reconciles
        # so a decode-pool scale event keeps pointing at live prefill
        # listeners instead of re-rolling every peer address.
        self._kv_ports: Dict[Tuple[str, str, int], int] = {}
        # fleet telemetry plane: a deployment-scope metrics registry every
        # member's /fleet snapshot merges into (per-member deltas, so a
        # member restart resets cleanly), the previous snapshot per member
        # the delta is diffed against, and the latest SLO burn verdicts
        # per (dep.key, predictor) — the autoscaler's page-veto signal
        from ..graph.engine_metrics import MetricsRegistry

        self.fleet_metrics = MetricsRegistry()
        self.fleet_period_s = 5.0
        self._fleet_prev: Dict[str, Dict] = {}
        self._fleet_units: Dict[str, Dict] = {}
        self._burn_verdicts: Dict[Tuple[str, str], List[Dict]] = {}
        # autonomic planning (planning/planner.py): one decision table
        # per (dep.key, predictor) that opted in via seldon.io/planner,
        # ticked next to the fleet scrape. Decisions actuate ONLY
        # through safe paths — handle.retune() lands at a poll
        # boundary, scale decisions rewrite replicas through the same
        # clamped spec path the HPA uses — and precedence with the
        # autoscaler is deterministic: a burn-verdict page VETOES any
        # same-tick scale-down (counted below), and any planner scale
        # change resets the autoscaler's stabilization streak so the
        # two controllers share ONE hysteresis window (docs/operate.md
        # §"Autonomic planning").
        self.planner_period_s = 5.0
        self._planners: Dict[Tuple[str, str], Any] = {}
        self._planner_profiles: Dict[str, Any] = {}  # path -> CostModel|None
        self._planner_events: deque = deque(maxlen=256)
        self.planner_stats = {
            "ticks": 0, "retunes": 0, "retunes_refused": 0,
            "scale_ups": 0, "scale_downs": 0, "vetoes": 0, "holds": 0,
        }

    # -- desired state ------------------------------------------------------

    async def _initialize_models(self, pspec: PredictorSpec) -> None:
        """Model-initializer: pull every unit's modelUri to local disk and
        point the unit at the local copy (reference: init-container download
        into emptyDir /mnt/models, model_initializer_injector.go:65-242).
        Downloads run on the default executor so a multi-GB pull doesn't
        stall the controller loop (or the co-hosted gateway)."""
        loop = asyncio.get_running_loop()
        for unit in pspec.graph.walk():
            if not unit.model_uri:
                continue
            scheme = unit.model_uri.split("://", 1)[0] if "://" in unit.model_uri else ""
            if scheme in ("", "file"):
                continue  # already local
            out_dir = (
                None if self.model_cache_dir is None else f"{self.model_cache_dir}/{unit.name}"
            )
            unit.model_uri = await loop.run_in_executor(
                None, Storage.download, unit.model_uri, out_dir
            )

    @staticmethod
    def _component_hash(dep: SeldonDeployment) -> str:
        """Spec hash extended with annotations: annotation flips (e.g.
        separate-engine) must produce new component names so running
        engines are replaced, not half-updated.

        Replica COUNTS are excluded: a scale event (autoscaler or manual
        `replicas` bump) must add/remove replica components without
        renaming — and so recreating — the survivors (the reference's HPA
        scales the Deployment without a pod-template change)."""
        import hashlib
        import json as _json

        blob = dep.spec_hash(include_replicas=False) + _json.dumps(
            dep.annotations, sort_keys=True
        )
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    async def desired_components(self, dep: SeldonDeployment) -> List[ComponentSpec]:
        specs: List[ComponentSpec] = []
        h = self._component_hash(dep)
        no_engine = dep.annotations.get(ANNOTATION_NO_ENGINE, "false") == "true"
        for pspec in dep.predictors:
            separate = dep.annotations.get(ANNOTATION_SEPARATE_ENGINE, "false") == "true"
            pspec = default_predictor(pspec, separate_pods=False)
            await self._initialize_models(pspec)
            # separate-pod units become standalone microservices; their ports
            # are allocated here and written back into the engine graph so
            # the engine's REST client dials the real socket (reference:
            # createStandaloneModelServers prepackaged_servers.go:248)
            if separate:
                for unit in pspec.graph.walk():
                    if unit.endpoint.transport in ("REST", "GRPC") and unit.implementation in PREPACKAGED_SERVERS:
                        from .runtime import free_port

                        port = free_port()
                        unit.endpoint.transport = "REST"
                        unit.endpoint.service_host = "127.0.0.1"
                        unit.endpoint.service_port = port
                        specs.append(
                            ComponentSpec(
                                name=f"{dep.key}/{pspec.name}/{unit.name}/svc-{h[:8]}",
                                kind="microservice",
                                deployment=dep.key,
                                predictor=pspec.name,
                                interface_name=PREPACKAGED_SERVERS[unit.implementation],
                                http_port=port,
                                parameters=[
                                    {"name": "model_uri", "value": unit.model_uri, "type": "STRING"},
                                    *[p.to_dict() for p in unit.parameters],
                                ],
                            )
                        )
            def explainer_spec() -> Optional[ComponentSpec]:
                explainer = pspec.annotations.get("seldon.io/explainer-type")
                if not explainer:
                    return None
                return ComponentSpec(
                    name=f"{dep.key}/{pspec.name}/explainer-{h[:8]}",
                    kind="explainer",
                    deployment=dep.key,
                    predictor=pspec.name,
                    interface_name="seldon_core_tpu.components.explainer.Explainer",
                    parameters=[
                        {"name": "explainer_type", "value": explainer, "type": "STRING"},
                        {
                            "name": "model_uri",
                            "value": pspec.annotations.get("seldon.io/explainer-model-uri", ""),
                            "type": "STRING",
                        },
                    ],
                )

            if no_engine:
                # no-engine mode: expose the single graph node directly, no
                # orchestrator hop (reference: seldon.io/no-engine annotation,
                # seldondeployment_types.go:43-45). Only single-node graphs
                # qualify — deeper graphs need the engine walk.
                if parse_disagg_annotations(pspec) is not None:
                    raise GraphSpecError(
                        f"{pspec.name}: seldon.io/disagg needs the engine "
                        f"(pool roles are engine parameters); drop "
                        f"{ANNOTATION_NO_ENGINE}"
                    )
                root = pspec.graph
                if root.children:
                    raise GraphSpecError(
                        f"{pspec.name}: {ANNOTATION_NO_ENGINE} requires a single-node graph"
                    )
                if root.implementation not in PREPACKAGED_SERVERS:
                    raise GraphSpecError(
                        f"{pspec.name}: {ANNOTATION_NO_ENGINE} needs a prepackaged "
                        f"implementation, got {root.implementation!r}"
                    )
                for replica in range(max(1, pspec.replicas)):
                    specs.append(
                        ComponentSpec(
                            name=f"{dep.key}/{pspec.name}/{replica}/model-{h[:8]}",
                            kind="microservice",
                            deployment=dep.key,
                            predictor=pspec.name,
                            replica=replica,
                            routable=True,
                            interface_name=PREPACKAGED_SERVERS[root.implementation],
                            parameters=[
                                {"name": "model_uri", "value": root.model_uri, "type": "STRING"},
                                *[p.to_dict() for p in root.parameters],
                            ],
                        )
                    )
                espec = explainer_spec()
                if espec is not None:
                    specs.append(espec)
                continue
            disagg = parse_disagg_annotations(pspec)
            if disagg is not None:
                specs.extend(self._disagg_components(dep, pspec, h, disagg))
                espec = explainer_spec()
                if espec is not None:
                    specs.append(espec)
                continue
            # kv-tier annotation: the byte budget lands on the
            # GENERATE_SERVER unit as the host_kv_tier_bytes parameter
            # (one source of truth — the annotation; see graph/spec.py)
            tier_bytes = parse_kv_tier_annotation(pspec)
            # mesh annotation: the shape lands on the member spec as the
            # tpuMesh field (one source of truth — the annotation; see
            # graph/spec.py) so placement and the engine's in-process
            # mesh build both read the same already-validated shape
            mesh_shape = parse_mesh_annotation(pspec)
            # tenants annotation: the validated roster lands on the
            # GENERATE_SERVER unit as the `tenants` parameter, verbatim
            # CSV (one source of truth — the annotation; the server
            # re-parses with the same strict grammar at construction)
            tenants_raw = (
                (pspec.annotations or {}).get(ANNOTATION_TENANTS)
                if parse_tenants_annotation(pspec) is not None else None
            )
            for replica in range(max(1, pspec.replicas)):
                name = f"{dep.key}/{pspec.name}/{replica}/engine-{h[:8]}"
                espec_dict = pspec.to_dict()
                if tenants_raw is not None:
                    espec_dict = inject_tenants_param(
                        espec_dict, tenants_raw
                    )
                    espec_dict["annotations"] = {
                        k: v
                        for k, v in (
                            espec_dict.get("annotations") or {}
                        ).items()
                        if k != ANNOTATION_TENANTS
                    }
                if tier_bytes is not None:
                    espec_dict = inject_kv_tier_param(espec_dict, tier_bytes)
                    # injected as a parameter now: strip the annotation
                    # so any re-validation of the member spec doesn't
                    # see both sources of truth at once
                    espec_dict["annotations"] = {
                        k: v
                        for k, v in (espec_dict.get("annotations") or {}).items()
                        if k != ANNOTATION_KV_TIER_BYTES
                    }
                if mesh_shape is not None:
                    espec_dict["tpuMesh"] = dict(mesh_shape)
                    # same inject-then-strip: tpuMesh carries the shape
                    # now, so re-validation never sees both sources
                    espec_dict["annotations"] = {
                        k: v
                        for k, v in (espec_dict.get("annotations") or {}).items()
                        if k != ANNOTATION_MESH
                    }
                specs.append(
                    ComponentSpec(
                        name=name,
                        kind="engine",
                        deployment=dep.key,
                        predictor=pspec.name,
                        replica=replica,
                        routable=True,
                        engine_spec=espec_dict,
                    )
                )
            espec = explainer_spec()
            if espec is not None:
                specs.append(espec)
        return specs

    def _disagg_components(
        self, dep: SeldonDeployment, pspec: PredictorSpec, h: str, disagg
    ) -> List[ComponentSpec]:
        """Split a ``seldon.io/disagg`` GENERATE_SERVER predictor into
        two independently scaled pools: ``prefill`` engines (role=prefill,
        each listening on a stable KV port, NOT routable — they serve the
        slab transport only) and ``decode`` engines (role=decode, peer
        pointed round-robin at the prefill listeners, routable — the
        gateway sends generate traffic here). Scaling either pool only
        adds/removes members of that pool: the per-pool replica
        annotations are excluded from the component-naming hash exactly
        like ``replicas`` is."""
        n_prefill, n_decode = disagg
        tier_bytes = parse_kv_tier_annotation(pspec)

        def pool_spec(role: str, extra) -> Dict:
            d = pspec.to_dict()
            if tier_bytes is not None:
                # both pools carry the tier: the prefill pool's tier is
                # what the KV-port listener answers peer prefix-lookups
                # from; the decode pool's tier is the pressure spill
                d = inject_kv_tier_param(d, tier_bytes)
            # the pool member is already specialized: strip the disagg
            # annotations (and the kv-tier annotation, now injected as
            # a parameter) so the runtime's re-validation doesn't see a
            # role/tier parameter on a spec that still asks to own it
            d["annotations"] = {
                k: v
                for k, v in (d.get("annotations") or {}).items()
                if not k.startswith("seldon.io/disagg")
                and k != ANNOTATION_KV_TIER_BYTES
            }
            graph = d["graph"]
            params = list(graph.get("parameters") or [])
            params.append({"name": "role", "value": role, "type": "STRING"})
            for k, v in extra:
                params.append({"name": k, "value": str(v), "type": "STRING"})
            graph["parameters"] = params
            return d

        from .runtime import free_port

        def port_bindable(port: int) -> bool:
            import socket as _socket

            s = _socket.socket()
            try:
                s.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
                s.bind(("0.0.0.0", port))
                return True
            except OSError:
                return False
            finally:
                s.close()

        ports = []
        for i in range(n_prefill):
            key = (dep.key, pspec.name, i)
            comp_name = f"{dep.key}/{pspec.name}/pf{i}/engine-{h[:8]}"
            if (
                key in self._kv_ports
                and comp_name not in self.components
                and not port_bindable(self._kv_ports[key])
            ):
                # the listener is NOT ours right now (component down) and
                # a foreign process holds the cached port: retrying the
                # same dead port every reconcile would wedge the
                # deployment in CREATING forever — allocate fresh (the
                # dependent decode members re-point via their peer-port
                # names)
                del self._kv_ports[key]
            if key not in self._kv_ports:
                self._kv_ports[key] = free_port()
            ports.append(self._kv_ports[key])
        out: List[ComponentSpec] = []
        for i in range(n_prefill):
            out.append(
                ComponentSpec(
                    name=f"{dep.key}/{pspec.name}/pf{i}/engine-{h[:8]}",
                    kind="engine",
                    deployment=dep.key,
                    predictor=pspec.name,
                    replica=i,
                    routable=False,
                    engine_spec=pool_spec(
                        "prefill", [("kv_port", ports[i])]
                    ),
                )
            )
        # peer-LIST assignment (not per-member round-robin): every decode
        # member gets the FULL candidate set of prefill listeners and the
        # engine's failover transport picks per transfer — so a prefill-
        # pool resize shrinks/grows the candidate set instead of
        # re-pointing (and so replacing) survivors. Decode names carry no
        # peer port: survivors keep serving through a resize, ejecting
        # torn-down listeners at runtime (a survivor only learns about
        # ADDED listeners when it is next recreated — acceptable, the
        # failover layer keeps it correct on its stale subset meanwhile).
        peer_list = ",".join(f"127.0.0.1:{p}" for p in ports)
        for r in range(n_decode):
            out.append(
                ComponentSpec(
                    name=f"{dep.key}/{pspec.name}/{r}/engine-{h[:8]}",
                    kind="engine",
                    deployment=dep.key,
                    predictor=pspec.name,
                    replica=r,
                    routable=True,
                    engine_spec=pool_spec(
                        "decode",
                        [("peer", peer_list)],
                    ),
                )
            )
        return out

    # -- reconcile ----------------------------------------------------------

    async def reconcile(self, dep: SeldonDeployment) -> DeploymentStatus:
        lock = self._reconciling.setdefault(dep.key, asyncio.Lock())
        async with lock:
            return await self._reconcile_locked(dep)

    async def _reconcile_locked(self, dep: SeldonDeployment) -> DeploymentStatus:
        status = DeploymentStatus(state=STATE_CREATING)

        def fail(desc: str) -> DeploymentStatus:
            status.state = STATE_FAILED
            status.description = desc
            status.predictor_status = []
            dep.status = status
            self.store.update_status(dep)
            if self.gateway is not None:
                # routes must track what actually survives a failed
                # reconcile (e.g. the recreate fallback tore the old
                # generation down) — never leave stale handles routable
                self.gateway.set_routes(
                    dep, self._routable_endpoints(dep), self._explainer_endpoints(dep)
                )
            return status

        try:
            validate_deployment(dep.predictors)
            desired = await self.desired_components(dep)
        except Exception as e:  # noqa: BLE001 - any bad spec must not kill run()
            return fail(str(e))

        desired_names = {s.name for s in desired}
        mine = {n for n, (h, _) in self.components.items() if h.spec.deployment == dep.key}

        # TPU placement: one block per (predictor, replica) engine. Prefer
        # create-before-delete; when chips don't fit both generations at
        # once, fall back to tearing the old generation down first
        # (Recreate-strategy equivalent).
        if self.placement is not None:
            try:
                self._allocate_blocks(dep, desired)
            except PlacementError:
                for name in sorted(mine - desired_names):
                    handle, _ = self.components.pop(name)
                    self.placement.release(name)
                    await handle.stop()
                mine = {n for n, (h, _) in self.components.items() if h.spec.deployment == dep.key}
                try:
                    self._allocate_blocks(dep, desired)
                except PlacementError as e:
                    self._release_blocks(desired)
                    return fail(str(e))

        # create-before-delete rolling update (reference: Deployment
        # rolling-update semantics exercised by test_rolling_updates.py)
        created: List[ComponentHandle] = []
        try:
            for spec in desired:
                if spec.name not in self.components:
                    if spec.kind == "explainer":
                        # point the explainer at a live engine of its
                        # predictor (reference: --predictor_host arg,
                        # seldondeployment_explainers.go:105-110); engines
                        # precede explainers in desired order so the port
                        # is known by now
                        self._wire_explainer_endpoint(spec, desired_names)
                    handle = await self.runtime.start(spec)
                    self.components[spec.name] = (handle, dep.spec_hash())
                    created.append(handle)
            # wait for new components to come ready before tearing down old
            ok = await self._await_ready(created)
        except Exception as e:  # noqa: BLE001 - component boot must not kill run()
            logger.exception("%s: component start failed", dep.key)
            for handle in created:
                self.components.pop(handle.spec.name, None)
                await handle.stop()
            self._release_blocks(desired, keep=mine)
            return fail(f"component start failed: {e}")

        if ok:
            # repoint the gateway at the new generation BEFORE tearing the
            # old one down — otherwise there's a window where the route
            # table still targets stopped components (502s under
            # SubprocessRuntime), defeating create-before-delete
            if self.gateway is not None:
                self.gateway.set_routes(
                    dep,
                    {
                        pred: [h for h in handles if h.spec.name in desired_names]
                        for pred, handles in self._routable_endpoints(dep).items()
                    },
                    {
                        pred: [h for h in handles if h.spec.name in desired_names]
                        for pred, handles in self._explainer_endpoints(dep).items()
                    },
                )
            for name in sorted(mine - desired_names):
                handle, _ = self.components.pop(name)
                if self.placement is not None:
                    self.placement.release(name)
                # zero-loss replacement/scale-down: checkpoint the
                # member's in-flight generations and hand them to a
                # surviving (or new-generation) peer BEFORE teardown —
                # rolling maintenance drops zero requests
                await self._drain_generate_member(handle)
                await handle.stop()
        else:
            # roll back: tear down the failed new generation, keep old
            for handle in created:
                self.components.pop(handle.spec.name, None)
                if self.placement is not None:
                    self.placement.release(handle.spec.name)
                await handle.stop()
            return fail("new components failed readiness")

        # status rollup (reference: seldondeployment_controller.go:1111-1119)
        for pspec in dep.predictors:
            replicas = max(1, pspec.replicas)
            try:
                disagg = parse_disagg_annotations(pspec)
            except GraphSpecError:
                disagg = None
            if disagg is not None:
                # routable components are the decode pool; availability
                # is judged against ITS size (prefill members gate
                # readiness through _await_ready like any component)
                replicas = disagg[1]
            avail = 0
            for name, (handle, _) in self.components.items():
                if (
                    handle.spec.deployment == dep.key
                    and handle.spec.predictor == pspec.name
                    and handle.spec.routable
                    and await handle.ready()
                ):
                    avail += 1
            status.predictor_status.append(
                PredictorStatus(name=pspec.name, replicas=replicas, replicas_available=avail)
            )
        status.state = (
            STATE_AVAILABLE
            if all(p.replicas_available >= p.replicas for p in status.predictor_status)
            else STATE_CREATING
        )
        dep.status = status
        self.store.update_status(dep)
        if self.gateway is not None:
            self.gateway.set_routes(
                dep, self._routable_endpoints(dep), self._explainer_endpoints(dep)
            )
        self._wire_shadow_mirrors(dep)
        return status

    @staticmethod
    def _generate_unit(handle, attr: str):
        """The in-process generate unit behind ``handle`` exposing
        ``attr`` (``drain_to`` / ``resume_checkpoint``), or None — only
        the default in-process engine runtime carries live unit objects.
        Delegates the graph walk to ``EngineApp.units_with`` so unit
        discovery lives in one module."""
        app = getattr(handle, "app", None)
        if app is None or not hasattr(app, "units_with"):
            return None
        return next((u for _n, u in app.units_with(attr)), None)

    async def _drain_generate_member(self, handle) -> None:
        """Before stopping a generate member (hot-swap replacement or
        decode-pool scale-down), checkpoint its live lanes + queued
        requests and migrate them to a surviving routable member of the
        same predictor (loopback — the handles share this process).
        Honors ``seldon.io/drain-seconds`` as the handoff budget.
        Best-effort: a member with nothing to migrate costs one empty
        drain; a failed handoff fails those requests typed exactly as a
        plain teardown would have, never worse."""
        from .runtime import _drain_seconds

        src = self._generate_unit(handle, "drain_to")
        if src is None or getattr(src, "batcher", None) is None:
            return
        peer = None
        for _name, (h, _) in self.components.items():
            if (
                h is not handle
                and h.spec.deployment == handle.spec.deployment
                and h.spec.predictor == handle.spec.predictor
                and h.spec.routable
            ):
                peer = self._generate_unit(h, "resume_checkpoint")
                if peer is not None:
                    break
        if peer is None:
            return
        drain_s = _drain_seconds(handle.spec)
        loop = asyncio.get_running_loop()
        try:
            summary = await asyncio.wait_for(
                loop.run_in_executor(
                    None, lambda: src.drain_to(peer, timeout_s=drain_s)
                ),
                timeout=drain_s + 5.0,
            )
            if summary.get("drained"):
                logger.info(
                    "%s: drained %d in-flight generation(s) to a peer "
                    "before teardown", handle.spec.name,
                    summary["drained"],
                )
        except Exception:  # noqa: BLE001 - drain is best-effort
            logger.exception(
                "%s: drain before teardown failed", handle.spec.name
            )

    def _wire_shadow_mirrors(self, dep: SeldonDeployment) -> None:
        """Shadow-mode rollouts mirror at the ENGINE: every live
        predictor's EngineApp gets a bounded, diffing ShadowMirror whose
        targets are the candidate's engines (the gateway then skips its
        legacy fire-and-forget for this deployment). Cleared — restoring
        the byte-identical no-rollout path — whenever no shadow rollout is
        active."""
        from ..rollout import ShadowMirror, plan_from_deployment

        try:
            plan = plan_from_deployment(dep)
        except GraphSpecError:
            plan = None
        if (
            plan is not None
            and plan.mode == "shadow"
            and self.rollout is not None
            and not self.rollout.shadow_active(dep, plan)
        ):
            # terminal rollout (failed on divergence, or promoted): no
            # longer active — keeping the mirror attached would double
            # every live request's device load forever just because the
            # annotations are still on the spec
            plan = None
        engines = self._routable_endpoints(dep)
        shadow_preds = {
            p.name for p in dep.predictors
            if p.annotations.get("seldon.io/shadow", "false") == "true"
        }
        mirror_targets = None
        if plan is not None and plan.mode == "shadow":
            # EVERY shadow predictor stays a target (a plain shadow must
            # not starve because a rollout candidate exists beside it —
            # the gateway's legacy mirror is suppressed for the whole
            # deployment), but only ONE handle per predictor: mirroring
            # each replica would multiply duplicate dispatch and inflate
            # the divergence denominator min_samples reads
            targets = []
            for pred in sorted(shadow_preds):
                for h in engines.get(pred, []):
                    if getattr(h, "app", None) is not None or h.spec.http_port:
                        targets.append((pred, h))
                        break
            if targets:
                mirror_targets = targets
            else:
                logger.warning(
                    "rollout %s: shadow mode but no mirrorable shadow "
                    "endpoint — the rollout will pause forever "
                    "(mirroring needs in-process or HTTP-reachable "
                    "shadow engines)", dep.key,
                )
        mirrors_wired = 0
        for pred, handles in engines.items():
            if mirror_targets is not None and pred in shadow_preds:
                continue  # shadows never re-mirror
            for h in handles:
                app = getattr(h, "app", None)
                if app is None:
                    continue
                if mirror_targets is None:
                    app.shadow_mirror = None
                    continue
                cur = getattr(app, "shadow_mirror", None)
                if (
                    cur is not None
                    and cur.deployment == dep.key
                    and cur.targets == mirror_targets
                ):
                    mirrors_wired += 1
                    continue  # unchanged: keep counts/bound/divergence ring
                app.shadow_mirror = ShadowMirror(
                    mirror_targets,
                    deployment=dep.key,
                    metrics=getattr(app, "metrics", None),
                )
                mirrors_wired += 1
        if mirror_targets is not None and mirrors_wired == 0:
            logger.warning(
                "rollout %s: shadow mode but no in-process live engine to "
                "mirror FROM — no mirrored samples will arrive and the "
                "rollout will pause forever (shadow rollouts need the "
                "default in-process engine runtime)", dep.key,
            )

    def _wire_explainer_endpoint(self, spec: ComponentSpec, desired_names) -> None:
        if any((p or {}).get("name") == "predictor_endpoint" for p in spec.parameters or []):
            return
        candidates = [
            handle.spec
            for _name, (handle, _) in self.components.items()
            if (
                handle.spec.deployment == spec.deployment
                and handle.spec.predictor == spec.predictor
                and handle.spec.routable
                and handle.spec.http_port
            )
        ]
        # during a rolling update both generations are alive here — wire
        # against the NEW generation (in desired_names); the old one is
        # torn down at the end of this same reconcile
        new_gen = [c for c in candidates if c.name in desired_names]
        target = (new_gen or candidates or [None])[0]
        if target is None:
            return
        path = "/predict" if target.kind == "microservice" else "/api/v0.1/predictions"
        spec.parameters = (spec.parameters or []) + [
            {"name": "predictor_endpoint", "value": f"127.0.0.1:{target.http_port}", "type": "STRING"},
            {"name": "predictor_path", "value": path, "type": "STRING"},
        ]

    def _allocate_blocks(self, dep: SeldonDeployment, desired: List[ComponentSpec]) -> None:
        """All-or-nothing device allocation for the desired engines: on a
        PlacementError, blocks grabbed within this call are released so a
        failed generation never leaks chips."""
        fresh: List[str] = []
        try:
            for spec in desired:
                if spec.kind != "engine":
                    continue
                pspec = dep.predictor(spec.predictor)
                mesh_spec = pspec.tpu_mesh if pspec else None
                if pspec is not None and mesh_spec is None:
                    # seldon.io/mesh predictors carry no tpuMesh on the
                    # DEPLOYMENT spec (the annotation owns it; the member
                    # spec got tpuMesh injected) — consult the annotation
                    # so placement carves the same block
                    mesh_spec = parse_mesh_annotation(pspec)
                if self.placement.assigned(spec.name) is None:
                    self.placement.allocate(spec.name, mesh_spec)
                    fresh.append(spec.name)
                if mesh_spec and spec.name not in self.components:
                    # hand the placed device block to the engine as a
                    # named mesh: its in-process jaxserver units shard over
                    # exactly the chips this engine was allocated (only
                    # components about to start — already-running engines
                    # keep their mesh and their desired spec is discarded)
                    spec.mesh = self.placement.mesh_for(spec.name, mesh_spec)
        except PlacementError:
            for name in fresh:
                self.placement.release(name)
            raise

    def _release_blocks(self, desired: List[ComponentSpec], keep=()) -> None:
        if self.placement is None:
            return
        for spec in desired:
            if spec.name not in keep and spec.name not in self.components:
                self.placement.release(spec.name)

    def _endpoints_by(self, dep: SeldonDeployment, want) -> Dict[str, List[ComponentHandle]]:
        out: Dict[str, List[ComponentHandle]] = {}
        for name, (handle, _) in self.components.items():
            if handle.spec.deployment == dep.key and want(handle.spec):
                out.setdefault(handle.spec.predictor, []).append(handle)
        return out

    def _routable_endpoints(self, dep: SeldonDeployment) -> Dict[str, List[ComponentHandle]]:
        return self._endpoints_by(dep, lambda s: s.routable)

    def _explainer_endpoints(self, dep: SeldonDeployment) -> Dict[str, List[ComponentHandle]]:
        return self._endpoints_by(dep, lambda s: s.kind == "explainer")

    async def _await_ready(self, handles: List[ComponentHandle]) -> bool:
        deadline = asyncio.get_running_loop().time() + self.ready_timeout_s
        pending = list(handles)
        while pending and asyncio.get_running_loop().time() < deadline:
            still = []
            for h in pending:
                if not await h.ready():
                    still.append(h)
            pending = still
            if pending:
                await asyncio.sleep(0.05)
        return not pending

    async def delete(self, dep: SeldonDeployment) -> None:
        mine = [n for n, (h, _) in self.components.items() if h.spec.deployment == dep.key]
        for name in mine:
            handle, _ = self.components.pop(name)
            if self.placement is not None:
                self.placement.release(name)
            await handle.stop()
        if self.gateway is not None:
            self.gateway.drop_routes(dep.key)
        # a re-created deployment must start a FRESH scale-down window
        for key in [k for k in self._scale_down_streak if k[0] == dep.key]:
            del self._scale_down_streak[key]
        # ... and fresh KV transport ports for its prefill pool
        for key in [k for k in self._kv_ports if k[0] == dep.key]:
            del self._kv_ports[key]

    # -- watch loop ---------------------------------------------------------

    # -- autoscaler ---------------------------------------------------------

    async def autoscale_once(self) -> Dict[str, int]:
        """One HPA evaluation pass (reference: createHpas
        seldondeployment_controller.go:805 + the k8s HPA control loop; the
        TPU-native metric is in-flight concurrency per engine replica,
        summed from the engines' /inflight gauges).

        desired = ceil(total_inflight / targetConcurrency), clamped to
        [minReplicas, maxReplicas]. Scale-up applies immediately;
        scale-down waits ``scale_down_ticks`` consecutive low passes
        (stabilization, so a burst lull doesn't thrash replicas). Returns
        {"<dep.key>/<predictor>": new_replicas} for every change applied.
        """
        changes: Dict[str, int] = {}
        for dep in self.store.list():
            try:
                changes.update(await self._autoscale_deployment(dep))
            except Exception:  # noqa: BLE001 - one malformed hpaSpec must
                # not stop autoscaling every other deployment
                logger.exception("autoscale %s failed", dep.key)
        return changes

    async def _autoscale_deployment(self, dep) -> Dict[str, int]:
        import math

        from ..graph.spec import parse_hpa_spec

        new_replicas: Dict[str, int] = {}
        for pspec in dep.predictors:
            hpa = pspec.hpa_spec
            if not hpa:
                continue
            lo, hi, target = parse_hpa_spec(hpa, who=f"{dep.key}/{pspec.name}")
            handles = [
                handle
                for handle, _ in self.components.values()
                if handle.spec.deployment == dep.key
                and handle.spec.predictor == pspec.name
                and handle.spec.routable
            ]
            # two counts: ``current`` is what the spec says (the value a
            # scale decision rewrites), ``observed`` what is actually
            # serving — after a placement-capped or lagging reconcile the
            # two diverge, and a decision made against the wrong one either
            # mis-triggers the scale-down streak or (worse) applies an
            # instant scale-down through the scale-UP branch
            current = max(1, pspec.replicas)
            observed = max(1, len(handles) or pspec.replicas)
            if self.placement is not None and pspec.tpu_mesh:
                # never scale past the chips that exist: desired beyond the
                # free device blocks would just flip the deployment FAILED
                # while the old replicas keep serving (k8s HPA's
                # unschedulable-pods analogue, caught before, not after)
                per_replica = 1
                for v in pspec.tpu_mesh.values():
                    per_replica *= int(v)
                # anchor at the LARGER of spec/observed: when placement is
                # exhausted (free=0) an observed-only anchor would clamp
                # desired to the observed count and ratchet the spec down
                # under sustained load, killing the lag guard below
                placeable = (
                    max(current, observed)
                    + self.placement.capacity()["free"] // per_replica
                )
                hi = min(hi, max(lo, placeable))
            # probes run concurrently: with SubprocessRuntime each is an
            # HTTP call with a 0.5s timeout, and the controller loop must
            # not stall on M x N sequential probes
            loads = await asyncio.gather(*(h.load() for h in handles))
            known = [v for v in loads if v is not None]
            if not known:
                continue
            total = sum(known)
            desired = min(hi, max(lo, math.ceil(total / target)))
            streak_key = (dep.key, pspec.name)
            if self._worst_burn(dep.key, pspec.name) == "page":
                # a paging SLO burn verdict (fast AND slow windows above
                # the page rate) overrides the load signal: tenants are
                # burning error budget even if per-replica load looks
                # fine, so veto any scale-down streak and apply one
                # replica of upward pressure (clamped to hi/placement)
                self._scale_down_streak.pop(streak_key, None)
                desired = min(hi, max(desired, current + 1))
            if desired > current:
                self._scale_down_streak.pop(streak_key, None)
                new_replicas[pspec.name] = desired
            elif desired < current:
                if desired > observed:
                    # load demands MORE capacity than is actually serving;
                    # the spec merely hasn't materialized yet. Not a
                    # low-load signal — don't let reconcile lag accumulate
                    # into a streak that shrinks the spec.
                    self._scale_down_streak.pop(streak_key, None)
                else:
                    streak = self._scale_down_streak.get(streak_key, 0) + 1
                    self._scale_down_streak[streak_key] = streak
                    if streak >= self.scale_down_ticks:
                        self._scale_down_streak.pop(streak_key, None)
                        new_replicas[pspec.name] = desired
            else:
                self._scale_down_streak.pop(streak_key, None)
        if not new_replicas:
            return {}
        updated = dep.clone()
        for pspec in updated.predictors:
            if pspec.name in new_replicas:
                pspec.replicas = new_replicas[pspec.name]
        self.store.apply(updated)  # generation bump -> reconcile
        changes = {}
        for name, n in new_replicas.items():
            changes[f"{dep.key}/{name}"] = n
            logger.info("autoscale %s/%s -> %d replicas", dep.key, name, n)
        return changes

    # -- fleet telemetry scrape ---------------------------------------------

    async def fleet_scrape_once(self) -> Dict[str, Dict]:
        """Pull every member's /fleet payload, diff it against the
        member's previous snapshot, and merge the deltas into the
        deployment-scope ``fleet_metrics`` registry with
        member/predictor/deployment labels — the one pane of glass for
        disagg/sharded/multi-tenant deployments. Also refreshes the SLO
        burn verdict feed the autoscaler consumes. Returns the latest
        unit summaries per member (tools/smoke assert on them)."""
        from ..graph.engine_metrics import diff_fleet_snapshot

        live = set()
        # verdicts accumulate across a predictor's MEMBERS (an idle
        # member's empty list must not mask a hot member's page)
        burn: Dict[Tuple[str, str], List[Dict]] = {}
        for name, (handle, _) in list(self.components.items()):
            snap = await handle.fleet()
            if snap is None:
                continue
            live.add(name)
            labels = {
                "deployment": handle.spec.deployment,
                "predictor": handle.spec.predictor,
                "member": name,
            }
            metrics = snap.get("metrics") or {}
            self.fleet_metrics.ingest_fleet(
                diff_fleet_snapshot(self._fleet_prev.get(name), metrics),
                labels,
            )
            self._fleet_prev[name] = metrics
            units = snap.get("units") or {}
            self._fleet_units[name] = units
            burn.setdefault(
                (handle.spec.deployment, handle.spec.predictor), []
            ).extend(
                v
                for unit in units.values()
                for v in (unit.get("slo_burn") or {}).get("verdicts", [])
            )
        self._burn_verdicts = burn
        # members torn down since the last scrape must not leave stale
        # snapshots (a re-created member under the same name would diff
        # against its predecessor's totals)
        for name in list(self._fleet_prev):
            if name not in live:
                del self._fleet_prev[name]
                self._fleet_units.pop(name, None)
        return dict(self._fleet_units)

    def fleet_summary(self) -> Dict[str, Dict]:
        """Deployment-level rollup: the merged metric plane plus the
        latest per-member unit summaries and burn verdicts."""
        return {
            "metrics": self.fleet_metrics.fleet_snapshot(),
            "members": dict(self._fleet_units),
            "burn_verdicts": {
                f"{dep}/{pred}": v
                for (dep, pred), v in self._burn_verdicts.items()
            },
            "planner": {
                "stats": dict(self.planner_stats),
                "recent": list(self._planner_events)[-20:],
            },
        }

    def _worst_burn(self, dep_key: str, predictor: str) -> str:
        """Worst burn severity across a predictor's members (``ok`` when
        no verdicts have been scraped)."""
        from ..serving.slo_burn import SEVERITIES

        worst = 0
        for v in self._burn_verdicts.get((dep_key, predictor), []):
            try:
                worst = max(worst, SEVERITIES.index(v.get("severity")))
            except ValueError:
                continue
        return SEVERITIES[worst]

    # -- autonomic planner tick ---------------------------------------------

    def _planner_for(self, key: Tuple[str, str], cfg: Dict[str, Any]):
        """The (dep, predictor)'s decision table, created on first tick.
        The planner shares the HPA's ``scale_down_ticks`` stabilization
        window — ONE hysteresis constant for both controllers. A
        profile artifact that fails to decode disables the cost model
        (typed refusal logged once per path), never the planner: the
        burn/pressure/idle rules need no profile."""
        planner = self._planners.get(key)
        if planner is not None:
            return planner
        from ..planning import CostModel, ServingPlanner, read_profile

        cost_model = None
        path = cfg.get("profile")
        if path:
            if path not in self._planner_profiles:
                try:
                    self._planner_profiles[path] = CostModel(
                        read_profile(path)
                    )
                except Exception:  # noqa: BLE001 - corrupt/missing SPF1
                    # refuses typed; the planner runs model-less
                    logger.exception(
                        "planner: profile %s unusable, running without "
                        "a cost model", path,
                    )
                    self._planner_profiles[path] = None
            cost_model = self._planner_profiles[path]
        planner = ServingPlanner(
            cost_model=cost_model,
            scale_down_ticks=self.scale_down_ticks,
        )
        self._planners[key] = planner
        return planner

    def _planner_telemetry(self, dep, pspec):
        """(gauges, counter_totals, current_config, census) for one
        predictor, all harvested from the LAST fleet scrape — the
        planner consumes the same telemetry plane operators see, never
        a private side channel."""
        busy: List[float] = []
        config = census = None
        for name, (handle, _) in self.components.items():
            if (
                handle.spec.deployment != dep.key
                or handle.spec.predictor != pspec.name
            ):
                continue
            for unit in (self._fleet_units.get(name) or {}).values():
                prof = unit.get("profiler") or {}
                if "device_busy_frac" in prof:
                    busy.append(float(prof["device_busy_frac"]))
                plan = unit.get("planning") or {}
                if config is None and plan.get("config"):
                    config = plan["config"]
                    census = plan.get("census")
        gauges: Dict[str, float] = {}
        if busy:
            gauges["device_busy_frac"] = sum(busy) / len(busy)
        want = {"deployment": dep.key, "predictor": pspec.name}
        totals = {
            "sheds": self.fleet_metrics.counter_total(
                "seldon_engine_pressure_sheds", want
            ),
            "preemptions": self.fleet_metrics.counter_total(
                "seldon_engine_preemptions", want
            ),
        }
        return gauges, totals, config, census

    async def planner_tick_once(self) -> Dict[str, Dict[str, Any]]:
        """One planner pass over every predictor that opted in via
        ``seldon.io/planner``. Returns the decision event per
        ``<dep.key>/<predictor>`` (tools/planner_smoke asserts on
        them)."""
        from ..graph.spec import parse_planner_annotations

        results: Dict[str, Dict[str, Any]] = {}
        live = set()
        for dep in self.store.list():
            for pspec in dep.predictors:
                try:
                    cfg = parse_planner_annotations(pspec)
                except GraphSpecError as e:
                    logger.warning(
                        "planner: %s/%s annotations unusable: %s",
                        dep.key, pspec.name, e,
                    )
                    continue
                if not cfg or not cfg["enabled"]:
                    continue
                key = (dep.key, pspec.name)
                live.add(key)
                try:
                    results[f"{dep.key}/{pspec.name}"] = (
                        await self._planner_tick_one(
                            dep, pspec, self._planner_for(key, cfg)
                        )
                    )
                except Exception:  # noqa: BLE001 - one predictor's tick
                    # must not stop planning the others
                    logger.exception("planner tick %s failed", key)
        # predictors that dropped the annotation (or the deployment)
        # must not keep stale streak/cooldown state around
        for key in [k for k in self._planners if k not in live]:
            del self._planners[key]
        return results

    async def _planner_tick_one(self, dep, pspec, planner) -> Dict[str, Any]:
        self.planner_stats["ticks"] += 1
        gauges, totals, config, census = self._planner_telemetry(dep, pspec)
        verdicts = self._burn_verdicts.get((dep.key, pspec.name), [])
        decision = planner.tick(
            verdicts=verdicts, gauges=gauges, counter_totals=totals,
            current_config=config, census=census,
        )
        outcome = await self._planner_actuate(dep, pspec, decision)
        event = {
            "deployment": dep.key, "predictor": pspec.name,
            "action": decision.action, "rank": decision.rank,
            "reason": decision.reason, "knobs": dict(decision.knobs),
            **outcome,
        }
        self._planner_events.append(event)
        if decision.action != "hold" or outcome.get("vetoed"):
            logger.info(
                "planner %s/%s: %s (%s)%s", dep.key, pspec.name,
                decision.action, decision.reason,
                " [VETOED by burn page]" if outcome.get("vetoed") else "",
            )
        return event

    async def _planner_actuate(self, dep, pspec, decision) -> Dict[str, Any]:
        """Actuate one decision through the safe paths ONLY. The
        planner/autoscaler precedence rule lives here, at the last
        writer, so it holds even when verdicts changed between the
        planner's tick and this actuation: a page-severity burn verdict
        VETOES any scale-down in the same tick (counted, logged) —
        exactly the autoscaler's own page veto, so the two controllers
        resolve every same-tick conflict the same way (the table in
        docs/operate.md §"Autonomic planning")."""
        streak_key = (dep.key, pspec.name)
        if decision.action == "hold":
            self.planner_stats["holds"] += 1
            return {}
        if decision.action == "retune":
            from ..serving.continuous import RetuneError

            handles = [
                handle for handle, _ in self.components.values()
                if handle.spec.deployment == dep.key
                and handle.spec.predictor == pspec.name
            ]
            applied = refused = 0
            for handle in handles:
                try:
                    out = await handle.retune(
                        dict(decision.knobs), origin="planner"
                    )
                except RetuneError as e:
                    # out-of-census knobs refuse typed and change
                    # NOTHING — never half-applied across members
                    refused += 1
                    logger.warning(
                        "planner: retune refused by %s: %s",
                        handle.spec.name, e,
                    )
                    continue
                except Exception:  # noqa: BLE001 - a dead member must
                    # not stop the rest of the pool retuning
                    refused += 1
                    logger.exception(
                        "planner: retune failed on %s", handle.spec.name
                    )
                    continue
                if out is not None:
                    applied += 1
            self.planner_stats["retunes"] += applied
            self.planner_stats["retunes_refused"] += refused
            return {"retuned": applied, "refused": refused}
        # scale decisions rewrite replicas through the same clamped
        # spec path the HPA uses (store.apply -> reconcile)
        current = max(1, pspec.replicas)
        lo, hi = 1, current + 1
        if pspec.hpa_spec:
            from ..graph.spec import parse_hpa_spec

            lo, hi, _target = parse_hpa_spec(
                pspec.hpa_spec, who=f"{dep.key}/{pspec.name}"
            )
        if decision.action == "scale_down":
            if self._worst_burn(dep.key, pspec.name) == "page":
                self.planner_stats["vetoes"] += 1
                self._scale_down_streak.pop(streak_key, None)
                return {"vetoed": True}
            desired = max(lo, current - 1)
        else:
            desired = min(hi, current + 1)
            if self.placement is not None and pspec.tpu_mesh:
                # same never-past-the-chips clamp as the autoscaler
                per_replica = 1
                for v in pspec.tpu_mesh.values():
                    per_replica *= int(v)
                desired = min(
                    desired,
                    current + self.placement.capacity()["free"] // per_replica,
                )
        if desired == current:
            return {"replicas": current, "clamped": True}
        updated = dep.clone()
        for p in updated.predictors:
            if p.name == pspec.name:
                p.replicas = desired
        self.store.apply(updated)  # generation bump -> reconcile
        # shared hysteresis: a planner scale event restarts the HPA's
        # stabilization window (the autoscaler pops the same streak on
        # its own scale events) — neither controller can saw against
        # the other's fresh decision
        self._scale_down_streak.pop(streak_key, None)
        key = "scale_ups" if decision.action == "scale_up" else "scale_downs"
        self.planner_stats[key] += 1
        logger.info(
            "planner %s/%s -> %d replicas", dep.key, pspec.name, desired
        )
        return {"replicas": desired}

    async def run(self, stop_event: Optional[asyncio.Event] = None) -> None:
        """Consume store events forever (controller-runtime manager parity,
        reference: operator/main.go:49-93). The autoscaler evaluates every
        ``autoscale_period_s`` between events."""
        q = self.store.watch()
        # reconcile pre-existing resources (controller restart)
        for dep in self.store.list():
            await self.reconcile(dep.clone())
        loop = asyncio.get_running_loop()
        next_autoscale = loop.time() + self.autoscale_period_s
        next_rollout = loop.time() + self.rollout_period_s
        next_fleet = loop.time() + self.fleet_period_s
        next_planner = loop.time() + self.planner_period_s
        try:
            while stop_event is None or not stop_event.is_set():
                if loop.time() >= next_autoscale:
                    next_autoscale = loop.time() + self.autoscale_period_s
                    try:
                        await self.autoscale_once()
                    except Exception:  # noqa: BLE001 - probe hiccups must
                        # not kill the manager loop
                        logger.exception("autoscale pass failed")
                if loop.time() >= next_fleet:
                    next_fleet = loop.time() + self.fleet_period_s
                    try:
                        await self.fleet_scrape_once()
                    except Exception:  # noqa: BLE001 - a slow/dead member's
                        # scrape must not kill the manager loop
                        logger.exception("fleet scrape failed")
                if loop.time() >= next_planner:
                    next_planner = loop.time() + self.planner_period_s
                    try:
                        await self.planner_tick_once()
                    except Exception:  # noqa: BLE001 - a bad profile or
                        # dead member must not kill the manager loop
                        logger.exception("planner pass failed")
                if loop.time() >= next_rollout:
                    next_rollout = loop.time() + self.rollout_period_s
                    try:
                        # analysis windows are plan-interval-gated inside;
                        # this cadence only bounds verdict latency. Weight
                        # changes surface as store events consumed below.
                        verdicts = self.rollout.tick_all()
                        # shadow verdicts change no spec (no store event,
                        # so no reconcile): rewire mirrors directly, or a
                        # failed/promoted shadow would keep receiving a
                        # duplicate of every request forever
                        for vkey in verdicts:
                            vdep = next(
                                (d for d in self.store.list()
                                 if d.key == vkey), None,
                            )
                            if vdep is not None:
                                self._wire_shadow_mirrors(vdep)
                    except Exception:  # noqa: BLE001 - one bad rollout
                        # must not kill the manager loop
                        logger.exception("rollout pass failed")
                try:
                    event, dep = await asyncio.wait_for(q.get(), timeout=0.2)
                except asyncio.TimeoutError:
                    continue
                try:
                    if event == EVENT_DELETED:
                        await self.delete(dep)
                    else:
                        await self.reconcile(dep.clone())
                except Exception:  # noqa: BLE001 - one bad resource must not
                    # stop reconciling the others (controller-runtime requeues
                    # on error rather than crashing the manager)
                    logger.exception("reconcile %s failed", dep.key)
        finally:
            self.store.unwatch(q)

    async def shutdown(self) -> None:
        for name in list(self.components):
            handle, _ = self.components.pop(name)
            if self.placement is not None:
                self.placement.release(name)
            await handle.stop()
