"""Component runtimes: how the reconciler materializes desired state.

The reference operator emits K8s Deployments and lets kubelet run pods
(reference: operator/controllers/seldondeployment_controller.go:855-900);
here a runtime starts the same logical components on the TPU host:

  * ``InProcessRuntime`` — engines and microservices as asyncio servers
    inside the controller process, on real localhost ports. This is both
    the test tier (SURVEY §4: in-process fake placement) and the
    single-host production mode: co-located graph units stay INPROCESS so
    a request never leaves the process between nodes.
  * ``SubprocessRuntime`` — one OS process per component, env-injected
    exactly like the engine sidecar (``ENGINE_PREDICTOR`` b64 —
    reference: operator/controllers/seldondeployment_engine.go:101-214)
    and the wrapper (``PREDICTIVE_UNIT_PARAMETERS`` env).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


DRAIN_ANNOTATION = "seldon.io/drain-seconds"
DEFAULT_DRAIN_S = 10.0


def _drain_seconds(spec: "ComponentSpec") -> float:
    """Rolling-update drain budget from the predictor's annotations
    (``seldon.io/drain-seconds``, default 10 — the reference's preStop
    sleep made configurable)."""
    ann = (spec.engine_spec or {}).get("annotations") or {}
    try:
        return float(ann.get(DRAIN_ANNOTATION, DEFAULT_DRAIN_S))
    except (TypeError, ValueError):
        return DEFAULT_DRAIN_S


@dataclass
class ComponentSpec:
    """One schedulable unit: an engine, a microservice, or an explainer."""

    name: str  # unique within the runtime, e.g. "default/dep/predictor-0/engine"
    kind: str  # "engine" | "microservice" | "explainer"
    deployment: str
    predictor: str
    replica: int = 0
    # engine kinds carry the full predictor spec dict; microservices carry
    # the interface name + parameters
    engine_spec: Optional[Dict[str, Any]] = None
    interface_name: Optional[str] = None
    parameters: Optional[List[Dict[str, Any]]] = None
    env: Dict[str, str] = field(default_factory=dict)
    http_port: int = 0
    grpc_port: int = 0
    # routable components receive external traffic from the gateway:
    # engines, and direct-exposed models in no-engine mode
    routable: bool = False
    # jax.sharding.Mesh over the engine's placement-allocated device block
    # (in-process runtime only; subprocess engines rebuild it from the
    # spec's tpuMesh over their own host's devices)
    mesh: Any = None


class ComponentHandle:
    """A running component; reconciler tracks these by spec name."""

    def __init__(self, spec: ComponentSpec):
        self.spec = spec
        # absolute start stamp, display/status only — never interval math
        # seldon-lint: disable=wall-clock
        self.started_at = time.time()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.spec.http_port}"

    async def ready(self) -> bool:  # pragma: no cover - overridden
        raise NotImplementedError

    async def stop(self) -> None:  # pragma: no cover - overridden
        raise NotImplementedError

    async def load(self) -> Optional[float]:
        """In-flight request concurrency (autoscaler signal); None when
        this component kind has no load probe."""
        return None

    async def fleet(self) -> Optional[dict]:
        """This member's /fleet telemetry payload (metric snapshot +
        profiler/burn summaries); None when the component kind has no
        fleet scrape."""
        return None

    async def retune(
        self, knobs: dict, origin: str = "planner"
    ) -> Optional[dict]:
        """Actuate a live scheduler retune on this member through the
        safe path (the engine's POST /retune — staged, validated against
        the boot compile census, applied at a poll boundary). Returns
        the per-unit ``{"changed": ...}`` payload, or None when the
        component kind has no retune surface. Out-of-census refusals
        raise (typed RetuneError in-process, HTTP 409 over the wire) —
        the planner prunes those configs instead of retrying."""
        return None


class _InProcessHandle(ComponentHandle):
    def __init__(
        self,
        spec: ComponentSpec,
        tasks: List[asyncio.Task],
        probe,
        grpc_server=None,
        app=None,
        rest_app=None,
    ):
        super().__init__(spec)
        self._tasks = tasks
        self._probe = probe
        self._grpc_server = grpc_server
        self.app = app
        self.rest_app = rest_app

    async def ready(self) -> bool:
        try:
            out = self._probe()
            if asyncio.iscoroutine(out):
                out = await out
            return bool(out)
        except Exception:
            return False

    async def load(self) -> Optional[float]:
        if self.app is None:
            return None
        return float(getattr(self.app, "inflight", 0))

    async def fleet(self) -> Optional[dict]:
        fn = getattr(self.app, "fleet_summary", None)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:  # noqa: BLE001 - telemetry must not fail ops
            return None

    async def retune(
        self, knobs: dict, origin: str = "planner"
    ) -> Optional[dict]:
        walk = getattr(self.app, "units_with", None)
        if walk is None:
            return None
        targets = list(walk("retune"))
        if not targets:
            return None
        loop = asyncio.get_running_loop()
        units: dict = {}
        for name, target in targets:
            # blocking until the scheduler's poll boundary: off the
            # event loop (same discipline as the /retune route).
            # RetuneError propagates — an out-of-census config is the
            # planner's signal to prune, not a fault to swallow.
            units[name] = await loop.run_in_executor(
                None, lambda f=target.retune: f(knobs, origin)
            )
        return {"units": units}

    async def stop(self) -> None:
        # graceful drain before teardown (reference preStop idiom:
        # `curl /pause; sleep 10` — seldondeployment_engine.go:173-177;
        # here pause ALWAYS rejects new work first, then the wait is
        # exact on the in-flight gauge, bounded by seldon.io/drain-seconds)
        if self.app is not None:
            self.app.paused = True
            drain_s = _drain_seconds(self.spec)
            loop = asyncio.get_running_loop()
            deadline = loop.time() + drain_s
            while getattr(self.app, "inflight", 0) > 0 and loop.time() < deadline:
                await asyncio.sleep(0.02)
        if self._grpc_server is not None:
            await self._grpc_server.stop(grace=0.1)
        tasks = list(self._tasks)
        # engine handles: the readiness poll loop and the executor's unit
        # clients outlive the server tasks unless shut down here — leaking
        # them keeps dead graphs polling forever across rolling updates
        if self.app is not None:
            ready_task = getattr(self.app, "_ready_task", None)
            if ready_task is not None:
                tasks.append(ready_task)
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if self.app is not None:
            try:
                await self.app.executor.close()
            except Exception:  # noqa: BLE001
                pass
            # the CloudEvents sink owns a worker thread + queue; without
            # this, every rolling update leaks one per replaced engine
            sink = getattr(getattr(self.app, "request_logger", None), "sink", None)
            if sink is not None and hasattr(sink, "close"):
                try:
                    sink.close()
                except Exception:  # noqa: BLE001
                    pass
        pool = getattr(self.rest_app, "_hook_pool", None)
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)


class InProcessRuntime:
    """Run components as asyncio servers in the controller's loop."""

    def __init__(self, open_ports: bool = True, grpc: bool = False):
        # open_ports=False → don't bind sockets (pure logical placement,
        # the reconciler-unit-test mode); engine apps are still constructed
        # and reachable via handle.app
        self.open_ports = open_ports
        self.grpc = grpc

    async def start(self, spec: ComponentSpec) -> ComponentHandle:
        from ..graph.service import EngineApp
        from ..graph.spec import PredictorSpec, default_predictor, validate_predictor

        if spec.kind == "engine":
            from ..graph.service import RequestLogger

            pspec = PredictorSpec.from_dict(spec.engine_spec)
            pspec = default_predictor(pspec)
            validate_predictor(pspec)
            app = EngineApp(
                pspec, mesh=spec.mesh, request_logger=RequestLogger.from_env()
            )
            app.start_readiness_loop()
            tasks = []
            if self.open_ports:
                spec.http_port = spec.http_port or free_port()
                rest = app.rest_app()
                # bind BEFORE returning the handle: readiness is probed
                # in-process (no socket), so a lazily-bound listener could
                # report Available while the port still refuses connections
                await rest.start("127.0.0.1", spec.http_port)
                tasks.append(asyncio.create_task(rest.serve()))
            grpc_server = None
            if self.open_ports and self.grpc:
                spec.grpc_port = spec.grpc_port or free_port()
                grpc_server = app.grpc_server()
                grpc_server.add_insecure_port(f"127.0.0.1:{spec.grpc_port}")
                await grpc_server.start()
            # probe the graph directly rather than app.graph_ready — the
            # cached flag initializes True before the first poll, which would
            # make the reconciler's rolling-update readiness gate vacuous
            handle = _InProcessHandle(
                spec, tasks, lambda: app.executor.ready(), grpc_server, app=app
            )
            return handle

        if spec.kind in ("microservice", "explainer"):
            from ..microservice import build_user_object
            from ..wrapper import ServerState, get_rest_microservice
            import json as _json

            user_object = build_user_object(
                spec.interface_name, _json.dumps(spec.parameters or [])
            )
            if hasattr(user_object, "load"):
                await asyncio.get_running_loop().run_in_executor(None, user_object.load)
            state = ServerState()
            rest = get_rest_microservice(user_object, state)
            tasks = []
            if self.open_ports:
                spec.http_port = spec.http_port or free_port()
                await rest.start("127.0.0.1", spec.http_port)
                tasks.append(asyncio.create_task(rest.serve()))
            handle = _InProcessHandle(
                spec,
                tasks,
                lambda: state.ready and (not tasks or rest.is_serving()),
                rest_app=rest,
            )
            handle.user_object = user_object
            return handle

        raise ValueError(f"unknown component kind {spec.kind!r}")


class _SubprocessHandle(ComponentHandle):
    def __init__(self, spec: ComponentSpec, proc: subprocess.Popen):
        super().__init__(spec)
        self.proc = proc

    async def ready(self) -> bool:
        if self.proc.poll() is not None:
            return False

        def probe() -> bool:
            try:
                with urllib.request.urlopen(f"{self.url}/ready", timeout=1.0) as r:
                    return r.status == 200
            except Exception:
                return False

        return await asyncio.get_running_loop().run_in_executor(None, probe)

    def _probe_inflight(self) -> Optional[float]:
        """GET /inflight. Returns the gauge, 0.0 when the process is GONE
        (connection refused / dead proc — nothing left to drain), or None
        when the state is UNKNOWN (timeout, slow event loop): callers must
        keep waiting on None, not treat it as drained — probes time out
        exactly when the engine is busiest."""
        if self.proc.poll() is not None:
            return 0.0
        try:
            with urllib.request.urlopen(f"{self.url}/inflight", timeout=0.5) as r:
                return float(json.loads(r.read()).get("inflight", 0))
        except urllib.error.URLError as e:
            if isinstance(getattr(e, "reason", None), ConnectionRefusedError):
                return 0.0
            return None
        except Exception:
            return None

    async def load(self) -> Optional[float]:
        out = await asyncio.get_running_loop().run_in_executor(
            None, self._probe_inflight
        )
        return None if out is None else out

    async def fleet(self) -> Optional[dict]:
        if self.proc.poll() is not None:
            return None

        def probe() -> Optional[dict]:
            try:
                with urllib.request.urlopen(
                    f"{self.url}/fleet", timeout=2.0
                ) as r:
                    return json.loads(r.read())
            except Exception:
                return None

        return await asyncio.get_running_loop().run_in_executor(None, probe)

    async def retune(
        self, knobs: dict, origin: str = "planner"
    ) -> Optional[dict]:
        if self.proc.poll() is not None:
            return None

        def post() -> Optional[dict]:
            body = json.dumps(
                {"knobs": knobs, "origin": origin}
            ).encode()
            req = urllib.request.Request(
                f"{self.url}/retune", data=body,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=15.0) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                if e.code == 409:
                    # out-of-census refusal: surface it typed so the
                    # planner prunes the config (parity with the
                    # in-process RetuneError path)
                    from ..serving.continuous import RetuneError

                    raise RetuneError(e.read().decode()) from None
                if e.code == 501:
                    return None  # member has no retune surface
                raise
            except Exception:  # noqa: BLE001 - member mid-restart
                return None

        return await asyncio.get_running_loop().run_in_executor(None, post)

    async def stop(self) -> None:
        # graceful drain first (reference preStop: curl /pause; sleep —
        # operator/controllers/seldondeployment_engine.go:173-177): pause
        # rejects new work, then poll /inflight until live requests hit
        # zero (exact, not a fixed sleep), bounded by seldon.io/drain-seconds
        loop = asyncio.get_running_loop()

        def pause():
            try:
                urllib.request.urlopen(f"{self.url}/pause", timeout=0.5).read()
            except Exception:
                pass

        await loop.run_in_executor(None, pause)
        deadline = loop.time() + _drain_seconds(self.spec)
        while loop.time() < deadline:
            n = await loop.run_in_executor(None, self._probe_inflight)
            if n is not None and n <= 0:
                break
            await asyncio.sleep(0.1)
        self.proc.terminate()
        try:
            await asyncio.get_running_loop().run_in_executor(None, self.proc.wait, 5)
        except Exception:
            self.proc.kill()


class SubprocessRuntime:
    """One OS process per component (the multi-process production mode)."""

    def __init__(self, python: str = sys.executable):
        self.python = python

    async def start(self, spec: ComponentSpec) -> ComponentHandle:
        import base64
        import json as _json

        spec.http_port = spec.http_port or free_port()
        env = {**os.environ, **spec.env}
        # scope persisted component state per deployment/predictor
        # (persistence.state_key reads these — reference: persistence.py:21)
        env.setdefault("SELDON_DEPLOYMENT_ID", spec.deployment.replace("/", "-"))
        env.setdefault("PREDICTOR_ID", spec.predictor)
        if spec.kind == "engine":
            env["ENGINE_PREDICTOR"] = base64.b64encode(
                _json.dumps(spec.engine_spec).encode()
            ).decode()
            cmd = [
                self.python, "-m", "seldon_core_tpu.engine_main",
                "--host", "127.0.0.1",
                "--http-port", str(spec.http_port),
                "--no-grpc",
            ]
        else:
            env["PREDICTIVE_UNIT_PARAMETERS"] = _json.dumps(spec.parameters or [])
            env["PREDICTIVE_UNIT_SERVICE_PORT"] = str(spec.http_port)
            cmd = [
                self.python, "-m", "seldon_core_tpu.microservice",
                spec.interface_name, "REST",
                "--host", "127.0.0.1",
                "--service-port", str(spec.http_port),
            ]
        proc = subprocess.Popen(cmd, env=env)
        return _SubprocessHandle(spec, proc)
