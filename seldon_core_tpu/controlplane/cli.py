"""Control-plane CLI: apply/get/delete deployments + run the controller.

kubectl-equivalent for the file-backed resource store (the reference's
users drive the operator with `kubectl apply -f deployment.json` —
reference: testing/scripts/test_prepackaged_servers.py:7-35):

    python -m seldon_core_tpu.controlplane apply -f dep.json
    python -m seldon_core_tpu.controlplane get [name]
    python -m seldon_core_tpu.controlplane scale <name> <replicas> [--predictor P]
    python -m seldon_core_tpu.controlplane status <name>
    python -m seldon_core_tpu.controlplane delete <name>
    python -m seldon_core_tpu.controlplane controller --gateway-port 8003
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys

from .ingress import Gateway
from .reconciler import DeploymentController
from .resource import SeldonDeployment
from .runtime import InProcessRuntime, SubprocessRuntime
from .store import ResourceStore
from .placement import TpuPlacement

DEFAULT_STORE = os.environ.get("SELDON_TPU_STORE", "/tmp/seldon-tpu-store")


def main(argv=None) -> None:
    parser = argparse.ArgumentParser("seldon-tpu-ctl")
    parser.add_argument("--store-dir", default=DEFAULT_STORE)
    parser.add_argument("--namespace", "-n", default="default")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_apply = sub.add_parser("apply")
    p_apply.add_argument("-f", "--filename", required=True)

    p_get = sub.add_parser("get")
    p_get.add_argument("name", nargs="?")

    p_delete = sub.add_parser("delete")
    p_delete.add_argument("name")

    p_scale = sub.add_parser(
        "scale", help="set a predictor's replica count (kubectl scale parity)"
    )
    p_scale.add_argument("name")
    p_scale.add_argument("replicas", type=int)
    p_scale.add_argument(
        "--predictor", default=None,
        help="predictor to scale (default: the only one; required when several)",
    )

    p_status = sub.add_parser(
        "status", help="per-predictor replica/traffic rollup for one deployment"
    )
    p_status.add_argument("name")

    p_render = sub.add_parser(
        "render",
        help="emit Kubernetes YAML (Deployments/Services/HPAs/VirtualService "
        "with GKE TPU node-pool scheduling) for a SeldonDeployment",
    )
    p_render.add_argument("-f", "--filename", required=True)
    p_render.add_argument("-o", "--output", default="-",
                          help="output file (default stdout)")

    sub.add_parser(
        "crd",
        help="print the SeldonDeployment CustomResourceDefinition YAML "
        "(GitOps alternative to controller --kube's auto-install)",
    )

    p_ctl = sub.add_parser("controller")
    p_ctl.add_argument("--gateway-port", type=int, default=int(os.environ.get("GATEWAY_PORT", 8003)))
    p_ctl.add_argument("--subprocess-runtime", action="store_true",
                       help="one OS process per component instead of in-process asyncio")
    p_ctl.add_argument("--placement", action="store_true",
                       help="enable TPU device placement (needs jax)")
    p_ctl.add_argument("--poll-s", type=float, default=1.0,
                       help="store re-scan period (picks up sdctl writes from other processes)")
    p_ctl.add_argument("--kube", action="store_true",
                       help="operator mode: install the SeldonDeployment CRD "
                       "on a live cluster, watch CRs and converge rendered "
                       "objects (instead of the self-hosted runtime)")
    p_ctl.add_argument("--kube-server", default=None,
                       help="kube-apiserver URL (default: in-cluster; use "
                       "`kubectl proxy` + http://127.0.0.1:8001 from a laptop)")
    p_ctl.add_argument("--kube-token", default=None,
                       help="bearer token (default: in-cluster service account)")
    p_ctl.add_argument("--resync-s", type=float, default=30.0,
                       help="kube mode: level-triggered reconcile period")
    p_ctl.add_argument("--once", action="store_true",
                       help="kube mode: one reconcile pass then exit "
                       "(GitOps/CI: converge and report, no daemon)")
    p_ctl.add_argument("--leader-elect", action="store_true",
                       help="kube mode: coordination.k8s.io Lease leader "
                       "election — run replicas for HA; only the leader "
                       "reconciles")
    p_ctl.add_argument("--lease-duration-s", type=float, default=15.0,
                       help="kube mode: leader lease duration (takeover "
                       "happens within ~one duration of a leader dying)")

    p_build = sub.add_parser(
        "build",
        help="user code -> servable image build context (s2i counterpart)",
    )
    p_build.add_argument("--src", required=True,
                         help="directory with the user component")
    p_build.add_argument("--model-name", required=True,
                         help="module.Class (python) or a label (cpp)")
    p_build.add_argument("--api-type", default="REST",
                         choices=["REST", "GRPC", "BOTH", "FBS"])
    p_build.add_argument("--service-type", default="MODEL",
                         choices=["MODEL", "ROUTER", "TRANSFORMER",
                                  "OUTPUT_TRANSFORMER", "COMBINER"])
    p_build.add_argument("--persistence", action="store_true")
    p_build.add_argument("--language", default="python",
                         choices=["python", "cpp"])
    p_build.add_argument("--out", required=True,
                         help="build-context output directory")
    p_build.add_argument("--image", default=None,
                         help="also run `docker build -t IMAGE` when a "
                         "docker CLI is present")

    args = parser.parse_args(argv)
    logging.basicConfig(level="INFO", format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if args.cmd == "build":
        from ..build import docker_build, write_build_context

        files = write_build_context(
            src=args.src, out=args.out, model_name=args.model_name,
            api_type=args.api_type, service_type=args.service_type,
            persistence=args.persistence, language=args.language,
        )
        print(f"wrote build context ({len(files)} files) to {args.out}")
        if args.image:
            if docker_build(args.out, args.image):
                print(f"built image {args.image}")
            else:
                print("docker CLI not found — build the context with: "
                      f"docker build -t {args.image} {args.out}")
        return
    store = ResourceStore(persist_dir=args.store_dir)

    if args.cmd == "apply":
        with open(args.filename) as f:
            dep = SeldonDeployment.from_dict(json.load(f))
        if dep.namespace == "default" and args.namespace != "default":
            dep.namespace = args.namespace
        dep, event = store.apply(dep)
        print(f"seldondeployment.machinelearning.seldon.io/{dep.name} {event.lower()}")
        return

    if args.cmd == "render":
        from .k8s import render, to_yaml, validate_manifests

        with open(args.filename) as f:
            dep = SeldonDeployment.from_dict(json.load(f))
        if dep.namespace == "default" and args.namespace != "default":
            dep.namespace = args.namespace
        manifests = render(dep)
        validate_manifests(manifests)
        out = to_yaml(manifests)
        if args.output == "-":
            sys.stdout.write(out)
        else:
            with open(args.output, "w") as f:
                f.write(out)
            print(f"wrote {len(manifests)} objects to {args.output}", file=sys.stderr)
        return

    if args.cmd == "crd":
        from .k8s import to_yaml
        from .kube import CRD_MANIFEST

        sys.stdout.write(to_yaml([CRD_MANIFEST]))
        return

    if args.cmd == "get":
        deps = store.list(args.namespace)
        if args.name:
            deps = [d for d in deps if d.name == args.name]
            if not deps:
                print(f"not found: {args.name}", file=sys.stderr)
                raise SystemExit(1)
            print(json.dumps(deps[0].to_dict(), indent=2))
            return
        for d in deps:
            s = d.status
            print(f"{d.namespace}/{d.name}\tgen={d.generation}\t{s.state}\t{s.description}")
        return

    if args.cmd == "delete":
        ok = store.delete(args.name, args.namespace)
        print(
            f"seldondeployment.machinelearning.seldon.io/{args.name} "
            + ("deleted" if ok else "not found")
        )
        return

    if args.cmd == "scale":
        dep = store.get(args.name, args.namespace)
        if dep is None:
            print(f"not found: {args.name}", file=sys.stderr)
            raise SystemExit(1)
        dep = dep.clone()
        candidates = [
            p for p in dep.predictors
            if args.predictor is None or p.name == args.predictor
        ]
        if args.predictor is None and len(candidates) > 1:
            names = [p.name for p in dep.predictors]
            print(f"deployment has predictors {names}; pass --predictor", file=sys.stderr)
            raise SystemExit(1)
        if not candidates:
            print(f"no predictor {args.predictor!r} in {args.name}", file=sys.stderr)
            raise SystemExit(1)
        if args.replicas < 1:
            print("replicas must be >= 1", file=sys.stderr)
            raise SystemExit(1)
        candidates[0].replicas = args.replicas
        store.apply(dep)  # generation bump -> controller reconciles
        print(
            f"seldondeployment.machinelearning.seldon.io/{args.name} "
            f"predictor {candidates[0].name} scaled to {args.replicas}"
        )
        return

    if args.cmd == "status":
        dep = store.get(args.name, args.namespace)
        if dep is None:
            print(f"not found: {args.name}", file=sys.stderr)
            raise SystemExit(1)
        s = dep.status
        print(f"{dep.namespace}/{dep.name}  gen={dep.generation}  {s.state}  {s.description}")
        by_name = {ps.name: ps for ps in s.predictor_status}
        for p in dep.predictors:
            ps = by_name.get(p.name)
            avail = f"{ps.replicas_available}/{ps.replicas}" if ps else "?/?"
            extras = []
            if p.hpa_spec:
                extras.append(
                    f"hpa {p.hpa_spec.get('minReplicas', 1)}-{p.hpa_spec.get('maxReplicas')}"
                )
            if p.tpu_mesh:
                extras.append(f"mesh {p.tpu_mesh}")
            print(
                f"  {p.name}\treplicas {avail}\ttraffic {p.traffic}%"
                + ("\t" + ", ".join(extras) if extras else "")
            )
        return

    if args.cmd == "controller" and args.kube:
        from .kube import HttpKubeApi, KubeController, LeaderElector

        api = HttpKubeApi(server=args.kube_server, token=args.kube_token)
        ns = args.namespace if args.namespace != "default" else None
        elector = None
        if args.leader_elect and not args.once:
            elector = LeaderElector(
                api, namespace=args.namespace,
                lease_duration_s=args.lease_duration_s,
            )
        ctl = KubeController(
            api, namespace=ns, resync_s=args.resync_s, elector=elector
        )
        if args.once:
            ctl.install_crd()
            # unconditional: even a pre-existing CRD (e.g. created by a
            # racing replica or a prior run that exited early) may not be
            # Established yet, and the immediate list below would crash
            # the whole one-shot pass. Cheap when already serving: the
            # first successful list returns.
            if not ctl.wait_crd_established():
                print(json.dumps(
                    {"failed": 1, "error": "CRD not established in time"}
                ))
                raise SystemExit(1)
            ops = ctl.reconcile_all()
            print(json.dumps(ops))
            raise SystemExit(1 if ops.get("failed") else 0)
        try:
            ctl.run()
        except KeyboardInterrupt:
            pass
        return

    if args.cmd == "controller":
        runtime = SubprocessRuntime() if args.subprocess_runtime else InProcessRuntime()
        placement = TpuPlacement() if args.placement else None
        gateway = Gateway()
        controller = DeploymentController(
            store, runtime=runtime, placement=placement, gateway=gateway
        )

        async def run():
            tasks = [
                asyncio.create_task(controller.run()),
                asyncio.create_task(
                    gateway.app().serve_forever("0.0.0.0", args.gateway_port)
                ),
                asyncio.create_task(_rescan_loop(store, args.store_dir, args.poll_s)),
            ]
            await asyncio.gather(*tasks)

        try:
            asyncio.run(run())
        except KeyboardInterrupt:
            pass
        return


async def _rescan_loop(store: ResourceStore, persist_dir: str, period_s: float) -> None:
    """Pick up applies/deletes written by other sdctl processes: re-read the
    persist dir and diff against the in-memory view."""
    loop = asyncio.get_running_loop()
    while True:
        await asyncio.sleep(period_s)
        try:
            # file parsing off the loop so big stores don't stall the gateway
            fresh = await loop.run_in_executor(
                None, lambda: ResourceStore(persist_dir=persist_dir)
            )
        except Exception:
            continue
        fresh_keys = {d.key for d in fresh.list()}
        for dep in fresh.list():
            mine = store.get(dep.name, dep.namespace)
            if (
                mine is None
                or mine.spec_hash() != dep.spec_hash()
                or mine.annotations != dep.annotations
            ):
                store.apply(dep)
        for dep in list(store.list()):
            if dep.key not in fresh_keys:
                store.delete(dep.name, dep.namespace)


if __name__ == "__main__":
    main()
