"""Topology-aware TPU device placement.

The reference schedules replicas as pods onto nodes and leaves placement
to the K8s scheduler (reference: operator/controllers/
seldondeployment_controller.go:855-900 createDeployments). On TPU the
scarce resource is chips wired by ICI, so the control plane allocates
device blocks itself: a predictor asks for a mesh shape (``tpuMesh`` on
PredictorSpec), and the allocator hands back a contiguous block of
devices that (1) stays within one host process when it fits, so the mesh
rides ICI not DCN, and (2) otherwise spans the fewest processes possible.
Equivalent of GKE TPU node-pool topology-aware scheduling
(google.com/tpu resources + topology selectors).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence


class PlacementError(RuntimeError):
    pass


def _mesh_size(mesh_spec: Dict[str, int]) -> int:
    size = 1
    for v in mesh_spec.values():
        size *= int(v)
    return size


class TpuPlacement:
    """Tracks which devices are assigned to which component key."""

    def __init__(self, devices: Optional[Sequence[Any]] = None):
        self._devices = list(devices) if devices is not None else None
        self._assignments: Dict[str, List[Any]] = {}

    @property
    def devices(self) -> List[Any]:
        if self._devices is None:
            import jax

            # stable topology order: host process first, then core coords —
            # adjacent entries share ICI links
            self._devices = sorted(
                jax.devices(),
                key=lambda d: (d.process_index, getattr(d, "coords", None) or d.id),
            )
        return self._devices

    def _free(self) -> List[Any]:
        used = {id(d) for devs in self._assignments.values() for d in devs}
        return [d for d in self.devices if id(d) not in used]

    def allocate(self, key: str, mesh_spec: Optional[Dict[str, int]]) -> List[Any]:
        """Reserve a device block for component `key`.

        mesh_spec None means "one device". Prefers a block fully inside one
        process (ICI-only); falls back to the smallest process span.
        """
        if key in self._assignments:
            return self._assignments[key]
        n = _mesh_size(mesh_spec) if mesh_spec else 1
        free = self._free()
        if len(free) < n:
            raise PlacementError(
                f"{key}: wants {n} devices, only {len(free)} free of {len(self.devices)}"
            )
        # group free devices by process, try to fit inside one
        by_proc: Dict[int, List[Any]] = {}
        for d in free:
            by_proc.setdefault(getattr(d, "process_index", 0), []).append(d)
        block: Optional[List[Any]] = None
        for procs_needed in range(1, len(by_proc) + 1):
            # greedy: largest processes first, take contiguous runs
            pools = sorted(by_proc.values(), key=len, reverse=True)[:procs_needed]
            pool = [d for p in pools for d in p]
            if len(pool) >= n:
                block = pool[:n]
                break
        if block is None:
            block = free[:n]
        self._assignments[key] = block
        return block

    def release(self, key: str) -> None:
        self._assignments.pop(key, None)

    def assigned(self, key: str) -> Optional[List[Any]]:
        return self._assignments.get(key)

    def mesh_for(self, key: str, mesh_spec: Dict[str, int]):
        """Build a jax.sharding.Mesh over the allocated block."""
        import numpy as np
        from jax.sharding import Mesh

        devs = self._assignments.get(key)
        if devs is None:
            devs = self.allocate(key, mesh_spec)
        shape = tuple(int(v) for v in mesh_spec.values())
        if math.prod(shape) != len(devs):
            raise PlacementError(
                f"{key}: mesh {mesh_spec} wants {math.prod(shape)} devices, have {len(devs)}"
            )
        arr = np.array(devs, dtype=object).reshape(shape)
        return Mesh(arr, tuple(mesh_spec.keys()))

    def capacity(self) -> Dict[str, int]:
        free = len(self._free())
        return {"total": len(self.devices), "free": free, "used": len(self.devices) - free}
