"""SeldonDeployment resource: the deployment-level config surface.

Schema parity with the reference CRD (reference:
proto/seldon_deployment.proto:12-88 — SeldonDeployment{metadata, spec{
name, predictors[], annotations, oauth...}, status{state, description,
predictorStatus[]}}; Go mirror operator/api/v1alpha2/
seldondeployment_types.go:246-370). Accepts both k8s-manifest style
(apiVersion/kind/metadata/spec) and flat dicts.
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..graph.spec import GraphSpecError, PredictorSpec

STATE_CREATING = "Creating"
STATE_AVAILABLE = "Available"
STATE_FAILED = "Failed"


@dataclass
class PredictorStatus:
    """Per-predictor rollup (reference: seldon_deployment.proto:72-80)."""

    name: str
    replicas: int = 0
    replicas_available: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "replicas": self.replicas,
            "replicasAvailable": self.replicas_available,
        }


@dataclass
class DeploymentStatus:
    """Status rollup written by the reconciler (reference:
    seldondeployment_controller.go:1111-1119 Available/Creating)."""

    state: str = STATE_CREATING
    description: str = ""
    predictor_status: List[PredictorStatus] = field(default_factory=list)
    # progressive-delivery checkpoint (rollout/controller.py): status
    # writes skip the generation bump, so the rollout state machine can
    # durably record its resume point — after a control-plane restart a
    # mid-ramp rollout keeps its TRUE pre-rollout baseline weights and a
    # promoted/rolled-back one stays terminal instead of re-ramping
    rollout: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "state": self.state,
            "description": self.description,
            "predictorStatus": [p.to_dict() for p in self.predictor_status],
        }
        if self.rollout is not None:
            out["rollout"] = self.rollout
        return out

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DeploymentStatus":
        return DeploymentStatus(
            state=d.get("state", STATE_CREATING),
            description=d.get("description", ""),
            predictor_status=[
                PredictorStatus(
                    name=p["name"],
                    replicas=int(p.get("replicas", 0)),
                    replicas_available=int(p.get("replicasAvailable", 0)),
                )
                for p in d.get("predictorStatus", [])
            ],
            rollout=d.get("rollout"),
        )


@dataclass
class SeldonDeployment:
    name: str
    predictors: List[PredictorSpec]
    namespace: str = "default"
    annotations: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    protocol: str = "seldon"
    generation: int = 1
    status: DeploymentStatus = field(default_factory=DeploymentStatus)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SeldonDeployment":
        if "spec" in d:  # k8s-manifest style
            meta = d.get("metadata") or {}
            spec = d["spec"]
            name = spec.get("name") or meta.get("name")
            namespace = meta.get("namespace", "default")
            annotations = {**(meta.get("annotations") or {}), **(spec.get("annotations") or {})}
            labels = meta.get("labels") or {}
        else:
            spec = d
            name = d.get("name")
            namespace = d.get("namespace", "default")
            annotations = d.get("annotations") or {}
            labels = d.get("labels") or {}
        if not name:
            raise GraphSpecError("deployment missing name")
        predictors = [PredictorSpec.from_dict(p) for p in spec.get("predictors", [])]
        if not predictors:
            raise GraphSpecError(f"deployment {name!r} has no predictors")
        return SeldonDeployment(
            name=name,
            namespace=namespace,
            predictors=predictors,
            annotations=annotations,
            labels=labels,
            protocol=spec.get("protocol", "seldon"),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "apiVersion": "machinelearning.seldon.io/v1alpha2",
            "kind": "SeldonDeployment",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "annotations": self.annotations,
                "labels": self.labels,
                "generation": self.generation,
            },
            "spec": {
                "name": self.name,
                "protocol": self.protocol,
                "predictors": [p.to_dict() for p in self.predictors],
            },
            "status": self.status.to_dict(),
        }

    def spec_hash(self, include_replicas: bool = True) -> str:
        """Stable digest of the spec (not metadata/status) used by the
        reconciler's change diff, like the operator's JSON-equality check
        (reference: seldondeployment_controller.go:842-853 jsonEquals).

        ``include_replicas=False`` gives the component-naming variant: a
        scale event must not rename (and so recreate) surviving replica
        components, only add/remove. Traffic weights are excluded there
        too — a canary ramp step (rollout controller rewriting
        ``PredictorSpec.traffic``) re-routes the gateway, it must never
        restart an engine mid-rollout."""
        import hashlib

        preds = [p.to_dict() for p in self.predictors]
        if not include_replicas:
            preds = [{**p, "replicas": None, "traffic": None} for p in preds]
            # disagg pool sizes are replica counts too: scaling the
            # prefill or decode pool must add/remove pool members, never
            # rename (and so restart) the survivors
            scale_keys = (
                "seldon.io/disagg-prefill-replicas",
                "seldon.io/disagg-decode-replicas",
            )
            preds = [
                {
                    **p,
                    "annotations": {
                        k: v
                        for k, v in (p.get("annotations") or {}).items()
                        if k not in scale_keys
                    },
                }
                for p in preds
            ]
        blob = json.dumps(
            {"protocol": self.protocol, "predictors": preds},
            sort_keys=True,
        )
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def clone(self) -> "SeldonDeployment":
        return copy.deepcopy(self)

    def predictor(self, name: str) -> Optional[PredictorSpec]:
        for p in self.predictors:
            if p.name == name:
                return p
        return None
