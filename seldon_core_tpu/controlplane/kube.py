"""Live-Kubernetes operator mode: watch SeldonDeployment CRs, apply the
rendered manifests, correct drift.

Counterpart of the reference's kubebuilder controller (reference:
operator/controllers/seldondeployment_controller.go:1067-1122 Reconcile;
owner-indexed watches ``SetupWithManager`` :1129-1199; JSON-equality diff
``jsonEquals`` :842) — the piece that turns ``k8s.py``'s render-only output
into a *controller*: a level-triggered loop that converges a live cluster
onto the CR's desired state and re-converges when someone mutates an owned
object out from under it.

Design differences from the reference, on purpose:

* **No client-go / controller-runtime** — a minimal typed client over the
  Kubernetes REST API (``HttpKubeApi``) with an injectable fake for tests
  (mirroring envtest's role, reference: operator/controllers/suite_test.go:
  17-30). The controller logic is transport-agnostic.
* **Level-triggered resync instead of edge-triggered caches**: every
  ``resync_s`` (and on every watch event) each CR is re-reconciled from
  scratch.  Apply is idempotent — create if absent, replace only when the
  desired spec is not a subset of the live object (``subset_equal``), so a
  converged cluster sees zero writes per cycle.
* **Label-based ownership** (``seldon-deployment-id`` +
  ``app.kubernetes.io/managed-by``) for pruning, *plus* ownerReferences on
  every object so a real cluster's GC also works.

The webhook-defaulting/validation step the reference runs server-side
(seldondeployment_webhook.go) happens inside ``k8s.render`` here.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .k8s import render, validate_manifests
from .resource import SeldonDeployment

logger = logging.getLogger(__name__)

GROUP = "machinelearning.seldon.io"
VERSION = "v1alpha2"
PLURAL = "seldondeployments"
MANAGED_BY = "seldon-core-tpu"

# kind -> (api prefix, plural). Everything k8s.render can emit.
KIND_ROUTES: Dict[str, Tuple[str, str]] = {
    "Deployment": ("apis/apps/v1", "deployments"),
    "StatefulSet": ("apis/apps/v1", "statefulsets"),
    "Service": ("api/v1", "services"),
    "ConfigMap": ("api/v1", "configmaps"),
    "HorizontalPodAutoscaler": ("apis/autoscaling/v2", "horizontalpodautoscalers"),
    "VirtualService": ("apis/networking.istio.io/v1beta1", "virtualservices"),
    "SeldonDeployment": (f"apis/{GROUP}/{VERSION}", PLURAL),
    "CustomResourceDefinition": (
        "apis/apiextensions.k8s.io/v1", "customresourcedefinitions"
    ),
}

# CRD for the SeldonDeployment resource itself: schema is open
# (x-kubernetes-preserve-unknown-fields) because k8s.render's webhook-
# equivalent defaulting/validation is the authoritative check, exactly like
# the reference's validating webhook rather than OpenAPI structural schema
# (reference: seldondeployment_webhook.go:388-411).
CRD_MANIFEST: Dict[str, Any] = {
    "apiVersion": "apiextensions.k8s.io/v1",
    "kind": "CustomResourceDefinition",
    "metadata": {"name": f"{PLURAL}.{GROUP}"},
    "spec": {
        "group": GROUP,
        "names": {
            "kind": "SeldonDeployment",
            "listKind": "SeldonDeploymentList",
            "plural": PLURAL,
            "singular": "seldondeployment",
            "shortNames": ["sdep"],
        },
        "scope": "Namespaced",
        "versions": [
            {
                "name": VERSION,
                "served": True,
                "storage": True,
                "subresources": {"status": {}},
                "schema": {
                    "openAPIV3Schema": {
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                    }
                },
            }
        ],
    },
}


class KubeApiError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"kube api {status}: {message}")
        self.status = status


class KubeApi:
    """Minimal REST surface the controller needs. Paths are full resource
    paths like ``apis/apps/v1/namespaces/default/deployments[/name]``."""

    def get(self, path: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def list(self, path: str, label_selector: str = "") -> List[Dict[str, Any]]:
        raise NotImplementedError

    def create(self, path: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def replace(self, path: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def delete(self, path: str) -> bool:
        raise NotImplementedError


class HttpKubeApi(KubeApi):
    """Talk to a real kube-apiserver. In-cluster by default (service-account
    token + CA from the pod filesystem, KUBERNETES_SERVICE_HOST env), or an
    explicit ``server``/``token`` pair (e.g. `kubectl proxy` => server=
    "http://127.0.0.1:8001", token=None)."""

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, server: Optional[str] = None, token: Optional[str] = None,
                 ca_file: Optional[str] = None, timeout: float = 10.0):
        import os

        if server is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            if not host:
                raise RuntimeError(
                    "not in-cluster (no KUBERNETES_SERVICE_HOST) and no "
                    "--kube-server given; try `kubectl proxy` + "
                    "--kube-server http://127.0.0.1:8001"
                )
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            server = f"https://{host}:{port}"
            if token is None and os.path.exists(f"{self.SA_DIR}/token"):
                with open(f"{self.SA_DIR}/token") as f:
                    token = f.read().strip()
            if ca_file is None and os.path.exists(f"{self.SA_DIR}/ca.crt"):
                ca_file = f"{self.SA_DIR}/ca.crt"
        self.server = server.rstrip("/")
        self.token = token
        self._ctx = None
        if self.server.startswith("https"):
            import ssl

            self._ctx = (
                ssl.create_default_context(cafile=ca_file)
                if ca_file else ssl.create_default_context()
            )
        self.timeout = timeout

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 query: str = "") -> Tuple[int, Any]:
        import urllib.error
        import urllib.request

        url = f"{self.server}/{path}{('?' + query) if query else ''}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout, context=self._ctx
            ) as r:
                return r.status, json.loads(r.read() or b"null")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except Exception:  # noqa: BLE001
                payload = {}
            return e.code, payload

    def get(self, path: str) -> Optional[Dict[str, Any]]:
        status, body = self._request("GET", path)
        if status == 404:
            return None
        if status >= 400:
            raise KubeApiError(status, str(body.get("message", body)))
        return body

    def list(self, path: str, label_selector: str = "") -> List[Dict[str, Any]]:
        import urllib.parse

        q = f"labelSelector={urllib.parse.quote(label_selector)}" if label_selector else ""
        status, body = self._request("GET", path, query=q)
        if status >= 400:
            raise KubeApiError(status, str(body.get("message", body)))
        return body.get("items", [])

    def create(self, path: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        status, body = self._request("POST", path, obj)
        if status >= 400:
            raise KubeApiError(status, str(body.get("message", body)))
        return body

    def replace(self, path: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        status, body = self._request("PUT", path, obj)
        if status >= 400:
            raise KubeApiError(status, str(body.get("message", body)))
        return body

    def delete(self, path: str) -> bool:
        status, body = self._request("DELETE", path)
        if status == 404:
            return False
        if status >= 400:
            raise KubeApiError(status, str(body.get("message", body)))
        return True

    def watch(self, path: str, timeout_s: float = 300.0):
        """Kubernetes watch stream: yields event dicts ({type, object})
        until the server closes the window. The controller treats every
        event as 'reconcile now' — level-triggered logic stays the source
        of truth, the watch only shortens reaction time."""
        import urllib.request

        url = f"{self.server}/{path}?watch=1&timeoutSeconds={int(timeout_s)}"
        req = urllib.request.Request(url)
        req.add_header("Accept", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        with urllib.request.urlopen(
            req, timeout=timeout_s + 10, context=self._ctx
        ) as r:
            for line in r:
                if not line.strip():
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue


def object_path(kind: str, namespace: Optional[str], name: Optional[str] = None) -> str:
    """REST path for a (kind, namespace, name). Cluster-scoped kinds (the
    CRD itself) ignore namespace."""
    if kind not in KIND_ROUTES:
        raise ValueError(f"no API route for kind {kind!r}")
    prefix, plural = KIND_ROUTES[kind]
    if kind == "CustomResourceDefinition":
        base = f"{prefix}/{plural}"
    else:
        base = f"{prefix}/namespaces/{namespace}/{plural}"
    return f"{base}/{name}" if name else base


def subset_equal(desired: Any, live: Any) -> bool:
    """True when ``desired`` is structurally contained in ``live``: every
    key/value the render produced matches, while server-populated fields
    (status, resourceVersion, defaulted specs) are ignored. The reference
    normalizes both sides and compares JSON (jsonEquals,
    seldondeployment_controller.go:842); subset containment gives the same
    idempotency without having to model every admission default."""
    if isinstance(desired, dict):
        if not isinstance(live, dict):
            return False
        return all(k in live and subset_equal(v, live[k]) for k, v in desired.items())
    if isinstance(desired, list):
        if not isinstance(live, list) or len(desired) != len(live):
            return False
        return all(subset_equal(d, l) for d, l in zip(desired, live))
    if isinstance(desired, (int, float)) and isinstance(live, (int, float)):
        return float(desired) == float(live)
    return desired == live


class KubeController:
    """Converge a cluster onto its SeldonDeployment CRs.

    ``reconcile_all`` is one level-triggered pass: list CRs, render+apply
    each, prune owned objects whose CR is gone. ``run`` loops it with a
    resync period. Every write path is recorded by the injectable
    ``KubeApi``, so tests assert convergence (second pass => zero writes)
    and drift repair exactly like the reference's envtest suite asserts
    reconcile results."""

    def __init__(self, api: KubeApi, namespace: Optional[str] = None,
                 resync_s: float = 30.0):
        self.api = api
        self.namespace = namespace  # None = all namespaces the api can list
        self.resync_s = resync_s
        self._stop = threading.Event()
        self._kick = threading.Event()  # watch events accelerate the loop
        # namespaces this controller has ever reconciled into: pruning after
        # the LAST CR in a namespace is deleted needs somewhere to look.
        # Survives for the controller's lifetime; across restarts a real
        # cluster's ownerReference GC covers the same case.
        self._known_namespaces: set = set()

    # -- setup --------------------------------------------------------------

    def install_crd(self) -> bool:
        """Create the SeldonDeployment CRD if missing; True if created."""
        path = object_path("CustomResourceDefinition", None,
                           CRD_MANIFEST["metadata"]["name"])
        if self.api.get(path) is not None:
            return False
        self.api.create(object_path("CustomResourceDefinition", None), CRD_MANIFEST)
        logger.info("installed CRD %s", CRD_MANIFEST["metadata"]["name"])
        return True

    def wait_crd_established(self, timeout_s: float = 10.0) -> bool:
        """Block until the CRD serves list requests (Established).

        Right after install_crd() a real apiserver needs a beat before the
        seldondeployments endpoint exists; a one-shot pass that lists
        immediately would crash on KubeApiError (the daemon loop tolerates
        this via catch-and-resync). Polls the list endpoint itself — the
        exact capability the next step needs — rather than parsing
        status.conditions, so it also works against minimal fake servers.
        """
        deadline = time.time() + timeout_s
        while True:
            try:
                self._list_crs()
                return True
            except KubeApiError:
                if time.time() >= deadline:
                    return False
                time.sleep(0.2)

    # -- one reconcile pass --------------------------------------------------

    def _list_crs(self) -> List[Dict[str, Any]]:
        if self.namespace:
            return self.api.list(object_path("SeldonDeployment", self.namespace))
        prefix, plural = KIND_ROUTES["SeldonDeployment"]
        return self.api.list(f"{prefix}/{plural}")

    def reconcile_all(self) -> Dict[str, int]:
        """One pass over every CR. Returns op counts for observability."""
        ops = {"created": 0, "replaced": 0, "deleted": 0, "unchanged": 0,
               "failed": 0}
        crs = self._list_crs()
        live_ids = set()
        for cr in crs:
            ns = cr.get("metadata", {}).get("namespace", "default")
            name = cr.get("metadata", {}).get("name", "?")
            live_ids.add((ns, name))
            self._known_namespaces.add(ns)
            try:
                self.reconcile_cr(cr, ops)
            except Exception as e:  # noqa: BLE001 - one bad CR must not
                # block the rest (reference: Reconcile returns the error and
                # requeues only that object)
                ops["failed"] += 1
                logger.warning("reconcile %s/%s failed: %s", ns, name, e)
                self._set_status(cr, "Failed", str(e))
        self._prune_orphans(live_ids, ops)
        return ops

    def reconcile_cr(self, cr: Dict[str, Any], ops: Optional[Dict[str, int]] = None
                     ) -> Dict[str, int]:
        """Render the CR and converge its owned objects."""
        ops = ops if ops is not None else {
            "created": 0, "replaced": 0, "deleted": 0, "unchanged": 0}
        dep = SeldonDeployment.from_dict(cr)
        # admission parity: same webhook-equivalent validation the
        # self-hosted reconciler runs (reference: ValidateCreate,
        # seldondeployment_webhook.go:388-411)
        from ..graph.spec import validate_deployment

        validate_deployment(dep.predictors)
        manifests = render(dep)
        validate_manifests(manifests)
        owner = self._owner_ref(cr)
        desired_keys = set()
        apply_errors: List[str] = []
        for m in manifests:
            if owner:
                m.setdefault("metadata", {})["ownerReferences"] = [owner]
            kind = m["kind"]
            ns = m["metadata"].get("namespace", "default")
            name = m["metadata"]["name"]
            desired_keys.add((kind, ns, name))
            try:
                self._apply_object(m, ops)
            except KubeApiError as e:
                # one rejected object must not block its siblings — record,
                # keep converging, surface in status, retry next resync
                apply_errors.append(f"{kind}/{name}: {e}")
                logger.warning("apply %s/%s %s/%s failed: %s",
                               kind, ns, name, dep.name, e)
        # prune: owned objects of this CR that the render no longer emits
        # (e.g. a predictor was removed -> its Deployment/Service must go)
        for kind in KIND_ROUTES:
            if kind in ("SeldonDeployment", "CustomResourceDefinition"):
                continue
            ns = cr.get("metadata", {}).get("namespace", "default")
            sel = f"seldon-deployment-id={dep.name},app.kubernetes.io/managed-by={MANAGED_BY}"
            try:
                existing = self.api.list(object_path(kind, ns), sel)
            except KubeApiError:
                continue  # API group absent (no istio) — nothing to prune
            for obj in existing:
                key = (kind, ns, obj["metadata"]["name"])
                if key not in desired_keys:
                    self.api.delete(object_path(kind, ns, obj["metadata"]["name"]))
                    ops["deleted"] += 1
        if apply_errors:
            # surfaced in ops too: --once CI mode exits nonzero on ANY
            # unconverged object, not just CR-level validation failures
            ops["failed"] = ops.get("failed", 0) + len(apply_errors)
            self._set_status(
                cr, "Creating",
                f"{len(apply_errors)} of {len(manifests)} objects failed: "
                + "; ".join(apply_errors[:3]),
            )
        else:
            self._set_status(cr, "Available", f"{len(manifests)} objects converged")
        return ops

    @staticmethod
    def _merge_for_put(desired: Any, live: Any) -> Any:
        """Desired state layered over the live object for a PUT: every
        rendered key wins, server-populated keys the render doesn't mention
        survive. A bare PUT of the rendered manifest would drop immutable
        server-set fields (Service spec.clusterIP, metadata.uid) and the
        apiserver would reject it with 422 — wedging drift repair."""
        if isinstance(desired, dict) and isinstance(live, dict):
            out = dict(live)
            for k, v in desired.items():
                out[k] = KubeController._merge_for_put(v, live.get(k))
            return out
        return desired

    def _apply_object(self, m: Dict[str, Any], ops: Dict[str, int]) -> None:
        kind = m["kind"]
        ns = m["metadata"].get("namespace", "default")
        name = m["metadata"]["name"]
        path = object_path(kind, ns, name)
        live = self.api.get(path)
        if live is None:
            self.api.create(object_path(kind, ns), m)
            ops["created"] += 1
            return
        if subset_equal(m, live):
            ops["unchanged"] += 1
            return
        self.api.replace(path, self._merge_for_put(m, live))
        ops["replaced"] += 1

    def _prune_orphans(self, live_ids: set, ops: Dict[str, int]) -> None:
        """Delete managed objects whose owning CR no longer exists — covers
        CR deletion on clusters without (or before) ownerRef GC. Looks in
        every namespace this controller has ever reconciled into, so the
        LAST CR of a namespace leaving still triggers cleanup there."""
        namespaces = {ns for ns, _ in live_ids} | set(self._known_namespaces)
        if self.namespace:
            namespaces.add(self.namespace)
        for kind in KIND_ROUTES:
            if kind in ("SeldonDeployment", "CustomResourceDefinition"):
                continue
            for ns in namespaces or {"default"}:
                try:
                    objs = self.api.list(
                        object_path(kind, ns),
                        f"app.kubernetes.io/managed-by={MANAGED_BY}",
                    )
                except KubeApiError:
                    continue
                for obj in objs:
                    dep_id = obj["metadata"].get("labels", {}).get(
                        "seldon-deployment-id"
                    )
                    if dep_id and (ns, dep_id) not in live_ids:
                        self.api.delete(
                            object_path(kind, ns, obj["metadata"]["name"])
                        )
                        ops["deleted"] += 1

    def _owner_ref(self, cr: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        meta = cr.get("metadata", {})
        if not meta.get("uid"):
            return None
        return {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": "SeldonDeployment",
            "name": meta.get("name"),
            "uid": meta["uid"],
            "controller": True,
            "blockOwnerDeletion": True,
        }

    def _set_status(self, cr: Dict[str, Any], state: str, description: str) -> None:
        """Status rollup on the CR's /status subresource (reference:
        seldondeployment_controller.go:1111-1119)."""
        ns = cr.get("metadata", {}).get("namespace", "default")
        name = cr.get("metadata", {}).get("name")
        if not name:
            return
        body = dict(cr)
        body["status"] = {"state": state, "description": description}
        try:
            self.api.replace(
                object_path("SeldonDeployment", ns, name) + "/status", body
            )
        except KubeApiError as e:
            logger.debug("status update for %s/%s skipped: %s", ns, name, e)

    # -- loop ---------------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()  # wake the run loop immediately

    def run(self, iterations: Optional[int] = None) -> None:
        """Level-triggered control loop: reconcile everything, wait for a
        CR watch event OR the resync period, repeat. Watch events only
        shorten the wait — every pass is a full level-triggered reconcile,
        so a dropped event costs at most resync_s of staleness, never
        correctness (the reference gets the same property from
        controller-runtime's informers + periodic resync)."""
        self.install_crd()
        kick = self._kick
        watcher: Optional[threading.Thread] = None
        if hasattr(self.api, "watch"):
            def watch_loop() -> None:
                if self.namespace:
                    path = object_path("SeldonDeployment", self.namespace)
                else:
                    prefix, plural = KIND_ROUTES["SeldonDeployment"]
                    path = f"{prefix}/{plural}"
                failures = 0
                while not self._stop.is_set():
                    try:
                        for _event in self.api.watch(path):
                            kick.set()
                            failures = 0
                            if self._stop.is_set():
                                return
                        failures = 0  # clean window close
                    except Exception as e:  # noqa: BLE001 - watch is an
                        # accelerator; resync covers a broken stream. But a
                        # PERSISTENT failure (RBAC missing the watch verb)
                        # silently degrades reactivity — say so, once.
                        failures += 1
                        log = logger.warning if failures in (1, 10) else logger.debug
                        log("watch stream failed (x%d, falling back to %ss "
                            "resync): %s", failures, self.resync_s, e)
                        self._stop.wait(min(1.0 * failures, 30.0))

            watcher = threading.Thread(
                target=watch_loop, daemon=True, name="sdep-watch"
            )
            watcher.start()
        n = 0
        while not self._stop.is_set():
            # clear BEFORE reconciling: an event landing mid-pass must wake
            # the next wait instead of being erased after the pass
            kick.clear()
            try:
                ops = self.reconcile_all()
                if any(ops[k] for k in ("created", "replaced", "deleted")):
                    logger.info("reconcile pass: %s", ops)
            except Exception as e:  # noqa: BLE001 - the loop must survive
                logger.warning("reconcile pass failed: %s", e)
            n += 1
            if iterations is not None and n >= iterations:
                return
            # woken early by a watch event or stop(); else the resync period
            kick.wait(self.resync_s)
