"""Live-Kubernetes operator mode: watch SeldonDeployment CRs, apply the
rendered manifests, correct drift.

Counterpart of the reference's kubebuilder controller (reference:
operator/controllers/seldondeployment_controller.go:1067-1122 Reconcile;
owner-indexed watches ``SetupWithManager`` :1129-1199; JSON-equality diff
``jsonEquals`` :842) — the piece that turns ``k8s.py``'s render-only output
into a *controller*: a level-triggered loop that converges a live cluster
onto the CR's desired state and re-converges when someone mutates an owned
object out from under it.

Design differences from the reference, on purpose:

* **No client-go / controller-runtime** — a minimal typed client over the
  Kubernetes REST API (``HttpKubeApi``) with an injectable fake for tests
  (mirroring envtest's role, reference: operator/controllers/suite_test.go:
  17-30). The controller logic is transport-agnostic.
* **Level-triggered resync instead of edge-triggered caches**: every
  ``resync_s`` (and on every watch event) each CR is re-reconciled from
  scratch.  Apply is idempotent — create if absent, replace only when the
  desired spec is not a subset of the live object (``subset_equal``), so a
  converged cluster sees zero writes per cycle.
* **Label-based ownership** (``seldon-deployment-id`` +
  ``app.kubernetes.io/managed-by``) for pruning, *plus* ownerReferences on
  every object so a real cluster's GC also works.

The webhook-defaulting/validation step the reference runs server-side
(seldondeployment_webhook.go) happens inside ``k8s.render`` here.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .k8s import render, validate_manifests
from .resource import SeldonDeployment

logger = logging.getLogger(__name__)

GROUP = "machinelearning.seldon.io"
VERSION = "v1alpha2"
PLURAL = "seldondeployments"
MANAGED_BY = "seldon-core-tpu"

# kind -> (api prefix, plural). Everything k8s.render can emit.
KIND_ROUTES: Dict[str, Tuple[str, str]] = {
    "Deployment": ("apis/apps/v1", "deployments"),
    "StatefulSet": ("apis/apps/v1", "statefulsets"),
    "Service": ("api/v1", "services"),
    "ConfigMap": ("api/v1", "configmaps"),
    "HorizontalPodAutoscaler": ("apis/autoscaling/v2", "horizontalpodautoscalers"),
    "VirtualService": ("apis/networking.istio.io/v1beta1", "virtualservices"),
    "DestinationRule": ("apis/networking.istio.io/v1beta1", "destinationrules"),
    "SeldonDeployment": (f"apis/{GROUP}/{VERSION}", PLURAL),
    "CustomResourceDefinition": (
        "apis/apiextensions.k8s.io/v1", "customresourcedefinitions"
    ),
    "Lease": ("apis/coordination.k8s.io/v1", "leases"),
}

# Prepackaged server implementations that require a modelUri (reference:
# checkPredictiveUnits, seldondeployment_webhook.go:356-363, extended with
# this repo's TRT/SageMaker servers)
PREPACKAGED_IMPLEMENTATIONS = (
    "SKLEARN_SERVER", "XGBOOST_SERVER", "TENSORFLOW_SERVER",
    "MLFLOW_SERVER", "TRT_SERVER", "SAGEMAKER_SERVER", "JAX_SERVER",
)

# CEL admission rules (x-kubernetes-validations): the apiserver rejects an
# invalid CR BEFORE it reaches etcd — the modern, webhook-server-free
# equivalent of the reference's ValidateCreate/ValidateUpdate
# (seldondeployment_webhook.go:388-411). Each rule has a Python twin in
# _CEL_TWINS below (used by validate_cr for fake-apiserver tests and
# defense-in-depth); a test pins the two lists in sync.
CEL_RULES = [
    {
        "rule": "self.predictors.all(p, self.predictors.exists_one("
                "q, q.name == p.name))",
        "message": "Duplicate predictor name",
    },
    {
        "rule": "size(self.predictors) <= 1 || "
                "self.predictors.map(p, has(p.traffic) ? p.traffic : 0)"
                ".sum() == 100",
        "message": "Traffic must sum to 100 for multiple predictors",
    },
    {
        "rule": "size(self.predictors) != 1 || "
                "!has(self.predictors[0].traffic) || "
                "self.predictors[0].traffic in [0, 100]",
        "message": "Traffic must be 100 for a single predictor when set",
    },
    {
        "rule": "self.predictors.all(p, "
                "!(has(p.graph.implementation) && p.graph.implementation in "
                + json.dumps(list(PREPACKAGED_IMPLEMENTATIONS))
                + ") || has(p.graph.modelUri))",
        "message": "Predictive unit modelUri required when using "
                   "standalone servers",
    },
]


def _graph_schema(depth: int) -> Dict[str, Any]:
    """Structural schema for a PredictiveUnit, nested to ``depth`` levels
    (structural schemas cannot recurse; below the bounded depth children
    stay open and are caught by the reconcile-time validator)."""
    unit: Dict[str, Any] = {
        "type": "object",
        "required": ["name"],
        "properties": {
            "name": {"type": "string", "minLength": 1},
            "type": {
                "type": "string",
                "enum": [
                    "MODEL", "ROUTER", "COMBINER", "TRANSFORMER",
                    "OUTPUT_TRANSFORMER",
                ],
            },
            "implementation": {"type": "string"},
            "modelUri": {"type": "string"},
            "endpoint": {
                "type": "object",
                "x-kubernetes-preserve-unknown-fields": True,
            },
            "parameters": {
                "type": "array",
                "items": {
                    "type": "object",
                    "x-kubernetes-preserve-unknown-fields": True,
                },
            },
        },
        "x-kubernetes-preserve-unknown-fields": True,
    }
    if depth > 0:
        unit["properties"]["children"] = {
            "type": "array", "maxItems": 16,
            "items": _graph_schema(depth - 1),
        }
    return unit


CRD_OPENAPI_SCHEMA: Dict[str, Any] = {
    "type": "object",
    "properties": {
        "spec": {
            "type": "object",
            "required": ["predictors"],
            "x-kubernetes-validations": CEL_RULES,
            "properties": {
                "name": {"type": "string"},
                "predictors": {
                    "type": "array",
                    "minItems": 1,
                    # bounded so the apiserver's CEL cost estimator accepts
                    # the quadratic uniqueness rule (unbounded arrays fail
                    # CRD admission on k8s >= 1.25 with "rule cost exceeds
                    # budget")
                    "maxItems": 32,
                    "items": {
                        "type": "object",
                        "required": ["name", "graph"],
                        "properties": {
                            "name": {"type": "string", "minLength": 1},
                            "replicas": {"type": "integer", "minimum": 0},
                            "traffic": {
                                "type": "integer",
                                "minimum": 0,
                                "maximum": 100,
                            },
                            "graph": _graph_schema(4),
                        },
                        "x-kubernetes-preserve-unknown-fields": True,
                    },
                },
            },
        },
        "status": {
            "type": "object",
            "x-kubernetes-preserve-unknown-fields": True,
        },
    },
}

CRD_MANIFEST: Dict[str, Any] = {
    "apiVersion": "apiextensions.k8s.io/v1",
    "kind": "CustomResourceDefinition",
    "metadata": {"name": f"{PLURAL}.{GROUP}"},
    "spec": {
        "group": GROUP,
        "names": {
            "kind": "SeldonDeployment",
            "listKind": "SeldonDeploymentList",
            "plural": PLURAL,
            "singular": "seldondeployment",
            "shortNames": ["sdep"],
        },
        "scope": "Namespaced",
        "versions": [
            {
                "name": VERSION,
                "served": True,
                "storage": True,
                "subresources": {"status": {}},
                "schema": {"openAPIV3Schema": CRD_OPENAPI_SCHEMA},
            }
        ],
    },
}


def _schema_check(schema: Dict[str, Any], obj: Any, path: str,
                  errs: List[str]) -> None:
    """Evaluate the structural subset CRD_OPENAPI_SCHEMA uses (type,
    required, enum, minItems, minLength, minimum/maximum) — the same
    checks a real apiserver applies from the manifest. Unknown fields
    pass (x-kubernetes-preserve-unknown-fields)."""
    t = schema.get("type")
    if t == "object":
        if not isinstance(obj, dict):
            errs.append(f"{path}: expected object")
            return
        for req in schema.get("required", ()):
            if req not in obj:
                errs.append(f"{path}.{req}: required")
        for key, sub in schema.get("properties", {}).items():
            if key in obj:
                _schema_check(sub, obj[key], f"{path}.{key}", errs)
    elif t == "array":
        if not isinstance(obj, list):
            errs.append(f"{path}: expected array")
            return
        if len(obj) < schema.get("minItems", 0):
            errs.append(f"{path}: fewer than {schema['minItems']} items")
        items = schema.get("items")
        if items:
            for i, item in enumerate(obj):
                _schema_check(items, item, f"{path}[{i}]", errs)
    elif t == "string":
        if not isinstance(obj, str):
            errs.append(f"{path}: expected string")
            return
        if len(obj) < schema.get("minLength", 0):
            errs.append(f"{path}: shorter than minLength")
        if "enum" in schema and obj not in schema["enum"]:
            errs.append(f"{path}: {obj!r} not one of {schema['enum']}")
    elif t == "integer":
        if not isinstance(obj, int) or isinstance(obj, bool):
            errs.append(f"{path}: expected integer")
            return
        if "minimum" in schema and obj < schema["minimum"]:
            errs.append(f"{path}: below minimum {schema['minimum']}")
        if "maximum" in schema and obj > schema["maximum"]:
            errs.append(f"{path}: above maximum {schema['maximum']}")


def _twin_unique_names(spec: Dict[str, Any]) -> bool:
    names = [p.get("name") for p in spec.get("predictors", [])]
    return len(names) == len(set(names))


def _twin_traffic_sum(spec: Dict[str, Any]) -> bool:
    preds = spec.get("predictors", [])
    if len(preds) <= 1:
        return True
    return sum(int(p.get("traffic", 0)) for p in preds) == 100


def _twin_traffic_single(spec: Dict[str, Any]) -> bool:
    preds = spec.get("predictors", [])
    if len(preds) != 1 or "traffic" not in preds[0]:
        return True
    return int(preds[0]["traffic"]) in (0, 100)


def _twin_model_uri(spec: Dict[str, Any]) -> bool:
    for p in spec.get("predictors", []):
        g = p.get("graph", {})
        if (
            g.get("implementation") in PREPACKAGED_IMPLEMENTATIONS
            and not g.get("modelUri")
        ):
            return False
    return True


# index-aligned with CEL_RULES — test_kube_admission pins the pairing
_CEL_TWINS = [
    _twin_unique_names, _twin_traffic_sum, _twin_traffic_single,
    _twin_model_uri,
]


def validate_cr(obj: Dict[str, Any]) -> None:
    """Admission-time validation of a SeldonDeployment CR: the structural
    schema plus every CEL rule's Python twin, exactly what a real
    apiserver enforces from CRD_MANIFEST before the object reaches etcd.
    Raises KubeApiError(422) — the apiserver's Invalid status — on the
    first batch of violations. Fake-apiserver tests install this on
    create/replace; on a live cluster the CRD schema itself enforces it
    (no webhook server needed)."""
    errs: List[str] = []
    _schema_check(CRD_OPENAPI_SCHEMA, obj, "", errs)
    spec = obj.get("spec")
    if isinstance(spec, dict) and isinstance(spec.get("predictors"), list):
        for rule, twin in zip(CEL_RULES, _CEL_TWINS):
            try:
                ok = twin(spec)
            except Exception:  # noqa: BLE001 - malformed spec: structural
                ok = False     # errors above already describe it
            if not ok:
                errs.append(f"spec: {rule['message']}")
    if errs:
        raise KubeApiError(
            422, "SeldonDeployment is invalid: " + "; ".join(errs[:8])
        )


class KubeApiError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"kube api {status}: {message}")
        self.status = status


class KubeApi:
    """Minimal REST surface the controller needs. Paths are full resource
    paths like ``apis/apps/v1/namespaces/default/deployments[/name]``."""

    def get(self, path: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def list(self, path: str, label_selector: str = "") -> List[Dict[str, Any]]:
        raise NotImplementedError

    def create(self, path: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def replace(self, path: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def delete(self, path: str) -> bool:
        raise NotImplementedError


class HttpKubeApi(KubeApi):
    """Talk to a real kube-apiserver. In-cluster by default (service-account
    token + CA from the pod filesystem, KUBERNETES_SERVICE_HOST env), or an
    explicit ``server``/``token`` pair (e.g. `kubectl proxy` => server=
    "http://127.0.0.1:8001", token=None)."""

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, server: Optional[str] = None, token: Optional[str] = None,
                 ca_file: Optional[str] = None, timeout: float = 10.0):
        import os

        if server is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            if not host:
                raise RuntimeError(
                    "not in-cluster (no KUBERNETES_SERVICE_HOST) and no "
                    "--kube-server given; try `kubectl proxy` + "
                    "--kube-server http://127.0.0.1:8001"
                )
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            server = f"https://{host}:{port}"
            if token is None and os.path.exists(f"{self.SA_DIR}/token"):
                with open(f"{self.SA_DIR}/token") as f:
                    token = f.read().strip()
            if ca_file is None and os.path.exists(f"{self.SA_DIR}/ca.crt"):
                ca_file = f"{self.SA_DIR}/ca.crt"
        self.server = server.rstrip("/")
        self.token = token
        self._ctx = None
        if self.server.startswith("https"):
            import ssl

            self._ctx = (
                ssl.create_default_context(cafile=ca_file)
                if ca_file else ssl.create_default_context()
            )
        self.timeout = timeout

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 query: str = "") -> Tuple[int, Any]:
        import urllib.error
        import urllib.request

        url = f"{self.server}/{path}{('?' + query) if query else ''}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout, context=self._ctx
            ) as r:
                return r.status, json.loads(r.read() or b"null")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except Exception:  # noqa: BLE001
                payload = {}
            return e.code, payload

    def get(self, path: str) -> Optional[Dict[str, Any]]:
        status, body = self._request("GET", path)
        if status == 404:
            return None
        if status >= 400:
            raise KubeApiError(status, str(body.get("message", body)))
        return body

    def list(self, path: str, label_selector: str = "") -> List[Dict[str, Any]]:
        import urllib.parse

        q = f"labelSelector={urllib.parse.quote(label_selector)}" if label_selector else ""
        status, body = self._request("GET", path, query=q)
        if status >= 400:
            raise KubeApiError(status, str(body.get("message", body)))
        return body.get("items", [])

    def create(self, path: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        status, body = self._request("POST", path, obj)
        if status >= 400:
            raise KubeApiError(status, str(body.get("message", body)))
        return body

    def replace(self, path: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        status, body = self._request("PUT", path, obj)
        if status >= 400:
            raise KubeApiError(status, str(body.get("message", body)))
        return body

    def delete(self, path: str) -> bool:
        status, body = self._request("DELETE", path)
        if status == 404:
            return False
        if status >= 400:
            raise KubeApiError(status, str(body.get("message", body)))
        return True

    def watch(self, path: str, timeout_s: float = 300.0):
        """Kubernetes watch stream: yields event dicts ({type, object})
        until the server closes the window. The controller treats every
        event as 'reconcile now' — level-triggered logic stays the source
        of truth, the watch only shortens reaction time."""
        import urllib.request

        url = f"{self.server}/{path}?watch=1&timeoutSeconds={int(timeout_s)}"
        req = urllib.request.Request(url)
        req.add_header("Accept", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        with urllib.request.urlopen(
            req, timeout=timeout_s + 10, context=self._ctx
        ) as r:
            for line in r:
                if not line.strip():
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    continue


def object_path(kind: str, namespace: Optional[str], name: Optional[str] = None) -> str:
    """REST path for a (kind, namespace, name). Cluster-scoped kinds (the
    CRD itself) ignore namespace."""
    if kind not in KIND_ROUTES:
        raise ValueError(f"no API route for kind {kind!r}")
    prefix, plural = KIND_ROUTES[kind]
    if kind == "CustomResourceDefinition":
        base = f"{prefix}/{plural}"
    else:
        base = f"{prefix}/namespaces/{namespace}/{plural}"
    return f"{base}/{name}" if name else base


def subset_equal(desired: Any, live: Any) -> bool:
    """True when ``desired`` is structurally contained in ``live``: every
    key/value the render produced matches, while server-populated fields
    (status, resourceVersion, defaulted specs) are ignored. The reference
    normalizes both sides and compares JSON (jsonEquals,
    seldondeployment_controller.go:842); subset containment gives the same
    idempotency without having to model every admission default."""
    if isinstance(desired, dict):
        if not isinstance(live, dict):
            return False
        return all(k in live and subset_equal(v, live[k]) for k, v in desired.items())
    if isinstance(desired, list):
        if not isinstance(live, list) or len(desired) != len(live):
            return False
        return all(subset_equal(d, l) for d, l in zip(desired, live))
    if isinstance(desired, (int, float)) and isinstance(live, (int, float)):
        return float(desired) == float(live)
    return desired == live


class LeaderElector:
    """coordination.k8s.io/v1 Lease leader election: acquire if absent,
    renew while held, steal when the holder's lease lapses.

    Two controller replicas would otherwise double-reconcile and fight
    over status writes (reference: the manager's EnableLeaderElection,
    operator/main.go:49-93). ``clock`` is injectable so tests drive
    expiry without sleeping.
    """

    def __init__(self, api: KubeApi, namespace: str = "default",
                 name: str = "seldon-tpu-controller",
                 identity: Optional[str] = None,
                 lease_duration_s: float = 15.0,
                 clock=time.time):
        import os
        import socket

        self.api = api
        self.namespace = namespace
        self.name = name
        self.identity = identity or f"{socket.gethostname()}-{os.getpid()}"
        self.lease_duration_s = float(lease_duration_s)
        self.clock = clock
        self.is_leader = False

    def _path(self, with_name: bool) -> str:
        prefix, plural = KIND_ROUTES["Lease"]
        base = f"{prefix}/namespaces/{self.namespace}/{plural}"
        return f"{base}/{self.name}" if with_name else base

    @staticmethod
    def _fmt(epoch: float) -> str:
        import datetime as dt

        return dt.datetime.fromtimestamp(
            epoch, dt.timezone.utc
        ).strftime("%Y-%m-%dT%H:%M:%S.%fZ")

    @staticmethod
    def _parse(stamp: str) -> float:
        import datetime as dt

        return dt.datetime.strptime(
            stamp, "%Y-%m-%dT%H:%M:%S.%fZ"
        ).replace(tzinfo=dt.timezone.utc).timestamp()

    def _spec(self, now: float, transitions: int) -> Dict[str, Any]:
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_duration_s),
            "renewTime": self._fmt(now),
            "leaseTransitions": transitions,
        }

    def try_acquire(self) -> bool:
        """One election round: returns whether this identity holds the
        lease afterwards. Safe to call every loop pass — holding costs
        one GET + one conditional write."""
        now = self.clock()
        try:
            lease = self.api.get(self._path(True))
            if lease is None:
                self.api.create(self._path(False), {
                    "apiVersion": "coordination.k8s.io/v1",
                    "kind": "Lease",
                    "metadata": {"name": self.name,
                                 "namespace": self.namespace},
                    "spec": self._spec(now, 0),
                })
                self.is_leader = True
                return True
            spec = lease.get("spec", {})
            holder = spec.get("holderIdentity")
            try:
                renewed = self._parse(spec.get("renewTime", ""))
            except (ValueError, TypeError):
                renewed = 0.0  # malformed lease: treat as lapsed
            duration = float(
                spec.get("leaseDurationSeconds", self.lease_duration_s)
            )
            if holder == self.identity:
                lease["spec"] = self._spec(
                    now, int(spec.get("leaseTransitions", 0))
                )
                self.api.replace(self._path(True), lease)
                self.is_leader = True
                return True
            if now - renewed > duration:
                # holder lapsed: steal. resourceVersion rides along, so a
                # racing steal loses with a conflict instead of splitting
                # the brain
                lease["spec"] = self._spec(
                    now, int(spec.get("leaseTransitions", 0)) + 1
                )
                self.api.replace(self._path(True), lease)
                self.is_leader = True
                return True
        except KubeApiError as e:
            logger.debug("leader election round lost: %s", e)
        self.is_leader = False
        return False


class KubeController:
    """Converge a cluster onto its SeldonDeployment CRs.

    ``reconcile_all`` is one level-triggered pass: list CRs, render+apply
    each, prune owned objects whose CR is gone. ``run`` loops it with a
    resync period. Every write path is recorded by the injectable
    ``KubeApi``, so tests assert convergence (second pass => zero writes)
    and drift repair exactly like the reference's envtest suite asserts
    reconcile results."""

    def __init__(self, api: KubeApi, namespace: Optional[str] = None,
                 resync_s: float = 30.0,
                 elector: Optional[LeaderElector] = None):
        self.api = api
        self.namespace = namespace  # None = all namespaces the api can list
        self.resync_s = resync_s
        self.elector = elector  # None = single-replica mode, always leader
        self._stop = threading.Event()
        self._kick = threading.Event()  # watch events accelerate the loop
        # namespaces this controller has ever reconciled into: pruning after
        # the LAST CR in a namespace is deleted needs somewhere to look.
        # Survives for the controller's lifetime; across restarts a real
        # cluster's ownerReference GC covers the same case.
        self._known_namespaces: set = set()

    # -- setup --------------------------------------------------------------

    def install_crd(self) -> bool:
        """Create the SeldonDeployment CRD if missing; True if created."""
        path = object_path("CustomResourceDefinition", None,
                           CRD_MANIFEST["metadata"]["name"])
        if self.api.get(path) is not None:
            return False
        self.api.create(object_path("CustomResourceDefinition", None), CRD_MANIFEST)
        logger.info("installed CRD %s", CRD_MANIFEST["metadata"]["name"])
        return True

    def wait_crd_established(self, timeout_s: float = 10.0) -> bool:
        """Block until the CRD serves list requests (Established).

        Right after install_crd() a real apiserver needs a beat before the
        seldondeployments endpoint exists; a one-shot pass that lists
        immediately would crash on KubeApiError (the daemon loop tolerates
        this via catch-and-resync). Polls the list endpoint itself — the
        exact capability the next step needs — rather than parsing
        status.conditions, so it also works against minimal fake servers.
        """
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                self._list_crs()
                return True
            except KubeApiError:
                if time.monotonic() >= deadline:
                    return False
                time.sleep(0.2)

    # -- one reconcile pass --------------------------------------------------

    def _list_crs(self) -> List[Dict[str, Any]]:
        if self.namespace:
            return self.api.list(object_path("SeldonDeployment", self.namespace))
        prefix, plural = KIND_ROUTES["SeldonDeployment"]
        return self.api.list(f"{prefix}/{plural}")

    def reconcile_all(self) -> Dict[str, int]:
        """One pass over every CR. Returns op counts for observability."""
        ops = {"created": 0, "replaced": 0, "deleted": 0, "unchanged": 0,
               "failed": 0}
        crs = self._list_crs()
        live_ids = set()
        for cr in crs:
            ns = cr.get("metadata", {}).get("namespace", "default")
            name = cr.get("metadata", {}).get("name", "?")
            live_ids.add((ns, name))
            self._known_namespaces.add(ns)
            try:
                self.reconcile_cr(cr, ops)
            except Exception as e:  # noqa: BLE001 - one bad CR must not
                # block the rest (reference: Reconcile returns the error and
                # requeues only that object)
                ops["failed"] += 1
                logger.warning("reconcile %s/%s failed: %s", ns, name, e)
                self._set_status(cr, "Failed", str(e))
        self._prune_orphans(live_ids, ops)
        return ops

    def reconcile_cr(self, cr: Dict[str, Any], ops: Optional[Dict[str, int]] = None
                     ) -> Dict[str, int]:
        """Render the CR and converge its owned objects."""
        ops = ops if ops is not None else {
            "created": 0, "replaced": 0, "deleted": 0, "unchanged": 0}
        dep = SeldonDeployment.from_dict(cr)
        # admission parity: same webhook-equivalent validation the
        # self-hosted reconciler runs (reference: ValidateCreate,
        # seldondeployment_webhook.go:388-411)
        from ..graph.spec import validate_deployment

        validate_deployment(dep.predictors)
        manifests = render(dep)
        validate_manifests(manifests)
        owner = self._owner_ref(cr)
        desired_keys = set()
        apply_errors: List[str] = []
        for m in manifests:
            if owner:
                m.setdefault("metadata", {})["ownerReferences"] = [owner]
            kind = m["kind"]
            ns = m["metadata"].get("namespace", "default")
            name = m["metadata"]["name"]
            desired_keys.add((kind, ns, name))
            try:
                self._apply_object(m, ops)
            except KubeApiError as e:
                # one rejected object must not block its siblings — record,
                # keep converging, surface in status, retry next resync
                apply_errors.append(f"{kind}/{name}: {e}")
                logger.warning("apply %s/%s %s/%s failed: %s",
                               kind, ns, name, dep.name, e)
        # prune: owned objects of this CR that the render no longer emits
        # (e.g. a predictor was removed -> its Deployment/Service must go)
        for kind in KIND_ROUTES:
            if kind in ("SeldonDeployment", "CustomResourceDefinition",
                        "Lease"):
                continue
            ns = cr.get("metadata", {}).get("namespace", "default")
            sel = f"seldon-deployment-id={dep.name},app.kubernetes.io/managed-by={MANAGED_BY}"
            try:
                existing = self.api.list(object_path(kind, ns), sel)
            except KubeApiError:
                continue  # API group absent (no istio) — nothing to prune
            for obj in existing:
                key = (kind, ns, obj["metadata"]["name"])
                if key not in desired_keys:
                    self.api.delete(object_path(kind, ns, obj["metadata"]["name"]))
                    ops["deleted"] += 1
        if apply_errors:
            # surfaced in ops too: --once CI mode exits nonzero on ANY
            # unconverged object, not just CR-level validation failures
            ops["failed"] = ops.get("failed", 0) + len(apply_errors)
            self._set_status(
                cr, "Creating",
                f"{len(apply_errors)} of {len(manifests)} objects failed: "
                + "; ".join(apply_errors[:3]),
            )
        else:
            self._set_status(cr, "Available", f"{len(manifests)} objects converged")
        return ops

    @staticmethod
    def _merge_for_put(desired: Any, live: Any) -> Any:
        """Desired state layered over the live object for a PUT: every
        rendered key wins, server-populated keys the render doesn't mention
        survive. A bare PUT of the rendered manifest would drop immutable
        server-set fields (Service spec.clusterIP, metadata.uid) and the
        apiserver would reject it with 422 — wedging drift repair."""
        if isinstance(desired, dict) and isinstance(live, dict):
            out = dict(live)
            for k, v in desired.items():
                out[k] = KubeController._merge_for_put(v, live.get(k))
            return out
        return desired

    def _apply_object(self, m: Dict[str, Any], ops: Dict[str, int]) -> None:
        kind = m["kind"]
        ns = m["metadata"].get("namespace", "default")
        name = m["metadata"]["name"]
        path = object_path(kind, ns, name)
        live = self.api.get(path)
        if live is None:
            self.api.create(object_path(kind, ns), m)
            ops["created"] += 1
            return
        if subset_equal(m, live):
            ops["unchanged"] += 1
            return
        self.api.replace(path, self._merge_for_put(m, live))
        ops["replaced"] += 1

    def _prune_orphans(self, live_ids: set, ops: Dict[str, int]) -> None:
        """Delete managed objects whose owning CR no longer exists — covers
        CR deletion on clusters without (or before) ownerRef GC. Looks in
        every namespace this controller has ever reconciled into, so the
        LAST CR of a namespace leaving still triggers cleanup there."""
        namespaces = {ns for ns, _ in live_ids} | set(self._known_namespaces)
        if self.namespace:
            namespaces.add(self.namespace)
        for kind in KIND_ROUTES:
            if kind in ("SeldonDeployment", "CustomResourceDefinition",
                        "Lease"):
                continue
            for ns in namespaces or {"default"}:
                try:
                    objs = self.api.list(
                        object_path(kind, ns),
                        f"app.kubernetes.io/managed-by={MANAGED_BY}",
                    )
                except KubeApiError:
                    continue
                for obj in objs:
                    dep_id = obj["metadata"].get("labels", {}).get(
                        "seldon-deployment-id"
                    )
                    if dep_id and (ns, dep_id) not in live_ids:
                        self.api.delete(
                            object_path(kind, ns, obj["metadata"]["name"])
                        )
                        ops["deleted"] += 1

    def _owner_ref(self, cr: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        meta = cr.get("metadata", {})
        if not meta.get("uid"):
            return None
        return {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": "SeldonDeployment",
            "name": meta.get("name"),
            "uid": meta["uid"],
            "controller": True,
            "blockOwnerDeletion": True,
        }

    def _set_status(self, cr: Dict[str, Any], state: str, description: str) -> None:
        """Status rollup on the CR's /status subresource (reference:
        seldondeployment_controller.go:1111-1119)."""
        ns = cr.get("metadata", {}).get("namespace", "default")
        name = cr.get("metadata", {}).get("name")
        if not name:
            return
        body = dict(cr)
        body["status"] = {"state": state, "description": description}
        try:
            self.api.replace(
                object_path("SeldonDeployment", ns, name) + "/status", body
            )
        except KubeApiError as e:
            logger.debug("status update for %s/%s skipped: %s", ns, name, e)

    # -- loop ---------------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()  # wake the run loop immediately

    def run(self, iterations: Optional[int] = None) -> None:
        """Level-triggered control loop: reconcile everything, wait for a
        CR watch event OR the resync period, repeat. Watch events only
        shorten the wait — every pass is a full level-triggered reconcile,
        so a dropped event costs at most resync_s of staleness, never
        correctness (the reference gets the same property from
        controller-runtime's informers + periodic resync)."""
        self.install_crd()
        kick = self._kick
        watcher: Optional[threading.Thread] = None
        if hasattr(self.api, "watch"):
            def watch_loop() -> None:
                if self.namespace:
                    path = object_path("SeldonDeployment", self.namespace)
                else:
                    prefix, plural = KIND_ROUTES["SeldonDeployment"]
                    path = f"{prefix}/{plural}"
                failures = 0
                while not self._stop.is_set():
                    try:
                        for _event in self.api.watch(path):
                            kick.set()
                            failures = 0
                            if self._stop.is_set():
                                return
                        failures = 0  # clean window close
                    except Exception as e:  # noqa: BLE001 - watch is an
                        # accelerator; resync covers a broken stream. But a
                        # PERSISTENT failure (RBAC missing the watch verb)
                        # silently degrades reactivity — say so, once.
                        failures += 1
                        log = logger.warning if failures in (1, 10) else logger.debug
                        log("watch stream failed (x%d, falling back to %ss "
                            "resync): %s", failures, self.resync_s, e)
                        self._stop.wait(min(1.0 * failures, 30.0))

            watcher = threading.Thread(
                target=watch_loop, daemon=True, name="sdep-watch"
            )
            watcher.start()
        # leader election: ONE synchronous round up front (so a one-shot
        # `run(iterations=1)` reflects current leadership), then a
        # dedicated renewer thread — a reconcile pass longer than the
        # lease duration must never let the lease lapse mid-pass (client-go
        # renews on its own goroutine for the same reason)
        renew_stop = threading.Event()
        renewer: Optional[threading.Thread] = None
        if self.elector is not None:
            self.elector.try_acquire()

            def renew_loop() -> None:
                period = self.elector.lease_duration_s / 3.0
                while not renew_stop.is_set() and not self._stop.is_set():
                    renew_stop.wait(period)
                    if renew_stop.is_set() or self._stop.is_set():
                        return
                    try:
                        self.elector.try_acquire()
                    except Exception as e:  # noqa: BLE001 - election must
                        logger.warning("lease renew failed: %s", e)  # retry

            renewer = threading.Thread(
                target=renew_loop, daemon=True, name="lease-renew"
            )
            renewer.start()
        n = 0
        try:
            while not self._stop.is_set():
                # clear BEFORE reconciling: an event landing mid-pass must
                # wake the next wait instead of being erased after the pass
                kick.clear()
                if self.elector is not None and not self.elector.is_leader:
                    # follower: never writes; the renewer keeps polling the
                    # lease so takeover happens within ~one duration of the
                    # leader lapsing (reference: operator/main.go:49-93)
                    n += 1
                    if iterations is not None and n >= iterations:
                        return
                    self._stop.wait(
                        min(self.elector.lease_duration_s / 3.0, self.resync_s)
                    )
                    continue
                try:
                    ops = self.reconcile_all()
                    if any(ops[k] for k in ("created", "replaced", "deleted")):
                        logger.info("reconcile pass: %s", ops)
                except Exception as e:  # noqa: BLE001 - the loop must survive
                    logger.warning("reconcile pass failed: %s", e)
                n += 1
                if iterations is not None and n >= iterations:
                    return
                # woken early by a watch event or stop(); else the resync
                kick.wait(self.resync_s)
        finally:
            renew_stop.set()
