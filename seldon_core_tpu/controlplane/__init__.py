"""Control plane: declarative SeldonDeployment resources reconciled onto
local TPU-host runtimes.

TPU-native counterpart of the reference's Go operator stack
(reference: operator/main.go:49-93, operator/controllers/
seldondeployment_controller.go:253-1199): a resource store stands in for
the K8s API server, an admission step mirrors the defaulting/validating
webhook, and the reconciler materializes engines + microservice processes
(instead of Deployments/Services) with topology-aware TPU device
placement instead of GKE node-pool scheduling.
"""

from .resource import DeploymentStatus, SeldonDeployment
from .store import ResourceStore
from .placement import PlacementError, TpuPlacement
from .reconciler import DeploymentController
from .ingress import Gateway

__all__ = [
    "SeldonDeployment",
    "DeploymentStatus",
    "ResourceStore",
    "TpuPlacement",
    "PlacementError",
    "DeploymentController",
    "Gateway",
]
