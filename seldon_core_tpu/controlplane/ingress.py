"""Ingress gateway: weighted canary routing across predictors.

Parity with the reference's Istio VirtualService traffic weights and
Ambassador mappings (reference: operator/controllers/
seldondeployment_controller.go:113-224 createIstioResources;
operator/controllers/ambassador.go:50-222 — weighted canaries, shadow
predictors, header-based routing). One asyncio HTTP front exposes

    /seldon/<namespace>/<deployment>/api/v0.1/predictions  (and /feedback)

and fans each request to one predictor's engine chosen by traffic weight,
honouring a ``seldon-predictor`` header override and mirroring traffic to
shadow predictors fire-and-forget.

Auth (reference: the legacy OAuth "apife" gateway the client SDK speaks —
python/seldon_core/seldon_client.py:931-1106): when key/secret pairs are
configured (constructor or ``SELDON_OAUTH_KEY``/``SELDON_OAUTH_SECRET``
env), ``POST /oauth/token`` with HTTP Basic credentials issues a bearer
token and every /seldon/* route requires ``Authorization: Bearer <tok>``.
Unconfigured gateways stay open (in-cluster mode).
"""

from __future__ import annotations

import asyncio
import base64
import logging
import os
import random
import secrets
import time
from typing import Dict, List, Optional, Tuple

from ..http_server import HTTPServer, Request, Response, error_body

logger = logging.getLogger(__name__)

HEADER_PREDICTOR = "seldon-predictor"
ANNOTATION_SHADOW = "seldon.io/shadow"


def _log_shadow_failure(task: "asyncio.Task") -> None:
    if not task.cancelled() and task.exception() is not None:
        logger.warning("shadow mirror failed: %s", task.exception())


class _Route:
    __slots__ = ("predictor", "weight", "handles", "shadow", "_rr")

    def __init__(self, predictor: str, weight: int, handles: List, shadow: bool):
        self.predictor = predictor
        self.weight = weight
        self.handles = handles
        self.shadow = shadow
        self._rr = 0

    def pick(self):
        """Round-robin over replica engines of one predictor."""
        if not self.handles:
            return None
        h = self.handles[self._rr % len(self.handles)]
        self._rr += 1
        return h


TOKEN_TTL_S = 3600.0


class Gateway:
    def __init__(self, seed: Optional[int] = None,
                 oauth: Optional[Dict[str, str]] = None):
        # deployment key -> list of routes
        self._routes: Dict[str, List[_Route]] = {}
        # deployment key -> predictor -> explainer handles (reference:
        # "<deployment>-explainer" service, seldondeployment_explainers.go:160)
        self._explainers: Dict[str, Dict[str, List]] = {}
        self._rng = random.Random(seed)
        # oauth key -> secret; empty = open gateway
        if oauth is None and os.environ.get("SELDON_OAUTH_KEY"):
            oauth = {
                os.environ["SELDON_OAUTH_KEY"]: os.environ.get("SELDON_OAUTH_SECRET", "")
            }
        self._oauth = dict(oauth or {})
        self._tokens: Dict[str, float] = {}  # token -> expiry monotonic

    # -- auth ---------------------------------------------------------------

    @property
    def auth_enabled(self) -> bool:
        return bool(self._oauth)

    def issue_token(self, key: str, secret: str) -> Optional[str]:
        if key not in self._oauth or not secrets.compare_digest(
            self._oauth[key], secret
        ):
            return None
        # sweep expired tokens on issuance so the table is bounded by the
        # number of live tokens, not the total ever issued
        now = time.monotonic()
        self._tokens = {t: exp for t, exp in self._tokens.items() if exp > now}
        token = secrets.token_urlsafe(24)
        self._tokens[token] = now + TOKEN_TTL_S
        return token

    def check_token(self, token: str) -> bool:
        exp = self._tokens.get(token)
        if exp is None:
            return False
        if time.monotonic() > exp:
            self._tokens.pop(token, None)
            return False
        return True

    def _authorized(self, req: Request) -> bool:
        if not self._oauth:
            return True
        header = req.headers.get("authorization", "")
        if header.lower().startswith("bearer "):
            return self.check_token(header[7:].strip())
        return False

    # -- route table maintenance (called by the reconciler) -----------------

    def set_routes(self, dep, endpoints: Dict[str, List], explainers: Optional[Dict[str, List]] = None) -> None:
        routes = []
        for pspec in dep.predictors:
            shadow = pspec.annotations.get(ANNOTATION_SHADOW, "false") == "true"
            routes.append(
                _Route(pspec.name, pspec.traffic, endpoints.get(pspec.name, []), shadow)
            )
        self._routes[dep.key] = routes
        self._explainers[dep.key] = dict(explainers or {})

    def drop_routes(self, key: str) -> None:
        self._routes.pop(key, None)
        self._explainers.pop(key, None)

    def select_explainer(self, key: str, header_predictor: Optional[str] = None):
        """Explainer handle for the (chosen) predictor of a deployment."""
        explainers = self._explainers.get(key) or {}
        if not explainers:
            return None
        if header_predictor:
            handles = explainers.get(header_predictor) or []
            return handles[0] if handles else None
        # only live (non-shadow) predictors' explainers are eligible — a
        # shadow's explainer explains a model serving 0% of real traffic
        routes = self._routes.get(key) or []
        for r in routes:
            if not r.shadow and r.predictor in explainers and explainers[r.predictor]:
                return explainers[r.predictor][0]
        return None

    def route_table(self) -> Dict[str, List[Tuple[str, int, int, bool]]]:
        return {
            k: [(r.predictor, r.weight, len(r.handles), r.shadow) for r in rs]
            for k, rs in self._routes.items()
        }

    # -- selection ----------------------------------------------------------

    def select(self, key: str, header_predictor: Optional[str] = None):
        """Choose (primary_handle, [shadow_handles]) for one request."""
        routes = self._routes.get(key)
        if not routes:
            return None, []
        live = [r for r in routes if not r.shadow]
        shadows = [r for r in routes if r.shadow]
        if header_predictor:
            for r in routes:
                if r.predictor == header_predictor:
                    return r.pick(), []
            return None, []
        total = sum(r.weight for r in live)
        if total <= 0:
            chosen = live[0] if live else None
        else:
            x = self._rng.uniform(0, total)
            acc = 0.0
            chosen = live[-1]
            for r in live:
                acc += r.weight
                if x <= acc:
                    chosen = r
                    break
        return (chosen.pick() if chosen else None), [s.pick() for s in shadows if s.handles]

    # -- HTTP front ---------------------------------------------------------

    async def _forward(self, handle, path: str, payload):
        """Dispatch to an engine; uses the in-process app when available
        (zero-copy localhost fast path, like the webhook's
        ServiceHost=localhost — reference: seldondeployment_webhook.go:211-216)."""
        import json as _json

        app = getattr(handle, "app", None)
        if app is not None:
            if path.endswith("/feedback"):
                return await app.send_feedback(payload)
            if path.endswith("/predictions") or path == "/predict":
                return await app.predict(payload)
            raise LookupError(f"no engine route {path}")
        user_object = getattr(handle, "user_object", None)
        if user_object is not None:
            # no-engine mode: the routable component is a bare model
            from .. import seldon_methods

            if path.endswith("/feedback") or path.endswith("/send-feedback"):
                fn = seldon_methods.send_feedback
            elif path.endswith("/predictions") or path == "/predict":
                fn = seldon_methods.predict
            elif path.endswith("/explain"):
                fn = seldon_methods.explain
            else:
                raise LookupError(f"no model route {path}")
            return await asyncio.get_running_loop().run_in_executor(
                None, fn, user_object, payload
            )

        def do_post():
            import urllib.request

            from ..payload import jsonable

            req = urllib.request.Request(
                f"{handle.url}{path}",
                data=_json.dumps(jsonable(payload)).encode(),
                headers={"content-type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10.0) as r:
                return _json.loads(r.read())

        return await asyncio.get_running_loop().run_in_executor(None, do_post)

    def app(self) -> HTTPServer:
        from ..http_server import max_body_from_env

        # the gateway fronts every engine: its cap must be raisable too or
        # a raised engine-side seldon.io/rest-max-body dies at the gateway
        server = HTTPServer("gateway", max_body_bytes=max_body_from_env())
        gw = self

        async def token_endpoint(req: Request) -> Response:
            """POST /oauth/token with HTTP Basic key:secret (the reference
            client's oauth flow — seldon_client.py:931-1106)."""
            if not gw.auth_enabled:
                return Response(error_body(404, "oauth not configured"), 404)
            header = req.headers.get("authorization", "")
            key = secret = None
            if header.lower().startswith("basic "):
                try:
                    decoded = base64.b64decode(header[6:]).decode()
                    key, _, secret = decoded.partition(":")
                except Exception:  # noqa: BLE001 - malformed basic auth
                    pass
            if key is None:
                body = req.json() or {}
                key, secret = body.get("key"), body.get("secret")
            token = gw.issue_token(key or "", secret or "")
            if token is None:
                return Response(error_body(401, "bad oauth credentials"), 401)
            return Response(
                {"access_token": token, "token_type": "bearer",
                 "expires_in": int(TOKEN_TTL_S)}
            )

        async def handler(req: Request) -> Response:
            if not gw._authorized(req):
                return Response(error_body(401, "missing or invalid bearer token"), 401)
            # /seldon/<ns>/<name>/api/v0.1/predictions
            parts = [p for p in req.path.split("/") if p]
            if len(parts) < 4 or parts[0] != "seldon":
                return Response(error_body(404, f"no route for {req.path}"), 404)
            ns, name = parts[1], parts[2]
            api_path = "/" + "/".join(parts[3:])
            key = f"{ns}/{name}"
            if api_path.endswith("/explain"):
                handle = gw.select_explainer(key, req.headers.get(HEADER_PREDICTOR))
                if handle is None:
                    return Response(error_body(404, f"no explainer for {key}"), 404)
                try:
                    out = await gw._forward(handle, "/explain", req.json())
                except Exception as e:  # noqa: BLE001 - gateway must answer
                    return Response(error_body(502, str(e)), 502)
                return Response(out)
            primary, shadows = gw.select(key, req.headers.get(HEADER_PREDICTOR))
            if primary is None:
                return Response(error_body(503, f"no live predictor for {key}"), 503)
            # req.json() handles both raw JSON and the reference's
            # form-encoded `json=` body style
            payload = req.json()
            # legacy gateway-side mirroring ONLY when the engine doesn't
            # mirror for itself: a rollout wires a bounded, diffing
            # ShadowMirror onto the primary's EngineApp (rollout/mirror.py),
            # and double-mirroring would send shadows every request twice.
            # The engine mirrors PREDICTIONS only — feedback (reward
            # signals a shadow's routers need) still fans out here even
            # mid-rollout
            engine_mirrors = (
                getattr(getattr(primary, "app", None), "shadow_mirror", None)
                is not None
                and (api_path.endswith("/predictions") or api_path == "/predict")
            )
            if not engine_mirrors:
                for s in shadows:
                    t = asyncio.ensure_future(gw._forward(s, api_path, payload))
                    t.add_done_callback(_log_shadow_failure)
            try:
                out = await gw._forward(primary, api_path, payload)
            except LookupError as e:
                return Response(error_body(404, str(e)), 404)
            except Exception as e:  # noqa: BLE001 - gateway must answer
                status = getattr(e, "status", None)
                if status == 503 and (
                    api_path.endswith("/predictions") or api_path == "/predict"
                ):
                    # engine-internal retry: a 503-class refusal (dead,
                    # restarting, or DRAINING generate batcher) is
                    # member-local — retry once on another routable
                    # member. Generation is seed-deterministic, and a
                    # client resume token riding the payload re-enters
                    # exactly where the dead member stopped, so the
                    # retried response is byte-identical to an
                    # uninterrupted run.
                    alt = None
                    for _ in range(3):
                        cand, _sh = gw.select(
                            key, req.headers.get(HEADER_PREDICTOR)
                        )
                        if cand is not None and cand is not primary:
                            alt = cand
                            break
                    if alt is not None:
                        try:
                            return Response(
                                await gw._forward(alt, api_path, payload)
                            )
                        except Exception as e2:  # noqa: BLE001 - second member
                            e = e2
                            status = getattr(e2, "status", None)
                if status == 503:
                    after = getattr(e, "retry_after_s", None)
                    return Response(
                        error_body(503, str(e)), 503,
                        headers={"Retry-After": str(max(1, int(after + 0.5)))
                                 if after else "1"},
                    )
                return Response(error_body(502, str(e)), 502)
            return Response(out)

        async def routes(req: Request) -> Response:
            if not gw._authorized(req):
                return Response(error_body(401, "missing or invalid bearer token"), 401)
            return Response(gw.route_table())

        server.add_prefix_route("/seldon/", handler)
        server.add_route("/routes", routes)
        server.add_route("/oauth/token", token_endpoint)
        return server
