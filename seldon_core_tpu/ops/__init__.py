"""TPU kernels (Pallas) for the hot ops.

The reference has no custom kernels anywhere (its FLOPs live behind
TFServing/Triton, SURVEY §2 #35-36); this package is new TPU-native
capability: hand-tiled Pallas kernels for the ops XLA leaves bandwidth
on the table for, with XLA fallbacks everywhere so every call site works
on CPU and in tests.
"""

from .flash_attention import attention, flash_attention  # noqa: F401
