"""Blocked (flash) attention as a Pallas TPU kernel.

Motivation: prefill attention materialises the full [T, T] score matrix
in XLA — at long prompts that is O(T^2) HBM traffic and VMEM spill. The
flash kernel streams K/V blocks through VMEM with an online-softmax
accumulator, so scores never leave VMEM and HBM traffic is O(T * Dh).
No reference counterpart (the reference ships no kernels at all); the
algorithm is the standard FlashAttention blocking, tiled for the MXU
(128-row blocks, f32 accumulators, bf16 operands).

``attention()`` is the public entry: it dispatches to the Pallas kernel
on TPU for shapes that tile cleanly and falls back to the XLA einsum
path (parallel/ring.full_attention's math) everywhere else — CPU tests,
tiny prompts, ragged head dims. ``flash_attention()`` is the kernel
itself (``interpret=True`` runs it on CPU for equivalence tests).

Used by DecoderLM.prefill (serving prefill is inference-only, so the
kernel needs no VJP). The BERT encoder keeps its XLA attention: its
per-row padding bias doesn't fit the kernel's mask model, and at seq 128
XLA is already at the compute roof.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG_INF = -1e30


def _xla_attention(q, k, v, causal: bool, kv_len=None):
    """Reference attention, same contract as the kernel — delegates to
    parallel/ring.full_attention so the fallback and the trained/ring
    paths share ONE copy of the math."""
    from ..parallel.ring import full_attention

    return full_attention(q, k, v, causal=causal, kv_len=kv_len)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q, block_k, causal):
    """One (bh, q-block) program: stream K/V blocks with online softmax.

    q_ref/o_ref: [1, block_q, Dh]; k_ref/v_ref: [1, Tk, Dh] (whole keys
    for this bh resident in VMEM — serving-sized Tk*Dh fits easily).
    """
    qb = pl.program_id(1)
    dh = q_ref.shape[-1]
    scale = 1.0 / np.sqrt(dh)
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, Dh]
    t_k = k_ref.shape[1]
    row = qb * block_q + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    def body(i, carry):
        o, m, l = carry
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            col = i * block_k + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
            s = jnp.where(row >= col, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        # Masked entries hold NEG_INF (finite -1e30): under this kernel's
        # causal dispatch every row admits column 0, so m_new is finite
        # after the first k-block and exp(NEG_INF - m_new) underflows to
        # exactly 0 — no NaN, no select needed in the hot loop. A mask
        # that fully hides a row would leave m_new == NEG_INF and make
        # p == 1 per entry (an unweighted mean of V, not zeros); reuse
        # with such masks requires a p = where(s == NEG_INF, 0, ...) guard.
        p = jnp.exp(s - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        o = o * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return o, m_new, l

    n_k = t_k // block_k
    if causal:
        # blocks fully above the diagonal contribute nothing: stop at the
        # q-block's last row (block sizes are equal-or-multiples, so the
        # bound lands on a block edge or inside the masked block)
        n_k = jnp.minimum(n_k, (qb * block_q + block_q + block_k - 1) // block_k)
    o = jnp.zeros((block_q, dh), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = lax.fori_loop(0, n_k, body, (o, m, l))
    # l == 0 is unreachable via the causal equal-block dispatch (see the
    # loop-body comment); kept as a belt against 0/0 if the kernel is
    # rebuilt with a row-hiding mask
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (o / l).astype(o_ref.dtype)


try:  # pallas is TPU/Triton-only in some builds; the fallback never needs it
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover - exercised only in pallas-less builds
    pl = None


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Pallas blocked attention. q [B,H,Tq,Dh], k/v [B,H,Tk,Dh].
    Tq must divide by block_q and Tk by block_k (use :func:`attention`
    for the dispatching fallback)."""
    if pl is None:
        raise RuntimeError("pallas is unavailable in this jax build")
    b, h, t_q, dh = q.shape
    t_k = k.shape[2]
    if t_q % block_q or t_k % block_k:
        raise ValueError(
            f"Tq={t_q} / Tk={t_k} must tile by block ({block_q}, {block_k})"
        )
    qf = q.reshape(b * h, t_q, dh)
    kf = k.reshape(b * h, t_k, dh)
    vf = v.reshape(b * h, t_k, dh)
    kernel = functools.partial(
        _flash_kernel,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(b * h, t_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, t_k, dh), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, t_k, dh), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dh), lambda bh, i: (bh, i, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t_q, dh)


def attention(q, k, v, kv_len=None, causal: bool = True):
    """Dispatching attention: Pallas flash kernel on TPU when the shape
    tiles onto the MXU, XLA einsum otherwise (CPU, tiny prompts). Inference
    only — the kernel defines no VJP; training paths keep the XLA/ring
    implementations (parallel/ring.py)."""
    t_q, t_k = q.shape[2], k.shape[2]
    # bigger blocks amortise the online-softmax rescale and MXU ramp-up;
    # measured on v5e: T=8192 runs 2x XLA at block 512, T<=2048 is at the
    # compute roof either way
    block = 128
    while block < 512 and t_q % (block * 2) == 0 and t_k % (block * 2) == 0 \
            and block * 16 < t_q:
        block *= 2
    use_kernel = (
        pl is not None
        and kv_len is None
        and jax.default_backend() == "tpu"
        and t_q % block == 0
        and t_k % block == 0
        and q.shape[-1] in (64, 128, 256)
    )
    if not use_kernel:
        return _xla_attention(q, k, v, causal=causal, kv_len=kv_len)
    return flash_attention(q, k, v, causal=causal, block_q=block, block_k=block)
