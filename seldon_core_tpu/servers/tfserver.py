"""TensorFlow model server: SavedModel served through the TPU path.

The reference bridged to an external TF-Serving container over gRPC/REST
(reference: integrations/tfserving/TfServingProxy.py:21-60 and the
TENSORFLOW_SERVER wiring in operator/controllers/
seldondeployment_prepackaged_servers.go:30-107). TPU-native design: no
sidecar — load the SavedModel with ``tf.saved_model.load`` and execute
its serving signature directly. NOTE: tensorflow is absent from this
image, so the real-loader branch has never executed here — it is
exercised only through the injectable ``loader`` seam (tests inject a
fake); when tensorflow is missing at runtime the server fails with a
clear error telling users to export to the jaxserver format instead.
"""

from __future__ import annotations

import numpy as np

from ..storage import Storage
from ..user_model import SeldonComponent


class TFServer(SeldonComponent):
    """``loader(model_dir, signature) -> fn(np.ndarray) -> np.ndarray`` is
    injectable so the full load+predict path is testable without
    tensorflow in the image (the real loader wraps tf.saved_model.load)."""

    def __init__(self, model_uri: str, signature: str = "serving_default",
                 loader=None, **kwargs):
        self.model_uri = model_uri
        self.signature = signature
        self._loader = loader
        self._fn = None

    @staticmethod
    def _tf_loader(model_dir: str, signature: str):
        try:
            import tensorflow as tf  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "TENSORFLOW_SERVER requires tensorflow (absent in this image). "
                "Export the SavedModel to jaxserver format (jax_config.json + "
                "orbax checkpoint) and use JAX_SERVER instead."
            ) from e
        import tensorflow as tf

        sig = tf.saved_model.load(model_dir).signatures[signature]

        def fn(arr: np.ndarray) -> np.ndarray:
            out = sig(tf.constant(arr))
            return next(iter(out.values())).numpy()

        return fn

    def load(self) -> None:
        if self._loader is None:
            # fail on a missing tensorflow BEFORE the (potentially multi-GB)
            # model download
            try:
                import tensorflow  # noqa: F401
            except ImportError as e:
                raise RuntimeError(
                    "TENSORFLOW_SERVER requires tensorflow (absent in this "
                    "image). Export the SavedModel to jaxserver format "
                    "(jax_config.json + orbax checkpoint) and use JAX_SERVER "
                    "instead."
                ) from e
        model_dir = Storage.download(self.model_uri)
        loader = self._loader or self._tf_loader
        self._fn = loader(model_dir, self.signature)

    def predict(self, X, names, meta=None):
        if self._fn is None:
            self.load()
        return self._fn(np.asarray(X))
