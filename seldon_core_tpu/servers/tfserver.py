"""TensorFlow model server: SavedModel served through the TPU path.

The reference bridged to an external TF-Serving container over gRPC/REST
(reference: integrations/tfserving/TfServingProxy.py:21-60 and the
TENSORFLOW_SERVER wiring in operator/controllers/
seldondeployment_prepackaged_servers.go:30-107). TPU-native design: no
sidecar — load the SavedModel and execute it with jax2tf round-trip or,
when tensorflow is absent (this image), fail with a clear error telling
users to export to the jaxserver format instead.
"""

from __future__ import annotations

import numpy as np

from ..storage import Storage
from ..user_model import SeldonComponent


class TFServer(SeldonComponent):
    def __init__(self, model_uri: str, signature: str = "serving_default", **kwargs):
        self.model_uri = model_uri
        self.signature = signature
        self._fn = None

    def load(self) -> None:
        try:
            import tensorflow as tf  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "TENSORFLOW_SERVER requires tensorflow (absent in this image). "
                "Export the SavedModel to jaxserver format (jax_config.json + "
                "orbax checkpoint) and use JAX_SERVER instead."
            ) from e
        import tensorflow as tf

        model_dir = Storage.download(self.model_uri)
        loaded = tf.saved_model.load(model_dir)
        self._fn = loaded.signatures[self.signature]

    def predict(self, X, names, meta=None):
        import tensorflow as tf

        if self._fn is None:
            self.load()
        out = self._fn(tf.constant(np.asarray(X)))
        first = next(iter(out.values()))
        return first.numpy()
