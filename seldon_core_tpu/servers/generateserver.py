"""Generate prepackaged server: LLM token generation with continuous
batching behind the standard unary predict protocol.

BASELINE.json config 5 ("Llama-2-7B generate() with engine-side dynamic
batching"); no reference counterpart — the reference's servers are all
unary classifiers (servers/sklearnserver/... — SURVEY §2 #32-35).

Model URI layout: same ``jax_config.json`` as jaxserver with
``"family": "llm"``; extra server params tune the scheduler::

    slots            decode lanes (default 8)
    max_seq          cache length override
    shard_cache_seq  shard the KV cache length over the mesh's `seq` axis
    mesh_shape       sharded serving: build a device mesh at load and
                     serve ONE model partitioned across it — params take
                     the TP layout (DecoderLM.param_sharding), every KV
                     slab shards its heads axis over ``model`` while the
                     lane axis stays data-parallel. ``"data=2,model=4"``
                     (strict axis=size pairs, typed MeshShapeError on
                     malformed/non-dividing shapes) or ``"auto"``
                     (factor jax.device_count() into the 2D data x model
                     serving mesh). Greedy AND seeded outputs stay
                     byte-identical to 1-device — see docs/generate.md
                     "Sharded serving". Ignored when an explicit ``mesh``
                     object is injected (the engine placement path)
    steps_per_poll   decode steps fused into one device burst (default 8;
                     pow2-floored — the value actually dispatched is
                     surfaced as ``steps_per_poll_effective`` in server
                     stats)
    fused_steps_per_dispatch
                     fused multi-step decode: one dispatch runs up to
                     this many decode steps ENTIRELY on device —
                     per-step KV append, greedy + seeded-categorical
                     sampling, stop-token detection, and per-lane done
                     masks that freeze finished lanes (0 = off, the
                     step-at-a-time burst path). K adapts per poll
                     (shrinks toward the nearest lane's stop budget and
                     to ``steps_per_poll`` under HBM pressure or a
                     staged swap/drain) and byte-identity on vs off is
                     the contract — see docs/generate.md "Fused decode"
    pipeline_depth   bursts in flight before the host reads the oldest
                     (default 3; 1 = synchronous)
    speculate_tokens speculative decoding: draft this many tokens per
                     round, verify with one target forward (0 = off).
                     Exact for any draft — greedy lanes reproduce the
                     target argmax decode, temperature lanes use
                     speculative sampling (the emitted distribution
                     equals sampling the target). Needs a draft:
    draft_layers     early-exit self-draft — the first N layers of the
                     SERVED model propose (no second checkpoint)
    draft_uri        separate draft model dir (same vocab)
    prefix_cache_hbm_bytes
                     radix prefix KV-cache budget in HBM bytes (0 = off,
                     the disable flag): completed requests publish their
                     prompt K/V; later prompts sharing a prefix splice it
                     and prefill only the suffix (LRU-evicted at radix-
                     node granularity). Responses then carry per-request
                     ``cache_hit_tokens``.
    prefix_cache_min_tokens
                     shortest prefix worth caching or reusing (default 16)
    admit_queue_limit
                     max queued-not-admitted requests before submits are
                     shed with 429 (0 = uncapped). Queued requests with a
                     deadline (meta ``deadlineMs``) are additionally shed
                     when the queue's expected wait exceeds it — see
                     docs/operate.md "Resilience"
    depth_groups     depth-aware decode: max fused sub-bursts per poll
                     (0/1 = off). Lanes partition by attention-read
                     bucket so shallow lanes stop paying the deepest
                     lane's cache read — see docs/generate.md
                     "Depth-aware scheduling"
    depth_group_split_bytes
                     cost-model override: HBM bytes/step an extra
                     sub-burst is charged (default: the params' byte
                     size — one more param read per step)
    prefill_chunk    chunked prefill: split long-prompt prefills into
                     this many tokens per slice, interleaved between
                     decode polls (0 = off) — a 1,792-token admit no
                     longer stalls every decode lane for one
                     prompt-length forward
    flight_recorder  scheduler flight-recorder capacity: the batcher
                     keeps this many per-poll decision records in a
                     bounded drop-oldest ring, dumped at the engine's
                     ``/flightrecorder`` route (0 = off; default 512 —
                     cheap enough to leave on, see docs/operate.md
                     "Observability")
    role             ``unified`` (default; serve prefill+decode locally,
                     byte-identical to every prior release) |
                     ``prefill`` (run prompt prefill only and export the
                     K/V slab over the KV transport — no decode lanes,
                     no scheduler loop) | ``decode`` (pull prefilled
                     slabs from ``peer`` and run decode-only lanes).
                     See docs/generate.md "Disaggregated serving"
    peer             decode role: the prefill pool's KV endpoints as a
                     ``host:port`` LIST (comma-separated string) — peers
                     are health-probed, ejected with backoff on transfer
                     failure, readmitted on probe success, and a failed
                     transfer retries once on the next healthy peer;
                     with the whole pool ejected, decode degrades to
                     LOCAL unified prefill (``degraded_local_prefill``
                     counts the regression). Tests/benches may instead
                     wire live prefill GenerateServer objects via
                     ``set_peer()`` (loopback transport — same codec,
                     in memory)
    kv_port          prefill role: TCP port the KV export listener
                     binds (0 = loopback-only, no listener)
    kv_chunk_bytes   KV transport write granularity — the sender-side
                     in-flight bound per slab stream (default 1 MiB)
    peer_eject_backoff_s
                     decode role: initial per-peer re-probe backoff
                     after a transfer failure (exponential, capped 30s;
                     default 1.0)
    restart_budget   scheduler supervision: how many times a dead
                     batcher loop may rebuild (fresh cache + re-warm)
                     before the member latches unready for replacement
                     (default 3); see docs/operate.md "Failure modes"
    restart_backoff_s
                     initial crash-restart backoff (exponential,
                     default 0.5)
    hbm_ledger_bytes HBM-pressure budget for the scheduler's unified
                     ledger (live decode footprint + staging slabs +
                     prefix cache + pending-swap double buffer; 0 =
                     off, the disable flag). Over the high watermark
                     the reclaim ladder runs: evict prefixes, cancel
                     speculation, preempt decode lanes
                     (checkpoint-to-host + recompute-resume, byte-
                     identical output), shed admissions — see
                     docs/generate.md "HBM pressure & preemption"
    pressure_high    high watermark as a fraction of the ledger budget
                     (default 0.90): crossing it latches pressure
    pressure_low     low watermark (default 0.75): reclaim runs until
                     usage drops here, then admissions resume
    host_kv_tier_bytes
                     tiered KV memory: byte budget of the pinned
                     host-RAM spill tier (0 = off, the disable flag).
                     With it on, the reclaim ladder DEMOTES prefix
                     slabs to host instead of destroying them (a later
                     match promotes: device_put + splice — a PCIe copy
                     instead of a re-prefill), preempted lanes
                     checkpoint their exact K/V for copy-back resume
                     (recompute+replay stays the fallback), prefill
                     exports publish their slabs for peers, and the KV
                     port answers peer prefix-lookups from the tier —
                     see docs/generate.md "Tiered KV memory"
    kv_tier_min_tokens
                     demote threshold: prefixes shorter than this never
                     enter the tier (0 = prefix_cache_min_tokens)
    kv_tier_promote_min_tokens
                     promote threshold: tier matches shallower than
                     this are not worth the PCIe copy (0 = the demote
                     threshold)
    kv_tier_peer_lookup
                     decode role: ask the prefill peers' host tiers for
                     a shared prefix before requesting a full prefill
                     (-1 = auto, on exactly when host_kv_tier_bytes is
                     set; 0 = off; 1 = force on — needs a local prefix
                     cache to splice the pulled slab)
    resume_tokens    live migration: attach an opaque SGC1 resume token
                     (serving/migration.py) to every streamed span (and
                     the unary response) so a member death mid-
                     generation is survivable — resubmit the token on
                     any peer serving the same weight_version and the
                     generation continues byte-identical with no span
                     re-sent (0 = off; incompatible with speculation —
                     the token's RNG re-derivation assumes plain
                     decode). See docs/generate.md "Live migration &
                     resumable streams"
    swap_drain_ms    hot-swap straggler bound: after this long draining
                     a staged weight swap, preempt-checkpoint the
                     remaining in-flight lanes so one long generation
                     cannot stall the flip (0 = wait forever)
    swap_resume_policy
                     what happens to swap-preempted stragglers:
                     ``resume`` (default) re-queues them to finish on
                     the NEW weights; ``fail`` refuses them typed
                     (WeightVersionMismatch, 409-class)

Request (jsonData)::

    {"prompt_tokens": [1, 2, ...],        # or "prompt": "text" (byte-level)
     "max_new_tokens": 32, "temperature": 0.0, "eos_id": null, "seed": 0}

Batched form: ``prompt_tokens`` may be a list of lists — each prompt is
submitted separately and rides the SAME in-flight decode batch (that is
the continuous-batching win; no padding to the longest prompt).

Response (jsonData): ``{"tokens": [[...]], "text": [...]}`` — ``text``
only for byte-level string prompts.
"""

from __future__ import annotations

import dataclasses
import logging
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

from ..analysis.roles import caller_thread
from ..user_model import SeldonComponent
from .jaxserver import JAXServer

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class StreamHandle:
    """A live token stream: iterate ``chunks``; call ``cancel()`` when the
    consumer goes away so the decode lane is reclaimed."""

    chunks: Iterable
    cancel: Callable[[], bool]


class GenerateServer(SeldonComponent):
    # class-level defaults so partially constructed instances (tests
    # build shells via __new__ around a bare batcher) behave as the
    # unified role with no transport endpoints
    _role = "unified"
    _kv_server = None
    _kv_client = None
    _resume_tokens = False
    _kv_tier_peer_lookup = False
    _tenant_spec = None
    tenant_pager = None
    tenant_scheduler = None
    batcher = None
    profiler = None
    slo_burn = None

    def __init__(
        self,
        model_uri: str,
        mesh=None,
        slots: int = 8,
        max_seq: Optional[int] = None,
        shard_cache_seq: bool = False,
        mesh_shape: Optional[str] = None,
        steps_per_poll: int = 8,
        fused_steps_per_dispatch: int = 0,
        pipeline_depth: int = 3,
        attn_bucket: int = 128,
        speculate_tokens: int = 0,
        draft_layers: int = 0,
        draft_uri: Optional[str] = None,
        prefix_cache_hbm_bytes: int = 0,
        prefix_cache_min_tokens: int = 16,
        admit_queue_limit: int = 0,
        depth_groups: int = 0,
        depth_group_split_bytes: Optional[int] = None,
        prefill_chunk: int = 0,
        flight_recorder: int = 512,
        role: str = "unified",
        peer: Optional[str] = None,
        kv_port: int = 0,
        kv_chunk_bytes: int = 1 << 20,
        peer_eject_backoff_s: float = 1.0,
        restart_budget: int = 3,
        restart_backoff_s: float = 0.5,
        hbm_ledger_bytes: int = 0,
        pressure_high: float = 0.90,
        pressure_low: float = 0.75,
        host_kv_tier_bytes: int = 0,
        kv_tier_min_tokens: int = 0,
        kv_tier_promote_min_tokens: int = 0,
        kv_tier_peer_lookup: int = -1,
        resume_tokens: int = 0,
        swap_drain_ms: int = 0,
        swap_resume_policy: str = "resume",
        warmup_prompt_lens: Optional[Sequence[int]] = None,
        warmup_max_new_tokens: int = 0,
        tenants: Optional[str] = None,
        weight_pager_host_bytes: int = 0,
        tenant_tick_ms: int = 20,
        tenant_max_wait_polls: int = 256,
        tenant_min_resident_ms: int = 50,
        profiler: int = 0,
        profiler_deep_every: int = 0,
        profiler_hbm_gb_s: float = 0.0,
        profiler_dispatch_floor_us: float = 0.0,
        slo_objectives: Optional[str] = None,
        slo_fast_window_s: float = 60.0,
        slo_slow_window_s: float = 3600.0,
        **kwargs,
    ):
        self.model_uri = model_uri
        role = str(role or "unified").lower()
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role must be unified|prefill|decode, got {role!r}"
            )
        self._role = role
        self._peer = peer or None
        self._kv_port = int(kv_port)
        self._kv_chunk_bytes = int(kv_chunk_bytes)
        self._peer_eject_backoff_s = float(peer_eject_backoff_s)
        self._restart_budget = int(restart_budget)
        self._restart_backoff_s = float(restart_backoff_s)
        self._hbm_ledger_bytes = int(hbm_ledger_bytes)
        self._pressure_high = float(pressure_high)
        self._pressure_low = float(pressure_low)
        self._host_kv_tier_bytes = int(host_kv_tier_bytes)
        self._kv_tier_min_tokens = int(kv_tier_min_tokens)
        self._kv_tier_promote_min_tokens = int(kv_tier_promote_min_tokens)
        # -1 = auto: peer prefix-lookups ride exactly the tier knob
        self._kv_tier_peer_lookup = (
            self._host_kv_tier_bytes > 0
            if int(kv_tier_peer_lookup) < 0 else bool(int(kv_tier_peer_lookup))
        )
        # typed-params env delivers booleans as strings
        self._resume_tokens = (
            resume_tokens.lower() == "true"
            if isinstance(resume_tokens, str) and not resume_tokens.isdigit()
            else bool(int(resume_tokens))
        )
        self._swap_drain_ms = int(swap_drain_ms)
        self._swap_resume_policy = str(swap_resume_policy or "resume")
        if self._resume_tokens and int(speculate_tokens) > 0:
            raise ValueError(
                "resume_tokens is not supported with speculative decoding "
                "(the token's RNG re-derivation assumes the plain decode "
                "split chain)"
            )
        self._kv_server = None   # PrefillTransportServer (prefill role)
        self._kv_client = None   # FailoverKVClient over the peer list (decode)
        self._faults = None      # FaultInjector (chaos harness), set at load
        if role != "unified" and int(speculate_tokens) > 0:
            raise ValueError(
                "disaggregated roles do not support speculative decoding "
                "(the draft cache cannot cross the KV transport)"
            )
        self._mesh = mesh
        # sharded-serving knob: parsed STRICTLY at construction (the
        # admission-time contract — a malformed shape must refuse here,
        # not as an opaque XLA failure mid-load). "auto" defers the
        # factoring to load(), when jax.device_count() is known.
        mesh_shape = (mesh_shape or "").strip() if isinstance(
            mesh_shape, str
        ) else mesh_shape
        self._mesh_shape: Optional[Any] = None
        if mesh_shape:
            if str(mesh_shape).lower() == "auto":
                self._mesh_shape = "auto"
            else:
                from ..parallel.mesh import parse_mesh_shape

                self._mesh_shape = parse_mesh_shape(str(mesh_shape))
        self._slots = int(slots)
        self._max_seq = int(max_seq) if max_seq else None
        self._shard_cache_seq = bool(shard_cache_seq) if not isinstance(
            shard_cache_seq, str
        ) else shard_cache_seq.lower() == "true"
        self._steps_per_poll = int(steps_per_poll)
        self._fused_steps_per_dispatch = int(fused_steps_per_dispatch)
        self._pipeline_depth = int(pipeline_depth)
        self._attn_bucket = int(attn_bucket)
        self._speculate_tokens = int(speculate_tokens)
        self._draft_layers = int(draft_layers)
        self._draft_uri = draft_uri
        self._prefix_cache_hbm_bytes = int(prefix_cache_hbm_bytes)
        self._prefix_cache_min_tokens = int(prefix_cache_min_tokens)
        self._admit_queue_limit = int(admit_queue_limit)
        self._depth_groups = int(depth_groups)
        self._depth_group_split_bytes = (
            int(depth_group_split_bytes)
            if depth_group_split_bytes is not None else None
        )
        self._prefill_chunk = int(prefill_chunk)
        self._flight_recorder = int(flight_recorder)
        # cumulative scheduler stats ship as true counters (deltas)
        # through Meta.metrics
        from ..metrics import CounterDeltas

        self._deltas = CounterDeltas()
        # parse CSV from typed-params env ("128,1792") as well as sequences
        if isinstance(warmup_prompt_lens, str):
            warmup_prompt_lens = [
                int(x) for x in warmup_prompt_lens.split(",") if x.strip()
            ]
        self._warmup_prompt_lens = list(warmup_prompt_lens or [])
        self._warmup_max_new_tokens = int(warmup_max_new_tokens)
        # multi-tenancy: `tenants` is the same strict grammar as the
        # seldon.io/tenants annotation (name=slo[@model_uri] CSV) —
        # parsed at construction so a malformed spec refuses at
        # admission, not mid-load. The pager host budget gates the
        # whole subsystem: 0 (default) = single-tenant, byte-identical
        # to the pre-tenant server.
        self._tenant_spec = None
        if tenants:
            from ..serving.weightpager import parse_tenant_spec

            self._tenant_spec = parse_tenant_spec(str(tenants))
        self._weight_pager_host_bytes = int(weight_pager_host_bytes)
        if self._tenant_spec and self._weight_pager_host_bytes <= 0:
            raise ValueError(
                "tenants configured but weight_pager_host_bytes is 0 — "
                "the pager's host-RAM staging budget must be set"
            )
        if self._tenant_spec and self._role != "unified":
            raise ValueError(
                "multi-tenant paging is not supported on disaggregated "
                "roles (the KV transport assumes one weight lineage)"
            )
        self._tenant_tick_ms = int(tenant_tick_ms)
        self._tenant_max_wait_polls = int(tenant_max_wait_polls)
        self._tenant_min_resident_ms = int(tenant_min_resident_ms)
        self.tenant_pager = None      # WeightPager, set at load
        self.tenant_scheduler = None  # TenantScheduler, set at load
        # device-time profiler (serving/profiler.py): off by default —
        # the ledger is a shared no-op then, and the identity/overhead
        # gates in tests/test_profiler.py hold it to byte-identical
        # output. The MBU / dispatch-floor denominators are knobs so
        # the live gauges use MEASURED numbers (modelbench publishes
        # them) — 0 omits the gauge rather than publishing a guess.
        from ..serving.profiler import DeviceTimeLedger

        self.profiler = DeviceTimeLedger(
            enabled=bool(int(profiler)),
            deep_every=int(profiler_deep_every),
            hbm_gb_s=float(profiler_hbm_gb_s),
            dispatch_floor_us=float(profiler_dispatch_floor_us),
        )
        # SLO burn-rate engine (serving/slo_burn.py), fed by the same
        # completed-request TTFT/TPOT/queue-wait drain /metrics exports.
        # Grammar: "slo:threshold_ms:target" CSV, e.g.
        # "ttft:200:0.99,queue_wait:50:0.999" — strict parse at
        # construction, same contract as the tenants spec.
        self.slo_burn = None
        if slo_objectives:
            from ..serving.slo_burn import SloBurnEngine, SloObjective

            objs = []
            for ent in str(slo_objectives).split(","):
                ent = ent.strip()
                if not ent:
                    continue
                parts = ent.split(":")
                if len(parts) != 3:
                    raise ValueError(
                        "slo_objectives entries are slo:threshold_ms:target "
                        f"(e.g. ttft:200:0.99), got {ent!r}"
                    )
                objs.append(SloObjective(
                    parts[0].strip(), float(parts[1]) * 1e-3, float(parts[2])
                ))
            self.slo_burn = SloBurnEngine(
                objs,
                fast_window_s=float(slo_fast_window_s),
                slow_window_s=float(slo_slow_window_s),
            )
        self._extra = kwargs
        self.batcher = None
        self._model = None
        self._swap_count = 0

    @staticmethod
    def _cast_params_freeing_impl(tree, dt):
        """Cast fp32 leaves to ``dt`` IN PLACE through nested dicts,
        dropping each fp32 leaf as it is replaced. A functional tree_map
        would hold the full fp32 tree alive until rebind — at flagship
        scale that is 5 GB of HBM pinned through warmup, the difference
        between slots=32 fitting or OOMing (the batcher's serving_cast
        then sees already-cast leaves and passes through)."""
        import jax.numpy as jnp

        # iterate KEYS only: a list of items() tuples would pin every fp32
        # value for the whole loop, re-creating the double-resident peak
        for key in list(tree):
            v = tree[key]
            if isinstance(v, dict):
                GenerateServer._cast_params_freeing_impl(v, dt)
            elif hasattr(v, "dtype") and v.dtype == jnp.float32:
                tree[key] = v.astype(dt)
            del v
        return tree

    def load(self) -> None:
        from ..serving.continuous import ContinuousBatcher

        server = JAXServer(self.model_uri)
        apply_fn, params = server.build()
        self._model = server._model
        import jax.numpy as jnp

        dt = jnp.dtype(getattr(self._model, "compute_dtype", "bfloat16"))
        if dt != jnp.float32 and isinstance(params, dict):
            params = self._cast_params_freeing_impl(params, dt)
        if self._model is None or not hasattr(self._model, "decode_step_ragged"):
            raise RuntimeError(
                f"model family {getattr(self._model, '__class__', None)} "
                "does not support generate(); use family 'llm'"
            )
        if self._mesh is None and self._mesh_shape is not None:
            # build the serving mesh from the knob: an injected mesh
            # object (the engine placement path) always wins, so a
            # reconciler-placed member never double-builds
            import jax

            from ..parallel.mesh import (
                factor_devices, make_mesh, validate_model_dims,
            )

            if self._mesh_shape == "auto":
                f = factor_devices(jax.device_count())
                # collapse to the 2D data x model serving mesh: generate
                # serving runs no pipeline axis, and the seq axis only
                # pays with shard_cache_seq (opt-in, explicit shapes)
                shape = {
                    "data": f["data"] * f["stage"] * f["seq"],
                    "model": f["model"],
                }
            else:
                shape = dict(self._mesh_shape)
            cfg = self._model.cfg
            validate_model_dims(
                shape, int(cfg.n_heads), int(cfg.d_ff),
                n_kv_heads=int(getattr(cfg, "n_kv_heads", 0) or 0),
            )
            self._mesh = make_mesh(shape)
            logger.info(
                "generateserver: sharded serving mesh %s over %d device(s)",
                shape, self._mesh.devices.size,
            )
        draft_model = None
        draft_params = None
        if self._speculate_tokens > 0:
            if self._draft_uri:
                dserver = JAXServer(self._draft_uri)
                _apply, draft_params = dserver.build()
                draft_model = dserver._model
            elif self._draft_layers > 0:
                if self._draft_layers >= self._model.cfg.n_layers:
                    raise ValueError(
                        f"draft_layers ({self._draft_layers}) must be < the "
                        f"served model's n_layers ({self._model.cfg.n_layers})"
                    )
                # early-exit self-draft: the first N layers of the served
                # model (shared embed/head/norm, blocks sliced) — no second
                # checkpoint, and the proposals improve with the model
                import dataclasses as _dc

                import jax

                cfg = _dc.asdict(self._model.cfg)
                cfg["n_layers"] = self._draft_layers
                from ..models.llm import DecoderLM

                draft_model = DecoderLM(**cfg)
                draft_params = {
                    **params,
                    "blocks": jax.tree_util.tree_map(
                        lambda a: a[: self._draft_layers], params["blocks"]
                    ),
                }
            else:
                raise ValueError(
                    "speculate_tokens needs draft_layers or draft_uri"
                )
        self.batcher = ContinuousBatcher(
            self._model,
            params,
            # a prefill-role server runs NO decode lanes: the slab is
            # built in staging and shipped, never inserted locally — one
            # token lane keeps the cache allocation minimal
            slots=1 if self._role == "prefill" else self._slots,
            max_seq=self._max_seq,
            mesh=self._mesh,
            shard_cache_seq=self._shard_cache_seq,
            steps_per_poll=self._steps_per_poll,
            fused_steps_per_dispatch=self._fused_steps_per_dispatch,
            pipeline_depth=self._pipeline_depth,
            attn_bucket=self._attn_bucket,
            draft_model=draft_model,
            draft_params=draft_params,
            speculate_tokens=self._speculate_tokens,
            prefix_cache_hbm_bytes=self._prefix_cache_hbm_bytes,
            prefix_cache_min_tokens=self._prefix_cache_min_tokens,
            admit_queue_limit=self._admit_queue_limit,
            depth_groups=self._depth_groups,
            depth_group_split_bytes=self._depth_group_split_bytes,
            prefill_chunk=self._prefill_chunk,
            flight_recorder_capacity=self._flight_recorder,
            restart_budget=self._restart_budget,
            restart_backoff_s=self._restart_backoff_s,
            hbm_ledger_bytes=self._hbm_ledger_bytes,
            pressure_high=self._pressure_high,
            pressure_low=self._pressure_low,
            host_kv_tier_bytes=self._host_kv_tier_bytes,
            kv_tier_min_tokens=self._kv_tier_min_tokens,
            kv_tier_promote_min_tokens=self._kv_tier_promote_min_tokens,
            swap_drain_ms=self._swap_drain_ms,
            swap_resume_policy=self._swap_resume_policy,
            profiler=self.profiler,
        )
        # chaos harness (off without SELDON_FAULTS): the scheduler
        # section wires induced poll death onto the batcher's fault
        # hook, the pressure section wires mid-run ledger re-budgeting;
        # kv rules are resolved per peer when transports are built below
        from ..resilience import FaultInjector

        self._faults = FaultInjector.from_env()
        if self._faults is not None:
            hook = self._faults.scheduler_hook()
            if hook is not None:
                self.batcher.fault_hook = hook
            phook = self._faults.pressure_hook()
            if phook is not None:
                self.batcher.pressure_hook = phook
        if self._tenant_spec:
            # multi-tenancy: register EVERY tenant's checkpoint in the
            # pager's host-RAM staging tier (the resident one included —
            # its staging copy is what makes demotion a pointer flip,
            # not an HBM download), align the batcher's weight-version
            # lineage to the primary tenant's namespaced version BEFORE
            # warm() so the caches never see the un-namespaced 0, and
            # hang the SLO scheduler off the poll loop. Done before
            # warm(): the compiled executables are shape-keyed, not
            # weight-keyed, so one warm covers all tenants (the
            # scale-to-zero no-recompile property).
            self._load_tenants(params)
        if self._warmup_prompt_lens:
            # compile-before-listen: every prefill/insert/burst variant the
            # declared traffic shape needs is built here, so the first
            # admission wave never stalls tens of seconds on XLA
            self.batcher.warm(
                prompt_lens=self._warmup_prompt_lens,
                max_new_tokens=self._warmup_max_new_tokens,
            )
        if self._role == "prefill":
            # no scheduler loop: export_prefill runs on the transport's
            # handler threads, decode lanes never activate
            if self._kv_port:
                from ..serving.disagg import PrefillTransportServer

                self._kv_server = PrefillTransportServer(
                    self, port=self._kv_port,
                    chunk_bytes=self._kv_chunk_bytes,
                )
                logger.info(
                    "generateserver: prefill role exporting KV on :%d",
                    self._kv_server.port,
                )
        else:
            self.batcher.start()
            if self.tenant_scheduler is not None:
                # the page-in driver blocks on scheduler progress
                # (request_weight_swap futures), so it only starts once
                # the poll loop is live
                self.tenant_scheduler.start()
        if self._role == "decode" and self._peer is not None:
            self._kv_client = self._build_failover(self._peer)
        logger.info(
            "generateserver: %s ready (role=%s, slots=%d, max_seq=%d)",
            self.model_uri, self._role, self._slots, self.batcher.max_seq,
        )

    def _load_tenants(self, primary_params) -> None:
        """Stage every declared tenant's checkpoint and align the
        batcher's weight-version lineage to the primary tenant's
        namespaced version. Secondary checkpoints load through the
        hot-swap discipline: same architecture required (one warmed
        executable set serves all tenants — THE scale-to-zero
        property), cast to the serving dtype before staging so page-in
        is decode+upload, never a cast."""
        import dataclasses as _dc

        import jax.numpy as jnp

        from ..serving.weightpager import TenantScheduler, WeightPager

        pager = WeightPager(self._weight_pager_host_bytes)
        primary, primary_slo, primary_uri = self._tenant_spec[0]
        if primary_uri and primary_uri != self.model_uri:
            raise ValueError(
                f"primary tenant {primary!r} declares model uri "
                f"{primary_uri!r} but the server loads {self.model_uri!r} "
                "— the first tenant boots resident on the served model"
            )
        v0 = pager.put(primary, primary_params, primary_slo)
        pager.mark_resident(primary)
        dt = jnp.dtype(getattr(self._model, "compute_dtype", "bfloat16"))
        served_cfg = _dc.asdict(self._model.cfg)
        served_cfg.pop("residual_scale", None)
        for name, slo, uri in self._tenant_spec[1:]:
            server = JAXServer(uri or self.model_uri)
            _apply, params = server.build()
            other = server._model
            if other is None or not hasattr(other, "cfg"):
                raise ValueError(
                    f"tenant {name!r} checkpoint at {uri!r} is not an "
                    "llm-family model dir"
                )
            other_cfg = _dc.asdict(other.cfg)
            other_cfg.pop("residual_scale", None)
            if other_cfg != served_cfg:
                changed = sorted(
                    k for k in set(other_cfg) | set(served_cfg)
                    if other_cfg.get(k) != served_cfg.get(k)
                )
                raise ValueError(
                    f"tenant {name!r} checkpoint architecture differs "
                    f"from the served model ({', '.join(changed)}); "
                    "paged tenants share one executable set"
                )
            if dt != jnp.float32 and isinstance(params, dict):
                params = self._cast_params_freeing_impl(params, dt)
            pager.put(name, params, slo)
        b = self.batcher
        # lineage alignment BEFORE warm()/start(): caches are empty, so
        # adopting the namespaced version purges nothing, and the first
        # real page-in retains this tenant's slabs by namespace
        b.weight_version = v0
        if b._prefix_index is not None:
            b._prefix_index.set_version(v0)
        if b._kv_tier is not None:
            b._kv_tier.set_version(v0)
        b.tenant_pager = pager
        self.tenant_pager = pager
        self.tenant_scheduler = TenantScheduler(
            b, pager,
            {name: slo for name, slo, _uri in self._tenant_spec},
            tick_s=self._tenant_tick_ms / 1e3,
            max_wait_polls=self._tenant_max_wait_polls,
            min_resident_s=self._tenant_min_resident_ms / 1e3,
        )
        logger.info(
            "generateserver: multi-tenant paging over %d tenant(s), "
            "%d host-staging bytes, resident=%s",
            len(self._tenant_spec), self._weight_pager_host_bytes, primary,
        )

    # -- byte-level text fallback (no tokenizer shipped in-image) ----------

    def _encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def _decode(self, tokens: Iterable[int]) -> str:
        return bytes(t for t in tokens if 0 <= t < 256).decode("utf-8", "replace")

    def _parse_prompts(self, body: Dict[str, Any]):
        """ONE wire-schema parser for the unary and streaming paths:
        returns (token_lists, text_mode, sampling_kwargs)."""
        if "prompt" in body and "prompt_tokens" not in body:
            prompts = body["prompt"]
            prompts = [prompts] if isinstance(prompts, str) else list(prompts)
            token_lists = [self._encode(p) for p in prompts]
            text_mode = True
        else:
            pt = body.get("prompt_tokens")
            if not pt:
                raise ValueError("need prompt_tokens or prompt")
            token_lists = (
                [list(p) for p in pt] if isinstance(pt[0], (list, tuple)) else [list(pt)]
            )
            text_mode = False
        kw = dict(
            max_new_tokens=int(body.get("max_new_tokens", 32)),
            temperature=float(body.get("temperature", 0.0)),
            eos_id=body.get("eos_id"),
            seed=int(body.get("seed", 0)),
        )
        return token_lists, text_mode, kw

    # -- disaggregated serving (prefill/decode pools) ----------------------

    def _note_peer_event(self, kind: str, addr: str, reason: str = "") -> None:
        """Counter + flight-record hook for the failover transport's
        eject/readmit decisions — the observable half of the peer
        failover contract (seldon_engine_peer_ejections, ``peer_ejected``
        flight records)."""
        b = self.batcher
        if b is None:
            return
        key = "peer_ejections" if kind == "peer_ejected" else "peer_readmissions"
        with b._export_lock:
            b.stats[key] += 1
        if b.flight is not None and b.flight.enabled:
            rec = {"type": kind, "peer": addr}
            if reason:
                rec["reason"] = reason
            b.flight.record(rec)

    def _build_failover(self, peers):
        """Decode role: the peer LIST (comma-separated ``host:port``
        string, a single live server object, or a sequence of either)
        becomes one FailoverKVClient with this server's ejection
        telemetry and per-peer chaos faults wired in."""
        from ..serving.disagg import make_failover

        injector = self._faults
        return make_failover(
            peers,
            chunk_bytes=self._kv_chunk_bytes,
            fault_for=(
                injector.kv_faults_for if injector is not None else None
            ),
            eject_backoff_s=self._peer_eject_backoff_s,
            on_eject=lambda addr, reason: self._note_peer_event(
                "peer_ejected", addr, reason
            ),
            on_readmit=lambda addr: self._note_peer_event(
                "peer_readmitted", addr
            ),
        )

    def set_peer(self, prefill_server) -> None:
        """Wire a decode-role server to its prefill peer(s): a live
        GenerateServer/handler object (loopback transport — the slab
        still round-trips the full wire codec in memory), a
        ``host:port`` string (TCP; comma-separated for a list), or a
        sequence of either. Always wrapped in the failover layer, so
        single-peer and multi-peer decode pools share one ejection/
        degradation contract."""
        if self._role != "decode":
            raise RuntimeError(f"set_peer on a {self._role}-role server")
        self._kv_client = self._build_failover(prefill_server)

    def kv_ping(self) -> bool:
        """Loopback health probe target (the in-process twin of the TCP
        listener's ``{"ping": true}`` frame): True while this server's
        batcher can still serve prefill exports."""
        return self.batcher is not None and self.batcher.health == "serving"

    @caller_thread
    def prefill_export(self, request: Dict[str, Any]):
        """PREFILL-side transport handler: run the prompt forward and
        return ``(meta, slab)`` for the wire codec. Called by the
        loopback transport directly and by PrefillTransportServer per
        TCP connection. A ``prefix_lookup`` request is answered from
        the HOST KV TIER instead — no device work at all: the longest
        stored prefix's slab (CRC-verified on read) goes back over the
        same codec, or a typed :class:`~..serving.disagg.TierMiss`
        frame that the failover layer passes through without ejecting
        (a cold tier is not a dead pool)."""
        if self.batcher is None:
            self.load()
        if request.get("prefix_lookup"):
            return self._tier_lookup(request)
        toks = request.get("tokens")
        if not toks:
            raise ValueError("prefill request needs tokens")
        return self.batcher.export_prefill(
            [int(t) for t in toks],
            max_new_tokens=int(request.get("max_new_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            eos_id=request.get("eos_id"),
            seed=int(request.get("seed", 0)),
            covered_len=int(request.get("covered_len", 0)),
        )

    @caller_thread
    def _tier_lookup(self, request: Dict[str, Any]):
        """Answer a peer's prefix-lookup from the local host KV tier:
        ``(meta, slab)`` covering the ENTRY's full token path (the
        puller re-inserts it into its own radix index and lets the
        ordinary match serve the common depth). Runs on transport
        handler threads — the tier is host bytes under its own lock, so
        this never touches the device or the scheduler."""
        from ..serving.disagg import TierMiss

        b = self.batcher
        tier = b._kv_tier
        toks = [int(t) for t in request.get("tokens") or []]
        if tier is None or not toks:
            raise TierMiss("no host KV tier on this member")
        want_version = request.get("weight_version")
        if want_version != b.weight_version:
            raise TierMiss(
                f"tier serves weight_version {b.weight_version!r}, "
                f"peer asked for {want_version!r}"
            )
        # the SHARED usable-hit probe (ContinuousBatcher.tier_prefix_
        # lookup): the same promote-gate + donor-width/near-max caps the
        # puller applies locally run HERE, before the transfer is paid
        # (pool members share one model config, so bucket geometry
        # agrees) — a corrupt entry is dropped typed inside the probe
        # and answers a MISS frame, never a generic error that would
        # eject a healthy listener
        hit = b.tier_prefix_lookup(
            toks, min_tokens=int(request.get("min_tokens", 0))
        )
        if hit is None:
            raise TierMiss(
                "no usable stored prefix for this prompt (miss, below "
                "the promote gate, or not a win at this prompt's bucket)"
            )
        depth, meta, slab = hit
        with b._export_lock:
            # the peer-serving hit is a TIER hit on THIS member (its RAM
            # saved the peer a prefill); the puller counts the promotion
            b.stats["kv_tier_hits"] = tier.stats["hits"]
        if b.flight is not None and b.flight.enabled:
            from ..serving.disagg import prompt_hash

            b.flight.record({
                "type": "tier_hit", "kind": "prefix", "source": "peer",
                "tokens": depth,
                "phash": prompt_hash(meta.get("tokens") or [])[:8],
            })
        out_meta = {
            "kind": "tier_prefix",
            "tokens": meta.get("tokens"),
            "weight_version": b.weight_version,
            "tier_depth": depth,
        }
        return out_meta, slab

    @caller_thread
    def _peer_prefix_pull(self, toks, deadline_s) -> int:
        """Decode-role tier sharing: on a LOCAL radix miss, ask the
        prefill peers' host tiers for a shared prefix and promote the
        answer into the local radix index. Returns the new
        ``remote_covered_len`` (0 when nothing was pulled). Misses and
        transport trouble are non-events — the ordinary full-prefill
        path is always right behind."""
        from ..serving.disagg import DisaggError, TierMiss

        b = self.batcher
        try:
            meta, slab = self._kv_client.prefill({
                "prefix_lookup": True,
                "tokens": [int(t) for t in toks],
                "weight_version": b.weight_version,
                "min_tokens": b.tier_promote_gate,
            }, deadline_s=deadline_s)
        except TierMiss:
            return 0
        except DisaggError:
            # peer trouble is the failover layer's business (it already
            # ejected/rotated as needed); the lookup is opportunistic
            return 0
        if meta.get("weight_version") != b.weight_version:
            return 0
        b.promote_peer_prefix(meta, slab)
        return b.remote_covered_len(toks)

    @caller_thread
    def _remote_submit(self, toks, kw, deadline_s, covered=None,
                       on_tokens=None):
        """Decode-role submit: consult the local radix cache for the
        transfer-dedup base, pull the (suffix-only when possible) slab
        from the prefill pool under a ``gen.kv_transfer`` span, and
        queue it as a remote lane insert. With the ENTIRE prefill pool
        ejected, degrade gracefully to local unified prefill — the
        batcher owns the full prefill path and its warmed executables,
        so greedy output stays byte-identical while
        ``degraded_local_prefill`` makes the regression visible."""
        from ..serving.disagg import AllPeersDown
        from ..tracing import get_tracer

        if self._kv_client is None:
            raise RuntimeError(
                "decode role has no prefill peer (set `peer` or call "
                "set_peer())"
            )
        # bounds-check BEFORE the handoff: over the TCP transport a
        # prefill-side PromptTooLong/BudgetExceeded comes back as a
        # generic error frame the failover layer reads as peer death —
        # one unservable request must never eject healthy prefill peers
        from ..serving.continuous import PromptTooLong

        n = len(toks)
        if n >= self.batcher.max_seq:
            raise PromptTooLong(
                f"prompt of {n} exceeds max_seq {self.batcher.max_seq}"
            )
        self.batcher._check_budget(n, kw.get("max_new_tokens", 32))
        # shed BEFORE the handoff costs anything: an overloaded decode
        # pool must not amplify load onto the prefill pool and the wire
        # only to reject the slab on arrival (admit_remote re-checks,
        # but by then the transfer is paid). remote=True makes an
        # HBM-pressure refusal the typed PressureRefused (503 +
        # Retry-After) — the decode pool pushes back to its prefill
        # peers instead of half-admitting slabs.
        self.batcher._shed_check(deadline_s, remote=True)
        if covered is None:
            covered = self.batcher.remote_covered_len(toks)
            if covered == 0 and self.batcher._kv_tier is not None:
                # a demoted prefix in this member's OWN tier promotes
                # back before asking anyone else
                covered = self.batcher.consult_tier_covered_len(toks)
            if (
                covered == 0
                and self._kv_tier_peer_lookup
                and self.batcher._prefix_index is not None
                and len(toks) >= self.batcher.tier_promote_gate
            ):
                # cluster-wide prefix sharing: a local radix miss asks
                # the prefill peers' host tiers before paying a full
                # prefill + full-slab transfer (the pulled slab promotes
                # into the local radix index, so the suffix-only
                # request below dedups the wire bytes too)
                covered = self._peer_prefix_pull(toks, deadline_s)
        request = {
            "tokens": [int(t) for t in toks],
            "covered_len": int(covered),
            **kw,
        }
        try:
            with get_tracer().span(
                "gen.kv_transfer",
                tags={"covered_len": int(covered), "tokens": len(toks),
                      "transport": self._kv_client.name},
            ):
                meta, slab = self._kv_client.prefill(
                    request, deadline_s=deadline_s
                )
        except AllPeersDown as e:
            return self._local_prefill_fallback(
                toks, kw, deadline_s, on_tokens, str(e)
            )
        return self.batcher.admit_remote(
            slab, meta, on_tokens=on_tokens, deadline_s=deadline_s
        )

    @caller_thread
    def _local_prefill_fallback(self, toks, kw, deadline_s, on_tokens,
                                reason: str):
        """The whole prefill pool is ejected: serve the prompt with a
        LOCAL unified prefill instead of failing the request. Counted
        (``degraded_local_prefill``) and flight-recorded so the
        regression is visible on dashboards while the failover layer
        keeps probing the pool back in."""
        b = self.batcher
        with b._export_lock:
            b.stats["degraded_local_prefill"] += 1
        if b.flight is not None and b.flight.enabled:
            b.flight.record({
                "type": "degraded_local_prefill",
                "tokens": len(toks),
                "reason": reason,
            })
        logger.warning(
            "prefill pool fully ejected (%s); serving %d-token prompt "
            "with local unified prefill", reason, len(toks),
        )
        return b.submit(toks, deadline_s=deadline_s, on_tokens=on_tokens,
                        **kw)

    # -- live-lane migration (graceful drain + resume tokens) --------------

    @caller_thread
    def resume_checkpoint(self, ck, on_tokens=None):
        """Admit one generate checkpoint — an SGC1 dict, a base64 resume
        token, or raw SGC1 bytes — and continue the generation exactly
        where it stopped (byte-identical, spans never re-sent). The
        decode-side entry point of a drain handoff and of a client's
        crash-resume retry; the engine's ``POST /drain`` import mode
        lands here per checkpoint."""
        from ..serving.migration import decode_checkpoint, parse_token

        if self.batcher is None:
            self.load()
        if isinstance(ck, str):
            ck = parse_token(ck)
        elif isinstance(ck, (bytes, bytearray)):
            ck = decode_checkpoint(bytes(ck))
        return self.batcher.submit_checkpoint(ck, on_tokens=on_tokens)

    def _settle_migrated(self, req, peer_future) -> None:
        """Done-callback chaining a migrated request's peer future back
        into the ORIGINAL future the local client thread is waiting on:
        the connection that carried the request never sees the drain."""
        if req.future.done():
            return
        try:
            req.future.set_result(peer_future.result())
        except Exception as e:  # noqa: BLE001 - relay the typed failure
            req.future.set_exception(e)

    @caller_thread
    def drain_to(self, peer, timeout_s: float = 60.0) -> Dict[str, Any]:
        """Graceful drain: checkpoint every in-flight generation at a
        poll boundary (``ContinuousBatcher.drain`` — the member flips to
        the ``"draining"`` health state and refuses new work typed) and
        hand the checkpoints plus queued requests to ``peer``:

        * a live server object exposing ``resume_checkpoint`` —
          loopback: per-request futures chain back into the original
          waiters and streamed spans keep flowing through the original
          ``on_tokens`` consumer, so clients observe nothing;
        * a ``"host:port"`` string — the peer ENGINE's ``POST /drain``
          route over TCP (``serving.migration.post_drain``): the final
          token lists come back positionally, stream consumers get the
          post-checkpoint tail as one span (never a re-send).

        Every request completes byte-identical to an uninterrupted run
        (greedy and seeded sampling — the SGC1 checkpoint carries the
        exact post-split RNG lane key). Returns a summary dict; failed
        handoffs fail their original futures typed rather than hanging
        them."""
        from ..serving import migration

        if self.batcher is None:
            self.load()
        b = self.batcher
        drained = b.drain(timeout_s=timeout_s)
        cks = [migration.checkpoint_of(req, b.weight_version)
               for req in drained]
        for req in drained:
            # the work leaves this member (peer resume, or typed failure
            # below): its host-tier K/V checkpoint would otherwise pin
            # tier budget forever
            b._release_tier_ckpt(req)
        with b._export_lock:
            b.stats["checkpoint_exports"] += len(cks)
        if b.flight is not None and b.flight.enabled:
            for ck in cks:
                b.flight.record({
                    "type": "checkpoint_export",
                    "tokens": len(ck["prompt"]),
                    "emitted": len(ck["emitted"]),
                    "weight_version": b.weight_version,
                })
        handed = failed = 0
        if hasattr(peer, "resume_checkpoint"):
            for req, ck in zip(drained, cks):
                try:
                    pf = peer.resume_checkpoint(ck, on_tokens=req.on_tokens)
                except Exception as e:  # noqa: BLE001 - typed refusal
                    failed += 1
                    if not req.future.done():
                        req.future.set_exception(e)
                    continue
                handed += 1
                pf.add_done_callback(
                    lambda f, req=req: self._settle_migrated(req, f)
                )
        else:
            try:
                results = migration.post_drain(
                    str(peer), cks, timeout_s=timeout_s
                )
            except Exception as e:  # noqa: BLE001 - typed refusal
                for req in drained:
                    failed += 1
                    if not req.future.done():
                        req.future.set_exception(e)
                results = None
            if results is not None:
                for req, ck, res in zip(drained, cks, results):
                    handed += 1
                    if req.on_tokens is not None:
                        # the post-checkpoint tail as one span: spans at
                        # or before stream_pos were already delivered
                        tail = list(res)[
                            len(ck["prompt"]) + ck["stream_pos"]:
                        ]
                        if tail:
                            try:
                                req.on_tokens(tail)
                            except Exception:  # noqa: BLE001 - consumer bug
                                logger.exception("on_tokens relay failed")
                    if not req.future.done():
                        req.future.set_result(list(res))
        with b._export_lock:
            b.stats["migrations"] += handed
        if b.flight is not None and b.flight.enabled and handed:
            b.flight.record({
                "type": "migrated_resume",
                "peer": getattr(peer, "model_uri", None) or str(peer),
                "handed": handed,
            })
        logger.info(
            "drain_to: %d checkpoint(s) exported, %d handed to the "
            "peer, %d failed typed", len(cks), handed, failed,
        )
        return {
            "drained": len(drained),
            "checkpoints": len(cks),
            "handed": handed,
            "failed": failed,
        }

    def _make_resume_token(self, req, prompt, delivered, kw,
                           text_mode=False) -> str:
        """Opaque resume token for a live generation: the SGC1 payload
        over prompt + delivered-so-far, keyless (the resume side
        re-derives the lane key from seed + emitted count) so refreshing
        it per span costs zero device syncs. ``text_mode`` rides the
        checkpoint so a resumed strData stream keeps decoding ``text``
        fields."""
        import time as _time

        from ..serving.migration import checkpoint_token

        gr = getattr(req, "gen_request", None) or req
        now = _time.monotonic()
        return checkpoint_token({
            "v": 1,
            "prompt": [int(t) for t in prompt],
            "emitted": [int(t) for t in delivered],
            "rng_key": None,
            "text_mode": bool(text_mode),
            "max_new_tokens": int(kw.get("max_new_tokens", 32)),
            "temperature": float(kw.get("temperature", 0.0)),
            "eos_id": kw.get("eos_id"),
            "seed": int(kw.get("seed", 0)),
            "weight_version": self.batcher.weight_version,
            "wait_s": round(max(0.0, now - gr.submit_t), 6)
            if getattr(gr, "submit_t", 0.0) else 0.0,
            "submit_wall_us": int(getattr(gr, "submit_wall_us", 0) or 0),
            "deadline_s": (
                max(0.0, gr.deadline_t - now)
                if getattr(gr, "deadline_t", None) is not None else None
            ),
            "stream_pos": len(delivered),
        })

    @caller_thread
    def _collect_results(self, futures, token_lists, kw, deadline_s,
                         expires_at, retry_prefix_gone=False):
        """Await every request future under the remaining deadline budget
        — ONE implementation for the unified and decode-role paths so
        the deadline/cancellation semantics cannot drift apart.

        All-or-nothing: any failure (or budget exhaustion) cancels the
        sibling futures, reclaiming queued slots and mid-decode lanes,
        before the error surfaces. Waits never exceed the request's own
        budget (600s safety fallback without one) — an abandoned wait
        would pin this worker thread and its decode lane.
        ``retry_prefix_gone`` adds the decode-role contract: a
        suffix-only handoff whose radix donor was evicted before the
        splice re-requests the FULL slab once — correctness never
        depends on the cache."""
        import time as _time

        from ..resilience import DeadlineExceeded

        def remaining() -> float:
            if expires_at is None:
                return 600.0
            return max(0.001, expires_at - _time.monotonic())

        try:
            results = []
            for i, f in enumerate(futures):
                try:
                    results.append(f.result(timeout=remaining()))
                except Exception as e:
                    if retry_prefix_gone:
                        from ..serving.disagg import PrefixGone

                        if isinstance(e, PrefixGone):
                            f2 = self._remote_submit(
                                token_lists[i], kw, deadline_s, covered=0
                            )
                            futures[i] = f2
                            results.append(f2.result(timeout=remaining()))
                            continue
                    raise
        except FuturesTimeout:
            for f in futures:
                f.cancel()
            if deadline_s is None:
                raise  # the 600s safety fallback fired, not a budget
            raise DeadlineExceeded(
                f"generate ran past its {deadline_s * 1000:.0f}ms budget"
            )
        except Exception:
            for f in futures:
                f.cancel()
            raise
        return results

    @caller_thread
    def _predict_disagg(self, token_lists, kw, deadline_s, expires_at):
        """Decode-role submit loop: prefill at the peer pool, slab over
        the KV transport, then the shared all-or-nothing collection.

        Multi-prompt requests dispatch their transfers CONCURRENTLY —
        sequential round trips would make prompt N's TTFT pay N-1 whole
        prefill+transfer latencies, and the prefill listener's bounded
        handler pool exists precisely to serve them in parallel."""
        from concurrent.futures import ThreadPoolExecutor

        if len(token_lists) == 1:
            futures = [self._remote_submit(token_lists[0], kw, deadline_s)]
        else:
            with ThreadPoolExecutor(
                max_workers=min(8, len(token_lists)),
                thread_name_prefix="kv-transfer",
            ) as pool:
                submits = [
                    pool.submit(self._remote_submit, toks, kw, deadline_s)
                    for toks in token_lists
                ]
            # the with-block joined the pool: every transfer has finished,
            # one way or the other. All-or-nothing: any failure cancels
            # EVERY sibling whose slab landed (sweeping `submits`, not a
            # partial collection list, so no admitted lane can leak).
            err = next(
                (sf.exception() for sf in submits if sf.exception()), None
            )
            if err is not None:
                for sf in submits:
                    if sf.exception() is None:
                        sf.result().cancel()
                raise err
            # in submission order, so responses stay positional
            futures = [sf.result() for sf in submits]
        results = self._collect_results(
            futures, token_lists, kw, deadline_s, expires_at,
            retry_prefix_gone=True,
        )
        return futures, results

    def close(self) -> None:
        """Stop the KV transport endpoints and the scheduler."""
        if self.tenant_scheduler is not None:
            # before the batcher: the driver blocks on swap futures the
            # poll loop resolves, and stop() fails queued work typed
            self.tenant_scheduler.stop()
            self.tenant_scheduler = None
        if self._kv_server is not None:
            self._kv_server.close()
            self._kv_server = None
        if self._kv_client is not None:
            self._kv_client.close()
            self._kv_client = None
        if self.batcher is not None:
            self.batcher.close()

    @caller_thread
    def predict(self, X, names, meta=None):
        if self.batcher is None:
            self.load()
        if self._role == "prefill":
            raise RuntimeError(
                "this unit is a prefill-role pool member: it serves the "
                "KV transport only — route generate requests at the "
                "decode pool"
            )
        body = X if isinstance(X, dict) else None
        if body is None:
            if isinstance(X, str):
                body = {"prompt": X}
            else:
                raise ValueError(
                    "generate expects jsonData {prompt_tokens|prompt, ...} or strData"
                )
        # remaining deadline budget rides the request meta (stamped per
        # hop by the graph executor): the batcher sheds the submit when
        # its admit queue cannot meet it (ShedError -> engine 429)
        from ..resilience import deadline_s_from_meta

        deadline_s = deadline_s_from_meta(meta)
        import time as _time

        expires_at = (
            _time.monotonic() + deadline_s if deadline_s is not None else None
        )
        if body.get("resume_token"):
            # crash-resume retry: the opaque SGC1 token continues the
            # generation exactly where the dead member stopped —
            # byte-identical, wait telemetry cumulative
            from ..serving.migration import parse_token

            ck = parse_token(str(body["resume_token"]))
            fut = self.batcher.submit_checkpoint(ck)
            gr = getattr(fut, "gen_request", None)
            prompt = list(gr.tokens) if gr is not None else []
            results = self._collect_results(
                [fut], [prompt], {}, deadline_s, expires_at
            )
            out: Dict[str, Any] = {"tokens": results}
            if ck.get("text_mode"):
                out["text"] = [self._decode(results[0][len(prompt):])]
            if self._resume_tokens and gr is not None:
                out["resume_tokens"] = [self._make_resume_token(
                    fut, prompt, results[0][len(prompt):],
                    {"max_new_tokens": gr.max_new_tokens,
                     "temperature": gr.temperature,
                     "eos_id": gr.eos_id, "seed": gr.seed},
                    text_mode=bool(ck.get("text_mode")),
                )]
            return out
        token_lists, text_mode, kw = self._parse_prompts(body)
        if self._role == "decode":
            # disaggregated path: prefill happens at the peer pool, the
            # slab crosses the KV transport, decode runs here
            futures, results = self._predict_disagg(
                token_lists, kw, deadline_s, expires_at
            )
            return self._build_response(
                futures, results, token_lists, text_mode, kw=kw
            )
        submit = self.batcher.submit
        skw = dict(kw)
        if self.tenant_scheduler is not None:
            # multi-tenant routing: the scheduler passes the resident
            # tenant's work straight through and queues everyone else
            # for a page-in; the id arrives in the message meta (engine
            # stamps the Seldon-Tenant header) or the body (direct use)
            from ..serving.weightpager import tenant_from_meta

            submit = self.tenant_scheduler.submit
            skw["tenant"] = body.get("tenant") or tenant_from_meta(meta)
        futures = []
        try:
            for toks in token_lists:
                futures.append(
                    submit(toks, deadline_s=deadline_s, **skw)
                )
        except Exception:
            # a multi-prompt request is all-or-nothing: whatever failed a
            # later submit (shed 429, over-long prompt 400, closed
            # batcher), cancel the prompts already queued so the error
            # never leaves orphaned device work decoding for a response
            # nobody will collect
            for f in futures:
                f.cancel()
            raise
        results = self._collect_results(
            futures, token_lists, kw, deadline_s, expires_at
        )
        return self._build_response(
            futures, results, token_lists, text_mode, kw=kw
        )

    def _build_response(self, futures, results, token_lists, text_mode,
                        kw=None):
        out: Dict[str, Any] = {"tokens": results}
        if text_mode:
            out["text"] = [
                self._decode(r[len(p):]) for r, p in zip(results, token_lists)
            ]
        if self._resume_tokens and kw is not None:
            out["resume_tokens"] = [
                self._make_resume_token(f, p, r[len(p):], kw,
                                        text_mode=text_mode)
                for f, r, p in zip(futures, results, token_lists)
            ]
        if self.batcher._prefix_index is not None:
            # per-request prompt tokens served from the prefix cache, in
            # request order — graph nodes and the engine report it. For a
            # decode pool the hit doubles as the transfer-dedup count:
            # those tokens' K/V never crossed the wire
            out["cache_hit_tokens"] = [
                int(getattr(getattr(f, "gen_request", None),
                            "cache_hit_tokens", 0))
                for f in futures
            ]
        return out

    @caller_thread
    def stream(self, body: Dict[str, Any]) -> "StreamHandle":
        """Streaming generate: validates and SUBMITS eagerly (malformed
        bodies and closed batchers raise HERE, before any response bytes
        exist), then returns a :class:`StreamHandle` whose ``chunks``
        iterator yields ``{"tokens": [...]}`` per credited span and a
        final ``{"done": true, "tokens": [prompt+generated]}``.
        ``handle.cancel()`` (client disconnect) releases the decode lane.
        One prompt per stream; batch prompts belong to unary predict."""
        import queue as _queue

        if self.batcher is None:
            self.load()
        if self._role == "prefill":
            raise RuntimeError(
                "prefill-role pool members serve the KV transport only"
            )
        q: "_queue.Queue" = _queue.Queue()
        if body.get("resume_token"):
            # crash-resume of an interrupted stream: continue from the
            # token's checkpoint — only NEW spans are yielded (crediting
            # resumes after the checkpoint), so no span is ever re-sent
            from ..serving.migration import parse_token

            ck = parse_token(str(body["resume_token"]))
            text_mode = bool(ck.get("text_mode"))
            toks = [int(t) for t in ck["prompt"]]
            kw = dict(
                max_new_tokens=int(ck.get("max_new_tokens", 32)),
                temperature=float(ck.get("temperature", 0.0)),
                eos_id=ck.get("eos_id"),
                seed=int(ck.get("seed", 0)),
            )
            resume_base = [int(t) for t in ck.get("emitted") or []]
            fut = self.batcher.submit_checkpoint(ck, on_tokens=q.put)
        else:
            token_lists, text_mode, kw = self._parse_prompts(body)
            if len(token_lists) != 1:
                raise ValueError("stream takes ONE prompt")
            toks = token_lists[0]
            resume_base = []
            if self._role == "decode":
                # streamed disaggregated generate: the slab handoff
                # happens before the first byte goes out, then tokens
                # stream as spans land exactly like the unary path.
                # Always the FULL slab (covered=0): the unary path's
                # PrefixGone retry cannot be replayed once response
                # bytes exist, so streaming trades the transfer dedup
                # for a handoff that can never lose its donor mid-stream
                fut = self._remote_submit(toks, kw, None, covered=0,
                                          on_tokens=q.put)
            elif self.tenant_scheduler is not None:
                from ..serving.weightpager import tenant_from_meta

                fut = self.tenant_scheduler.submit(
                    toks, tenant=body.get("tenant")
                    or tenant_from_meta(body.get("meta")),
                    on_tokens=q.put, **kw,
                )
            else:
                fut = self.batcher.submit(toks, on_tokens=q.put, **kw)
        fut.add_done_callback(lambda _f: q.put(None))

        def chunks():
            # delivered-so-far accumulator: the per-span resume token is
            # the SGC1 checkpoint over prompt + delivered (keyless — the
            # resume side re-derives the lane key), refreshed per span
            delivered = list(resume_base)
            while True:
                item = q.get()
                if item is None:
                    break
                delivered.extend(int(t) for t in item)
                chunk: Dict[str, Any] = {"tokens": item}
                if text_mode:
                    chunk["text"] = self._decode(item)
                if self._resume_tokens:
                    chunk["resume_token"] = self._make_resume_token(
                        fut, toks, delivered, kw, text_mode=text_mode
                    )
                yield chunk
            result = fut.result(timeout=600.0)
            final: Dict[str, Any] = {"done": True, "tokens": result}
            if text_mode:
                final["text"] = self._decode(result[len(toks):])
            if self.batcher._prefix_index is not None:
                final["cache_hit_tokens"] = int(
                    getattr(getattr(fut, "gen_request", None),
                            "cache_hit_tokens", 0)
                )
            yield final

        return StreamHandle(chunks=chunks(), cancel=fut.cancel)

    @caller_thread
    def hot_swap(self, model_uri: str, wait_s: float = 30.0) -> Dict[str, Any]:
        """Live weight hot-swap: load a new checkpoint and replace the
        served weights WITHOUT restarting the process or dropping a
        request (the progressive-delivery path — the engine's
        ``/weights/swap`` route lands here).

        The new checkpoint must be the SAME architecture (the decode
        executables are shape-specialized); its params are cast to the
        serving dtype and handed to the batcher's double-buffered
        ``request_weight_swap`` — new-weight upload overlaps old-weight
        serving, in-flight lanes finish on the old version, the flip
        happens at a scheduler poll boundary, and the prefix cache is
        re-keyed so old-weights K/V can never serve a new-weights
        prefill. Waits up to ``wait_s`` for the flip; a swap still
        draining after that returns ``swapped: false`` and lands on its
        own."""
        if self.batcher is None:
            self.load()
        if self.batcher.swap_pending():
            # fail BEFORE the checkpoint load: the conflict is knowable
            # now, and a large model's read+cast+upload takes minutes
            raise RuntimeError("a weight swap is already pending")
        server = JAXServer(model_uri)
        _apply, params = server.build()
        new_model = server._model
        if new_model is None or not hasattr(new_model, "cfg"):
            raise ValueError(
                f"hot-swap checkpoint at {model_uri!r} is not an llm-family "
                "model dir"
            )
        old_cfg = dataclasses.asdict(self._model.cfg)
        new_cfg = dataclasses.asdict(new_model.cfg)
        # residual_scale only shapes synthetic INIT draws, not the forward
        for skip in ("residual_scale",):
            old_cfg.pop(skip, None)
            new_cfg.pop(skip, None)
        if old_cfg != new_cfg:
            changed = sorted(
                k for k in set(old_cfg) | set(new_cfg)
                if old_cfg.get(k) != new_cfg.get(k)
            )
            raise ValueError(
                f"hot-swap checkpoint architecture differs from the served "
                f"model ({', '.join(changed)}); same-shape checkpoints only"
            )
        import jax.numpy as jnp

        dt = jnp.dtype(getattr(self._model, "compute_dtype", "bfloat16"))
        if dt != jnp.float32 and isinstance(params, dict):
            params = self._cast_params_freeing_impl(params, dt)
        self._swap_count += 1
        version = f"v{self._swap_count}"
        fut = self.batcher.request_weight_swap(params, version=version)
        swapped = True
        try:
            fut.result(timeout=max(0.001, float(wait_s)))
        except FuturesTimeout:
            swapped = False  # still draining; the flip lands on its own
        return {
            "version": version,
            "swapped": swapped,
            "model_uri": model_uri,
            "weight_version": self.batcher.weight_version,
        }

    def cancel_hot_swap(self) -> Dict[str, Any]:
        """Abort a staged swap whose drain isn't converging (admissions
        resume on the next poll); see ContinuousBatcher.cancel_weight_swap."""
        cancelled = (
            self.batcher.cancel_weight_swap()
            if self.batcher is not None else False
        )
        return {
            "cancelled": cancelled,
            "weight_version":
                self.batcher.weight_version if self.batcher else None,
        }

    def retune(self, knobs: Dict[str, Any], origin: str = "planner",
               wait_s: float = 10.0) -> Dict[str, Any]:
        """Actuate a live scheduler retune through the safe path (the
        engine's ``POST /retune`` route and the reconciler's planner
        tick both land here): stage via ContinuousBatcher.retune() —
        synchronous typed validation against the boot compile census —
        then wait for the scheduler to apply it at a poll boundary.
        Returns ``{"changed": {knob: [old, new]}, "census": {...}}``;
        RetuneError propagates to the caller (the route maps it to a
        409-class refusal, the same contract as out-of-census configs)."""
        from ..serving.continuous import RetuneError

        if self.batcher is None:
            raise RuntimeError("retune before load(): no batcher")
        if not isinstance(knobs, dict):
            raise RetuneError(
                f"knobs must be an object, got {type(knobs).__name__}"
            )
        fut = self.batcher.retune(origin=str(origin), **knobs)
        changed = fut.result(timeout=wait_s)
        return {
            "changed": changed,
            "census": self.batcher.retune_census(),
            "origin": str(origin),
        }

    def retune_census(self) -> Optional[Dict[str, Any]]:
        """The loaded batcher's boot compile census (None before load)
        — the planner prunes its profile walk to in-census configs."""
        return (
            self.batcher.retune_census()
            if self.batcher is not None else None
        )

    def serving_config(self) -> Optional[Dict[str, Any]]:
        """The batcher's CURRENT profile-axis knob values (None before
        load) — ships in the /fleet payload so the reconciler's planner
        tick can diff the cost model's pick against what is serving."""
        return (
            self.batcher.serving_config()
            if self.batcher is not None else None
        )

    def tags(self) -> Dict:
        return {"server": "generateserver"}

    def health_status(self):
        """Readiness hook (InProcessClient.ready -> GraphExecutor.ready
        -> the engine's /ready): a batcher that is mid-crash-restart or
        latched dead flips this unit — and with it the engine — unready,
        so the gateway routes around the member and, once the crash-loop
        budget is exhausted, the reconciler replaces it. A server that
        has not loaded yet keeps the default lenient readiness."""
        b = self.batcher
        if b is not None and b.health != "serving":
            raise RuntimeError(f"continuous batcher is {b.health}")
        return "ok"

    def flight_dump(self, limit: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Scheduler flight-recorder export (the ``/flightrecorder`` route's
        payload): the per-poll decision ring plus the SLO reservoir summary
        and a scheduler-stat snapshot, so one dump is enough to attribute a
        tail-latency regression. None when the recorder is off/not loaded."""
        if self.batcher is None or self.batcher.flight is None:
            return None
        if self.batcher._kv_tier is not None:
            self.batcher.sync_kv_tier_stats()
        out = self.batcher.flight.dump(limit)
        out["slo"] = self.batcher.slo_summary()
        out["stats"] = {k: v for k, v in self.batcher.stats.items()}
        out["weight_version"] = self.batcher.weight_version
        pressure = self.batcher.pressure_summary()
        if pressure is not None:
            out["pressure"] = pressure
        tier = self.batcher.kv_tier_summary()
        if tier is not None:
            out["kv_tier"] = tier
        if self.tenant_pager is not None:
            out["weight_pager"] = self.tenant_pager.summary()
        if self.tenant_scheduler is not None:
            out["tenant_scheduler"] = self.tenant_scheduler.summary()
        if self.profiler is not None and self.profiler.enabled:
            out["profiler"] = self.profiler.summary()
        if self.slo_burn is not None:
            out["slo_burn"] = self.slo_burn.summary()
        return out

    def metrics(self) -> List[Dict]:
        """Meta.metrics hook: every cumulative scheduler total ships as a
        COUNTER **delta** through one CounterDeltas instance (the engine
        sink sums counter values per response — see metrics.CounterDeltas
        for the contract), SLO samples ship as per-completion TIMERs the
        engine folds into TTFT/TPOT/queue-wait histograms, and only true
        levels (cache bytes, occupancy, acceptance) ship as GAUGEs."""
        if self.batcher is None:
            return []
        s = self.batcher.stats
        delta = self._deltas.counter
        out = [
            delta("gen_tokens", s["tokens"]),
            delta("gen_steps", s["steps"]),
            delta("gen_finished", s["finished"]),
            delta("gen_admitted", s["admitted"]),
            # prefill-vs-decode split: per-node cache wins show up as
            # prefill step/token counters flattening while decode keeps pace
            delta("gen_prefill_steps", s["prefill_steps"]),
            delta("gen_prefill_tokens", s["prefill_tokens"]),
            delta("gen_decode_steps", s["steps"]),
            # per-burst modeled HBM read traffic (params + bucketed KV per
            # dispatched (sub)burst) — the depth-grouping win shows up as
            # read bytes per decoded token dropping at mixed depths
            delta("gen_burst_reads", s["burst_reads"]),
            delta("gen_burst_read_bytes", s["burst_read_bytes"]),
        ]
        if s.get("prefill_chunks"):
            out.append(delta("gen_prefill_chunks", s["prefill_chunks"]))
        if s.get("fused_dispatches"):
            # fused multi-step decode: device steps per dispatched fused
            # burst — engine_metrics maps these to the first-class
            # seldon_engine_fused_{steps,dispatches} series; their ratio
            # is the realized K (the dispatch-floor win)
            out.extend([
                delta("gen_fused_steps", s["fused_steps"]),
                delta("gen_fused_dispatches", s["fused_dispatches"]),
            ])
        if s.get("group_bursts"):
            out.extend([
                delta("gen_group_bursts", s["group_bursts"]),
                delta("gen_group_lanes", s["group_lanes"]),
                {
                    "type": "GAUGE", "key": "gen_group_occupancy",
                    # real lanes / gathered rows across grouped sub-bursts:
                    # the pow2 pad overhead the cost model is trading away
                    "value": round(
                        s["group_lanes"]
                        / max(1, s["group_lanes"] + s["group_pad_lanes"]),
                        4,
                    ),
                },
            ])
        if s.get("shed"):
            out.append(delta("gen_shed_total", s["shed"]))
        if s.get("weight_swaps"):
            out.append(delta("gen_weight_swaps", s["weight_swaps"]))
        if s.get("planner_retunes"):
            # autonomic planner actuations that landed at a poll
            # boundary — engine_metrics maps this to the first-class
            # seldon_engine_planner_retunes series (rate > a few per
            # minute = the planner is thrashing; flight_report renders
            # the matching planner_retune records with a DIAGNOSIS)
            out.append(delta("gen_planner_retunes", s["planner_retunes"]))
        # fault-tolerance counters + the first-class health gauge: the
        # engine sink maps the counters to seldon_engine_batcher_restarts
        # / _peer_ejections / _degraded_local_prefill (engine_metrics
        # _RECOVERY) so a chaotic run is diagnosable off /metrics alone
        out.append({
            "type": "GAUGE", "key": "gen_batcher_healthy",
            "value": 1.0 if self.batcher.health == "serving" else 0.0,
        })
        if self.batcher.mesh is not None:
            # sharded serving: mesh shape + the per-chip footprint levels
            # (engine_metrics maps these to the first-class
            # seldon_engine_mesh_* gauges) — param_shard_bytes vs the
            # global param bytes is the >1-chip-model headroom proof
            mshape = dict(self.batcher.mesh.shape)
            out.extend([
                {"type": "GAUGE", "key": "gen_mesh_devices",
                 "value": float(self.batcher.mesh.devices.size)},
                {"type": "GAUGE", "key": "gen_mesh_data",
                 "value": float(mshape.get("data", 1))},
                {"type": "GAUGE", "key": "gen_mesh_model",
                 "value": float(mshape.get("model", 1))},
                {"type": "GAUGE", "key": "gen_mesh_param_shard_bytes",
                 "value": float(self.batcher._param_shard_bytes)},
                {"type": "GAUGE", "key": "gen_mesh_kv_shard",
                 "value": float(self.batcher._kv_shard)},
            ])
        if s.get("batcher_restarts"):
            out.append(delta("gen_batcher_restarts", s["batcher_restarts"]))
        if s.get("peer_ejections"):
            out.append(delta("gen_peer_ejections", s["peer_ejections"]))
        if s.get("peer_readmissions"):
            out.append(delta("gen_peer_readmissions",
                             s["peer_readmissions"]))
        if s.get("degraded_local_prefill"):
            out.append(delta("gen_degraded_local_prefill",
                             s["degraded_local_prefill"]))
        # live migration: graceful drains, checkpoints exported/handed
        # to a peer, resumes admitted from wire checkpoints or resume
        # tokens, and hot-swap straggler preemptions — engine_metrics
        # maps these to seldon_engine_drains_total /
        # seldon_engine_migrations_total and friends
        if s.get("drains"):
            out.append(delta("gen_drains", s["drains"]))
        if s.get("checkpoint_exports"):
            out.append(delta("gen_checkpoint_exports",
                             s["checkpoint_exports"]))
        if s.get("migrations"):
            out.append(delta("gen_migrations", s["migrations"]))
        if s.get("migrated_resumes"):
            out.append(delta("gen_migrated_resumes",
                             s["migrated_resumes"]))
        if s.get("swap_preemptions"):
            out.append(delta("gen_swap_preemptions",
                             s["swap_preemptions"]))
        # HBM pressure: preemption/resume/shed counters plus the ledger
        # gauges — engine_metrics maps them to the first-class
        # seldon_engine_pressure_* / seldon_engine_preemptions series so
        # an overload window is diagnosable straight off /metrics
        if s.get("preemptions"):
            out.append(delta("gen_preemptions", s["preemptions"]))
        if s.get("preempt_resumes"):
            out.append(delta("gen_preempt_resumes", s["preempt_resumes"]))
        if s.get("pressure_sheds"):
            out.append(delta("gen_pressure_sheds", s["pressure_sheds"]))
        if s.get("pressure_refused"):
            out.append(delta("gen_pressure_refused", s["pressure_refused"]))
        if s.get("pressure_prefix_evictions"):
            out.append(delta("gen_pressure_prefix_evictions",
                             s["pressure_prefix_evictions"]))
        # tiered KV memory: demote/promote/hit/evict counters plus the
        # tier's live byte level — engine_metrics maps them to the
        # first-class seldon_engine_kv_tier_* series (host RAM, NOT the
        # HBM pressure gauges)
        if self.batcher._kv_tier is not None:
            self.batcher.sync_kv_tier_stats()
            out.extend([
                delta("gen_kv_tier_demotions", s["kv_tier_demotions"]),
                delta("gen_kv_tier_promotions", s["kv_tier_promotions"]),
                delta("gen_kv_tier_hits", s["kv_tier_hits"]),
                delta("gen_kv_tier_evictions", s["kv_tier_evictions"]),
                delta("gen_kv_tier_replay_fallbacks",
                      s["kv_tier_replay_fallbacks"]),
                {"type": "GAUGE", "key": "gen_kv_tier_bytes",
                 "value": float(s["kv_tier_bytes"])},
            ])
        pressure = self.batcher.pressure_summary()
        if pressure is not None:
            out.extend([
                {"type": "GAUGE", "key": "gen_pressure_used_bytes",
                 "value": float(pressure["used_bytes"])},
                {"type": "GAUGE", "key": "gen_pressure_budget_bytes",
                 "value": float(pressure["budget_bytes"])},
                {"type": "GAUGE", "key": "gen_pressure_active",
                 "value": 1.0 if pressure["active"] else 0.0},
            ])
        if s.get("kv_exports") or s.get("kv_imports"):
            # disaggregated serving: slab/byte counters per direction plus
            # the transfer-dedup savings — engine_metrics maps these to
            # the first-class seldon_engine_kv_transfer_* series
            out.extend([
                delta("gen_kv_export_slabs", s["kv_exports"]),
                delta("gen_kv_export_bytes", s["kv_export_bytes"]),
                delta("gen_kv_import_slabs", s["kv_imports"]),
                delta("gen_kv_import_bytes", s["kv_import_bytes"]),
                delta("gen_kv_transfer_bytes_saved",
                      s["kv_transfer_bytes_saved"]),
            ])
        if self.batcher._prefix_index is not None:
            out.extend([
                delta("prefix_cache_hits", s["prefix_hits"]),
                delta("prefix_cache_misses", s["prefix_misses"]),
                delta("prefix_cache_evictions", s["prefix_evicted"]),
                delta("prefix_tokens_saved", s["prefix_tokens_saved"]),
                {"type": "GAUGE", "key": "prefix_cache_bytes",
                 "value": float(s["prefix_cache_bytes"])},
            ])
        if s.get("spec_rounds"):
            out.append(
                {
                    "type": "GAUGE",
                    "key": "gen_spec_tokens_per_round",
                    # 1.0 = nothing accepted, gamma+1 = every draft accepted
                    "value": round(s["spec_emitted"] / s["spec_rounds"], 4),
                }
            )
        # SLO samples: one TIMER triple per request completed since the
        # last export (drained, bounded by the pending ring). The engine
        # sink turns TIMER ms into seconds histograms per graph node —
        # TTFT/TPOT/queue-wait become first-class series there
        # (engine_metrics._SLO_TIMERS).
        pending = self.batcher.slo_pending
        while pending:
            try:
                queue_wait, ttft, tpot = pending.popleft()
            except IndexError:  # raced another exporter thread
                break
            out.append({"type": "TIMER", "key": "gen_queue_wait_ms",
                        "value": round(queue_wait * 1e3, 4)})
            out.append({"type": "TIMER", "key": "gen_ttft_ms",
                        "value": round(ttft * 1e3, 4)})
            if tpot is not None:
                out.append({"type": "TIMER", "key": "gen_tpot_ms",
                            "value": round(tpot * 1e3, 4)})
            if self.slo_burn is not None:
                # the burn engine rides the SAME drain: one sample feed,
                # two consumers (histograms + error budgets)
                self.slo_burn.observe("queue_wait", queue_wait)
                self.slo_burn.observe("ttft", ttft)
                self.slo_burn.observe("tpot", tpot)
        if self.tenant_pager is not None:
            # multi-tenant serving: pager counters/levels plus PER-TENANT
            # request counters and SLO timer triples, each tagged with
            # its tenant id — engine_metrics maps them to the
            # seldon_engine_tenant_* / seldon_engine_weight_pager_*
            # series, and the tag becomes a label so one /metrics scrape
            # separates every tenant's histograms
            p = self.tenant_pager.stats
            out.extend([
                delta("gen_weight_page_ins", p["page_ins"]),
                delta("gen_weight_page_outs", p["page_outs"]),
                delta("gen_weight_pager_evictions", p["evictions"]),
                delta("gen_weight_pager_refused", p["refused"]),
                {"type": "GAUGE", "key": "gen_weight_pager_host_bytes",
                 "value": float(self.tenant_pager.host_bytes)},
                {"type": "GAUGE", "key": "gen_weight_pager_resident_bytes",
                 "value": float(self.tenant_pager.resident_hbm_bytes)},
                {"type": "GAUGE", "key": "gen_tenants_registered",
                 "value": float(len(self.tenant_pager.tenants()))},
            ])
            if self.tenant_scheduler is not None:
                out.append(delta(
                    "gen_tenant_switches",
                    self.tenant_scheduler.stats["switches"],
                ))
            for t, sums in list(self.batcher.tenant_slo.items()):
                out.append(delta("gen_tenant_requests", sums["finished"],
                                 tags={"tenant": t}))
            for t, tp in list(self.batcher.tenant_slo_pending.items()):
                while tp:
                    try:
                        queue_wait, ttft, tpot = tp.popleft()
                    except IndexError:  # raced another exporter thread
                        break
                    if self.slo_burn is not None:
                        self.slo_burn.observe("queue_wait", queue_wait, t)
                        self.slo_burn.observe("ttft", ttft, t)
                        self.slo_burn.observe("tpot", tpot, t)
                    tags = {"tenant": t}
                    out.append({"type": "TIMER",
                                "key": "gen_tenant_queue_wait_ms",
                                "value": round(queue_wait * 1e3, 4),
                                "tags": tags})
                    out.append({"type": "TIMER", "key": "gen_tenant_ttft_ms",
                                "value": round(ttft * 1e3, 4),
                                "tags": tags})
                    if tpot is not None:
                        out.append({"type": "TIMER",
                                    "key": "gen_tenant_tpot_ms",
                                    "value": round(tpot * 1e3, 4),
                                    "tags": tags})
        if self.profiler is not None and self.profiler.enabled:
            # device-time ledger: cumulative per-(kind, variant, tenant)
            # buckets ship as COUNTER deltas — engine_metrics maps them
            # to the seldon_engine_device_* series with the attribution
            # as labels — plus the live gauges the sliding window backs
            for (kind, variant, tenant), (secs, n, nbytes, _toks) in sorted(
                self.profiler.buckets().items()
            ):
                tags = {"kind": kind, "variant": variant}
                if tenant:
                    tags["tenant"] = tenant
                out.append(delta(
                    "gen_device_time_ms",
                    round(secs * 1e3, 3), tags=tags,
                ))
                out.append(delta("gen_device_dispatches", n, tags=tags))
                out.append(delta("gen_device_bytes", nbytes, tags=tags))
            live = self.profiler.gauges()
            for key, name in (("device_busy_frac", "gen_device_busy_frac"),
                              ("mbu_pct", "gen_mbu_pct"),
                              ("dispatch_floor_pct",
                               "gen_dispatch_floor_pct")):
                val = live.get(key)
                if val is not None:
                    out.append({"type": "GAUGE", "key": name,
                                "value": float(val)})
        if self.slo_burn is not None:
            # burn-rate verdicts: per-(tenant, slo) gauges + a severity
            # counter — the fleet scrape and the reconciler's scale
            # signals read the same feed via slo_verdicts()
            for v in self.slo_burn.verdicts():
                tags = {"slo": v["slo"], "window": "fast"}
                if v["tenant"]:
                    tags["tenant"] = v["tenant"]
                out.append({"type": "GAUGE", "key": "gen_slo_burn_rate",
                            "value": v["fast_burn"], "tags": dict(tags)})
                tags["window"] = "slow"
                out.append({"type": "GAUGE", "key": "gen_slo_burn_rate",
                            "value": v["slow_burn"], "tags": dict(tags)})
                del tags["window"]
                out.append({"type": "GAUGE",
                            "key": "gen_slo_budget_remaining",
                            "value": v["budget_remaining"],
                            "tags": dict(tags)})
            for (t, slo, sev), n in sorted(
                self.slo_burn.verdict_counts().items()
            ):
                tags = {"slo": slo, "severity": sev}
                if t:
                    tags["tenant"] = t
                out.append(delta("gen_slo_verdicts", n, tags=tags))
        return out
