"""Prepackaged model servers.

Counterparts of the reference's servers/ tree (reference:
servers/sklearnserver/sklearnserver/SKLearnServer.py:15-43,
servers/xgboostserver/xgboostserver/XGBoostServer.py,
servers/mlflowserver/mlflowserver/MLFlowServer.py,
integrations/tfserving/TfServingProxy.py:21-60) plus the TPU-native
JAXServer (new — BASELINE.json north star: serve SavedModel/flax
checkpoints as jit-compiled XLA executables on TPU).

SDKs not present in this image (xgboost, mlflow, tensorflow-serving)
are import-gated: the server class exists, raises a clear error on load.
"""
