"""Triton / TensorRT Inference Server proxy.

Parity with reference: integrations/nvidia-inference-server/TRTProxy.py:1-40
— a SeldonComponent that bridges graph traffic to an external inference
server, negotiating the model's input dtype/shape from its model config.
Rebuilt against Triton's current KServe-v2 HTTP protocol (the reference
spoke the 2019 TRTIS API); the transport is injectable so the bridge logic
is fully testable without a Triton container.

Parameters: ``url`` (http://host:8000), ``model_name``, ``model_version``.
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..user_model import SeldonComponent

logger = logging.getLogger(__name__)

# numpy dtype name -> KServe v2 datatype
V2_DTYPES = {
    "bool": "BOOL",
    "uint8": "UINT8",
    "uint16": "UINT16",
    "uint32": "UINT32",
    "uint64": "UINT64",
    "int8": "INT8",
    "int16": "INT16",
    "int32": "INT32",
    "int64": "INT64",
    "float16": "FP16",
    "float32": "FP32",
    "float64": "FP64",
}
NP_DTYPES = {v: k for k, v in V2_DTYPES.items()}


def _http_transport(url: str, body: Optional[bytes], timeout: float) -> Dict:
    req = urllib.request.Request(
        url, data=body,
        headers={"Content-Type": "application/json"} if body else {},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class TRTServer(SeldonComponent):
    """Bridge: SeldonMessage tensors in, KServe-v2 infer call out.

    ``transport(url, body_bytes_or_None, timeout) -> dict`` is injectable
    for tests; default is plain HTTP.
    """

    def __init__(
        self,
        model_uri: str = "",
        url: str = "http://localhost:8000",
        model_name: str = "",
        model_version: str = "",
        timeout_s: float = 10.0,
        transport: Callable[[str, Optional[bytes], float], Dict] = _http_transport,
        **kwargs,
    ):
        self.url = url.rstrip("/")
        self.model_name = model_name or model_uri.rsplit("/", 1)[-1] or "model"
        self.model_version = str(model_version) if model_version else ""
        self.timeout_s = float(timeout_s)
        self.transport = transport
        self._meta: Optional[Dict] = None

    def _model_path(self) -> str:
        base = f"{self.url}/v2/models/{self.model_name}"
        if self.model_version:
            base += f"/versions/{self.model_version}"
        return base

    def load(self) -> None:
        """Dtype/shape negotiation from the server's model metadata
        (reference parse_model, TRTProxy.py:1-40)."""
        self._meta = self.transport(self._model_path(), None, self.timeout_s)
        logger.info(
            "trtserver: model %s inputs=%s",
            self.model_name, [i.get("name") for i in self._meta.get("inputs", [])],
        )

    def _input_spec(self) -> Dict:
        if self._meta is None:
            self.load()
        inputs = self._meta.get("inputs") or []
        if not inputs:
            raise RuntimeError(f"model {self.model_name} reports no inputs")
        return inputs[0]

    def predict(self, X, names, meta=None):
        spec = self._input_spec()
        arr = np.asarray(X)
        v2_dtype = spec.get("datatype", "FP32")
        np_dtype = NP_DTYPES.get(v2_dtype)
        if np_dtype is None:
            raise RuntimeError(
                f"model {self.model_name} input datatype {v2_dtype!r} is not a "
                f"numeric KServe-v2 type this bridge supports ({sorted(NP_DTYPES)})"
            )
        arr = arr.astype(np_dtype, copy=False)
        body = json.dumps(
            {
                "inputs": [
                    {
                        "name": spec.get("name", "input"),
                        "shape": list(arr.shape),
                        "datatype": v2_dtype,
                        "data": arr.ravel().tolist(),
                    }
                ]
            }
        ).encode()
        out = self.transport(self._model_path() + "/infer", body, self.timeout_s)
        outputs = out.get("outputs") or []
        if not outputs:
            raise RuntimeError(f"model {self.model_name} returned no outputs")
        first = outputs[0]
        out_v2 = first.get("datatype", "FP32")
        out_np = NP_DTYPES.get(out_v2)
        if out_np is None:
            raise RuntimeError(
                f"model {self.model_name} output datatype {out_v2!r} unsupported"
            )
        result = np.asarray(first.get("data", []), dtype=out_np)
        shape = first.get("shape")
        return result.reshape(shape) if shape else result

    def class_names(self) -> List[str]:
        outputs = (self._meta or {}).get("outputs") or []
        return [o.get("name", f"t:{i}") for i, o in enumerate(outputs)]

    def tags(self) -> Dict[str, Any]:
        return {"server": "trtserver", "model": self.model_name}
